"""Minimal Spark Connect wire client.

Speaks the exact protocol a stock PySpark ``SparkSession.builder.remote``
client uses (same protos, same RPC names), so tests exercise true wire
compatibility even though this image has no pyspark installed.
Reference role: the client side of crates/sail-spark-connect tests.
"""

from __future__ import annotations

import uuid
from typing import Dict, Iterator, List, Optional

import grpc

from . import convert  # noqa: F401  (gen/ path setup)

from spark.connect import base_pb2 as bpb
from spark.connect import commands_pb2 as cpb
from spark.connect import relations_pb2 as rpb

_SERVICE = "spark.connect.SparkConnectService"


class SparkConnectClient:
    def __init__(self, address: str, session_id: Optional[str] = None):
        self._channel = grpc.insecure_channel(address)
        self.session_id = session_id or str(uuid.uuid4())

        self._execute_plan = self._channel.unary_stream(
            f"/{_SERVICE}/ExecutePlan",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=bpb.ExecutePlanResponse.FromString)
        self._analyze_plan = self._channel.unary_unary(
            f"/{_SERVICE}/AnalyzePlan",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=bpb.AnalyzePlanResponse.FromString)
        self._config_rpc = self._channel.unary_unary(
            f"/{_SERVICE}/Config",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=bpb.ConfigResponse.FromString)
        self._reattach = self._channel.unary_stream(
            f"/{_SERVICE}/ReattachExecute",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=bpb.ExecutePlanResponse.FromString)
        self._release_session_rpc = self._channel.unary_unary(
            f"/{_SERVICE}/ReleaseSession",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=bpb.ReleaseSessionResponse.FromString)

    # -- plan execution ----------------------------------------------------
    def execute_plan(self, plan: bpb.Plan,
                     reattachable: bool = False,
                     operation_id: Optional[str] = None
                     ) -> Iterator[bpb.ExecutePlanResponse]:
        req = bpb.ExecutePlanRequest(session_id=self.session_id, plan=plan)
        if operation_id:
            req.operation_id = operation_id
        if reattachable:
            opt = req.request_options.add()
            opt.reattach_options.reattachable = True
        return self._execute_plan(req)

    def _collect_stream(self, responses) -> "pyarrow.Table":  # noqa: F821
        import pyarrow as pa

        chunks: List[pa.Table] = []
        sql_result_rel = None
        for resp in responses:
            kind = resp.WhichOneof("response_type")
            if kind == "arrow_batch":
                chunks.append(
                    pa.ipc.open_stream(resp.arrow_batch.data).read_all())
            elif kind == "sql_command_result":
                sql_result_rel = resp.sql_command_result.relation
        if sql_result_rel is not None:
            # lazy result: execute the returned relation
            return self.execute_relation(sql_result_rel)
        if not chunks:
            return pa.table({})
        return pa.concat_tables(chunks)

    def execute_relation(self, rel: rpb.Relation) -> "pyarrow.Table":  # noqa: F821
        plan = bpb.Plan()
        plan.root.CopyFrom(rel)
        return self._collect_stream(self.execute_plan(plan))

    def sql(self, query: str) -> "pyarrow.Table":  # noqa: F821
        """spark.sql(): SqlCommand via ExecutePlan, as PySpark does."""
        plan = bpb.Plan()
        plan.command.sql_command.input.sql.query = query
        return self._collect_stream(self.execute_plan(plan))

    # -- analysis ----------------------------------------------------------
    def schema(self, rel: rpb.Relation):
        req = bpb.AnalyzePlanRequest(session_id=self.session_id)
        req.schema.plan.root.CopyFrom(rel)
        return self._analyze_plan(req).schema.schema

    def explain(self, rel: rpb.Relation) -> str:
        req = bpb.AnalyzePlanRequest(session_id=self.session_id)
        req.explain.plan.root.CopyFrom(rel)
        req.explain.explain_mode = \
            bpb.AnalyzePlanRequest.Explain.EXPLAIN_MODE_SIMPLE
        return self._analyze_plan(req).explain.explain_string

    def spark_version(self) -> str:
        req = bpb.AnalyzePlanRequest(session_id=self.session_id)
        req.spark_version.SetInParent()
        return self._analyze_plan(req).spark_version.version

    def ddl_parse(self, ddl: str):
        req = bpb.AnalyzePlanRequest(session_id=self.session_id)
        req.ddl_parse.ddl_string = ddl
        return self._analyze_plan(req).ddl_parse.parsed

    # -- config ------------------------------------------------------------
    def config_set(self, pairs: Dict[str, str]):
        req = bpb.ConfigRequest(session_id=self.session_id)
        for k, v in pairs.items():
            req.operation.set.pairs.add(key=k, value=v)
        return self._config_rpc(req)

    def config_get(self, *keys: str) -> Dict[str, str]:
        req = bpb.ConfigRequest(session_id=self.session_id)
        req.operation.get.keys.extend(keys)
        resp = self._config_rpc(req)
        return {p.key: p.value for p in resp.pairs}

    # -- lifecycle ---------------------------------------------------------
    def release_session(self):
        return self._release_session_rpc(
            bpb.ReleaseSessionRequest(session_id=self.session_id))

    def close(self):
        self._channel.close()
