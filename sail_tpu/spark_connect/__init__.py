"""Spark Connect protocol front-end.

Reference role: crates/sail-spark-connect — the gRPC service speaking the
real `spark.connect` protocol (vendored Apache Spark protos, see
proto/PROVENANCE.md) so stock Spark Connect clients can attach. The
proto→spec converters mirror crates/sail-spark-connect/src/proto/plan.rs.
"""

import os
import sys

_GEN = os.path.join(os.path.dirname(__file__), "gen")
if _GEN not in sys.path:
    sys.path.insert(0, _GEN)

from .service import SparkConnectServer  # noqa: E402,F401
