#!/bin/sh
# Regenerate the Spark Connect protobuf modules.
# The gRPC service is served via grpc generic handlers (no grpc_tools needed).
set -e
cd "$(dirname "$0")"
mkdir -p gen
protoc -I proto --python_out=gen \
  proto/spark/connect/*.proto
touch gen/__init__.py gen/spark/__init__.py gen/spark/connect/__init__.py
