"""Wire-level PySpark UDF decoding.

Reference role: crates/sail-python-udf/src/udf/pyspark_udf.rs:19-27 and
src/cereal/ — decoding ``CommonInlineUserDefinedFunction`` payloads
(cloudpickled function + return type) sent by Spark Connect clients, and
binding them into the engine's trace-first UDF machinery
(sail_tpu/functions/udf.py): traceable pandas/arrow UDFs fuse into the
surrounding XLA program; untraceable ones run via ``jax.pure_callback``.

The image has no PySpark, so payloads referencing ``pyspark.sql.types``
unpickle against a minimal shim module installed on demand; payloads made
with plain cloudpickle (our own test client, third-party clients) decode
directly.
"""

from __future__ import annotations

import sys
import types as _pytypes
from typing import Optional, Tuple

from ..functions.udf import UdfExpr, UserDefinedFunction
from ..spec import data_type as dt

# PySpark PythonEvalType values (python/pyspark/util.py in Spark) → the
# engine's UDF kinds.
EVAL_TYPES = {
    100: "batch",          # SQL_BATCHED_UDF
    101: "arrow",          # SQL_ARROW_BATCHED_UDF
    200: "pandas",         # SQL_SCALAR_PANDAS_UDF
    201: "grouped_map",    # SQL_GROUPED_MAP_PANDAS_UDF
    202: "grouped_agg",    # SQL_GROUPED_AGG_PANDAS_UDF
    203: "window_agg",     # SQL_WINDOW_AGG_PANDAS_UDF
    204: "pandas_iter",    # SQL_SCALAR_PANDAS_ITER_UDF
    205: "map_pandas",     # SQL_MAP_PANDAS_ITER_UDF
    206: "cogrouped_map",  # SQL_COGROUPED_MAP_PANDAS_UDF
    207: "map_arrow",      # SQL_MAP_ARROW_ITER_UDF
    300: "udtf",           # SQL_TABLE_UDF
    301: "arrow_udtf",
}


class WireUdfError(ValueError):
    pass


# ---------------------------------------------------------------------------
# pyspark.sql.types shim — just enough for pickled DataType instances to
# unpickle by reference without PySpark installed
# ---------------------------------------------------------------------------

_ATOMIC_SHIM_TYPES = [
    "DataType", "NullType", "StringType", "CharType", "VarcharType",
    "BinaryType", "BooleanType", "DateType", "TimestampType",
    "TimestampNTZType", "DoubleType", "FloatType", "ByteType", "ShortType",
    "IntegerType", "LongType", "DayTimeIntervalType", "YearMonthIntervalType",
]


def _install_pyspark_shim():
    if "pyspark.sql.types" in sys.modules:
        return
    import importlib.util
    try:
        if importlib.util.find_spec("pyspark.sql.types") is not None:
            return  # real PySpark available: never shadow it
    except (ImportError, ModuleNotFoundError, ValueError):
        pass
    pyspark = sys.modules.get("pyspark") or _pytypes.ModuleType("pyspark")
    sql = _pytypes.ModuleType("pyspark.sql")
    tmod = _pytypes.ModuleType("pyspark.sql.types")

    def make_atomic(name):
        def __init__(self, *args, **kwargs):
            self.args = args
            self.kwargs = kwargs
        return type(name, (object,), {"__init__": __init__,
                                      "__module__": "pyspark.sql.types"})

    for name in _ATOMIC_SHIM_TYPES:
        setattr(tmod, name, make_atomic(name))

    class DecimalType:
        def __init__(self, precision=10, scale=0):
            self.precision = precision
            self.scale = scale

    class ArrayType:
        def __init__(self, elementType=None, containsNull=True):
            self.elementType = elementType
            self.containsNull = containsNull

    class MapType:
        def __init__(self, keyType=None, valueType=None,
                     valueContainsNull=True):
            self.keyType = keyType
            self.valueType = valueType
            self.valueContainsNull = valueContainsNull

    class StructField:
        def __init__(self, name=None, dataType=None, nullable=True,
                     metadata=None):
            self.name = name
            self.dataType = dataType
            self.nullable = nullable
            self.metadata = metadata

    class StructType:
        def __init__(self, fields=None):
            self.fields = fields or []

    for cls in (DecimalType, ArrayType, MapType, StructField, StructType):
        cls.__module__ = "pyspark.sql.types"
        setattr(tmod, cls.__name__, cls)

    pyspark.sql = sql
    sql.types = tmod
    sys.modules.setdefault("pyspark", pyspark)
    sys.modules["pyspark.sql"] = sql
    sys.modules["pyspark.sql.types"] = tmod


def _shim_type_to_spec(t) -> Optional[dt.DataType]:
    """Best-effort conversion of a (shimmed or real) pyspark DataType."""
    name = type(t).__name__
    simple = {
        "NullType": dt.NullType, "StringType": dt.StringType,
        "BinaryType": dt.BinaryType, "BooleanType": dt.BooleanType,
        "DateType": dt.DateType, "TimestampType": dt.TimestampType,
        "DoubleType": dt.DoubleType, "FloatType": dt.FloatType,
        "ByteType": dt.ByteType, "ShortType": dt.ShortType,
        "IntegerType": dt.IntegerType, "LongType": dt.LongType,
    }
    if name in simple:
        return simple[name]()
    if name == "TimestampNTZType":
        return dt.TimestampType(False)
    if name == "DecimalType":
        return dt.DecimalType(getattr(t, "precision", 10),
                              getattr(t, "scale", 0))
    if name == "ArrayType":
        el = _shim_type_to_spec(getattr(t, "elementType", None))
        return dt.ArrayType(el or dt.StringType(), True)
    if name == "MapType":
        k = _shim_type_to_spec(getattr(t, "keyType", None))
        v = _shim_type_to_spec(getattr(t, "valueType", None))
        return dt.MapType(k or dt.StringType(), v or dt.StringType(), True)
    if name == "StructType":
        fields = []
        for f in getattr(t, "fields", []):
            ft = _shim_type_to_spec(getattr(f, "dataType", None))
            fields.append(dt.StructField(getattr(f, "name", "col"),
                                         ft or dt.StringType(), True))
        return dt.StructType(tuple(fields))
    return None


# ---------------------------------------------------------------------------
# command decoding
# ---------------------------------------------------------------------------

def decode_command(command: bytes) -> Tuple[object, Optional[dt.DataType]]:
    """cloudpickle payload → (callable, optional return type).

    Accepted layouts (newest PySpark first):
    - ``(func, returnType)`` — the Spark Connect PythonUDF contract
    - ``func`` alone
    - any tuple whose first callable element is the function
    """
    import cloudpickle

    _install_pyspark_shim()
    try:
        obj = cloudpickle.loads(command)
    except Exception as e:  # noqa: BLE001 — surfaced as a client error
        raise WireUdfError(f"cannot deserialize UDF payload: {e}") from e
    if callable(obj):
        return obj, None
    if isinstance(obj, tuple):
        func = next((x for x in obj if callable(x)), None)
        if func is None:
            raise WireUdfError("UDF payload tuple contains no callable")
        rt = None
        for x in obj:
            if x is func:
                continue
            if isinstance(x, dt.DataType):
                rt = x
                break
            conv = _shim_type_to_spec(x) if x is not None else None
            if conv is not None:
                rt = conv
                break
        return func, rt
    raise WireUdfError(f"unsupported UDF payload type {type(obj)!r}")


def udf_from_proto(cif) -> UserDefinedFunction:
    """CommonInlineUserDefinedFunction → engine UDF handle."""
    from .convert import ConvertError, data_type_from_proto

    which = cif.WhichOneof("function")
    if which != "python_udf":
        raise ConvertError(f"unsupported UDF flavor: {which}")
    p = cif.python_udf
    kind = EVAL_TYPES.get(p.eval_type)
    if kind is None:
        raise ConvertError(f"unsupported Python UDF eval type {p.eval_type}")
    func, pickled_rt = decode_command(p.command)
    out_t = None
    if p.HasField("output_type"):
        out_t = data_type_from_proto(p.output_type)
    if out_t is None:
        out_t = pickled_rt
    if out_t is None:
        raise ConvertError("UDF without an output type")
    engine_kind = {"batch": "batch", "arrow": "arrow", "pandas": "pandas",
                   "pandas_iter": "pandas_iter",
                   "grouped_agg": "grouped_agg"}.get(kind)
    if engine_kind is None:
        raise ConvertError(
            f"UDF kind {kind!r} is not valid as a scalar expression")
    return UserDefinedFunction(func, out_t, engine_kind,
                               cif.function_name or "udf",
                               cif.deterministic)


def relation_udf_from_proto(cif, expected_kinds) -> UserDefinedFunction:
    """CommonInlineUserDefinedFunction in RELATION position (GroupMap /
    CoGroupMap / MapPartitions) → engine UDF handle keeping the wire kind
    as eval_type (reference: pyspark_udf.rs grouped/map-iter kinds)."""
    from .convert import ConvertError, data_type_from_proto

    which = cif.WhichOneof("function")
    if which != "python_udf":
        raise ConvertError(f"unsupported UDF flavor: {which}")
    p = cif.python_udf
    kind = EVAL_TYPES.get(p.eval_type)
    if kind not in expected_kinds:
        raise ConvertError(
            f"UDF eval type {p.eval_type} ({kind}) is not valid here; "
            f"expected one of {sorted(expected_kinds)}")
    func, pickled_rt = decode_command(p.command)
    out_t = None
    if p.HasField("output_type"):
        out_t = data_type_from_proto(p.output_type)
    if out_t is None:
        out_t = pickled_rt
    if out_t is None:
        raise ConvertError("UDF without an output type")
    return UserDefinedFunction(func, out_t, kind,
                               cif.function_name or "udf",
                               cif.deterministic)


def udtf_from_proto(tf):
    """CommonInlineUserDefinedTableFunction → (handler class, StructType).

    Reference: crates/sail-python-udf/src/udf/pyspark_udtf.rs — the
    payload is a cloudpickled handler class (eval(*args) yields rows,
    optional terminate()); the declared return type is the table schema.
    """
    from .convert import ConvertError, data_type_from_proto

    if tf.WhichOneof("function") != "python_udtf":
        raise ConvertError("unsupported UDTF flavor")
    p = tf.python_udtf
    handler, pickled_rt = decode_command(p.command)
    rt = None
    if p.HasField("return_type"):
        rt = data_type_from_proto(p.return_type)
    if rt is None:
        rt = pickled_rt
    if not isinstance(rt, dt.StructType):
        raise ConvertError("UDTF must declare a struct return type")
    return handler, rt


def udf_expr_from_proto(cif):
    """Expression-position CommonInlineUserDefinedFunction → UdfExpr."""
    from .convert import expr_from_proto

    udf = udf_from_proto(cif)
    args = tuple(expr_from_proto(a) for a in cif.arguments)
    return UdfExpr(udf, args)
