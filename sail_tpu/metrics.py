"""Registry-driven metrics: declared instruments, validated recording.

Reference role: crates/sail-telemetry/src/metrics/ — a YAML registry of
every instrument (name/type/unit/attributes) from which the reference
generates typed Rust instruments (instruments.rs) at build time. The
same contract here is enforced at record time: a metric name or
attribute key outside the registry raises, so instruments cannot drift
from their declarations. Values are queryable in-process through the
``system.telemetry.metrics`` table and export as OTLP/HTTP JSON gauge
datapoints (``/v1/metrics``) when an exporter is configured.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Tuple

_REGISTRY_PATH = os.path.join(os.path.dirname(__file__),
                              "metrics_registry.yaml")


@dataclass(frozen=True)
class MetricDef:
    name: str
    description: str
    type: str                      # counter | gauge
    value_type: str
    unit: str = ""
    attributes: Tuple[str, ...] = ()


class MetricsRegistry:
    def __init__(self, defs: List[MetricDef]):
        self._defs: Dict[str, MetricDef] = {d.name: d for d in defs}
        self._values: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                           float] = {}
        self._lock = threading.Lock()
        self._dirty = False

    @classmethod
    def from_yaml(cls, path: str = _REGISTRY_PATH) -> "MetricsRegistry":
        import yaml

        with open(path, "r", encoding="utf-8") as f:
            raw = yaml.safe_load(f) or []
        defs = [MetricDef(
            name=e["name"], description=e.get("description", ""),
            type=str(e.get("type", "counter")).lower(),
            value_type=str(e.get("value_type", "u64")),
            unit=e.get("unit", ""),
            attributes=tuple(e.get("attributes") or ()))
            for e in raw]
        return cls(defs)

    def definitions(self) -> List[MetricDef]:
        return list(self._defs.values())

    def record(self, name: str, value, **attributes) -> None:
        """Counter: accumulate. Gauge: last value wins. Unknown metric
        names or attribute keys are declaration drift and raise."""
        d = self._defs.get(name)
        if d is None:
            raise KeyError(f"metric {name!r} is not in the registry")
        unknown = set(attributes) - set(d.attributes)
        if unknown:
            raise KeyError(
                f"metric {name!r} does not declare attributes "
                f"{sorted(unknown)}")
        key = (name, tuple(sorted(
            (k, str(v)) for k, v in attributes.items())))
        with self._lock:
            if d.type == "counter":
                self._values[key] = self._values.get(key, 0) + value
            else:
                self._values[key] = value
            self._dirty = True

    def snapshot(self) -> List[dict]:
        """One row per (metric, attribute-set) with its current value."""
        with self._lock:
            items = list(self._values.items())
        out = []
        for (name, attrs), value in items:
            d = self._defs[name]
            out.append({"name": name, "type": d.type, "unit": d.unit,
                        "description": d.description,
                        "attributes": json.dumps(dict(attrs)),
                        "value": float(value)})
        return sorted(out, key=lambda r: (r["name"], r["attributes"]))

    def reset(self) -> None:
        with self._lock:
            self._values.clear()
            self._dirty = False

    def take_dirty(self) -> bool:
        """True once per batch of changes — the exporter posts only when
        something was recorded since the last flush."""
        with self._lock:
            d, self._dirty = self._dirty, False
            return d

    # -- OTLP/HTTP JSON export (/v1/metrics) ----------------------------
    def otlp_payload(self, service_name: str = "sail-tpu") -> dict:
        now = str(time.time_ns())
        metrics = []
        by_name: Dict[str, List] = {}
        with self._lock:
            for (name, attrs), value in self._values.items():
                by_name.setdefault(name, []).append((attrs, value))
        for name, points in sorted(by_name.items()):
            d = self._defs[name]
            dps = [{
                "timeUnixNano": now,
                "asDouble" if d.value_type.startswith("f")
                else "asInt": value if d.value_type.startswith("f")
                else str(int(value)),
                "attributes": [
                    {"key": k, "value": {"stringValue": v}}
                    for k, v in attrs],
            } for attrs, value in points]
            body = {"name": name, "description": d.description,
                    "unit": d.unit}
            if d.type == "counter":
                body["sum"] = {"dataPoints": dps, "isMonotonic": True,
                               "aggregationTemporality": 2}  # cumulative
            else:
                body["gauge"] = {"dataPoints": dps}
            metrics.append(body)
        return {"resourceMetrics": [{
            "resource": {"attributes": [
                {"key": "service.name",
                 "value": {"stringValue": service_name}}]},
            "scopeMetrics": [{"scope": {"name": "sail_tpu"},
                              "metrics": metrics}],
        }]}


REGISTRY = MetricsRegistry.from_yaml()

_ENABLED: "bool | None" = None


def _enabled() -> bool:
    """``telemetry.metrics_enabled`` gate, read once per process —
    record() sits on hot paths, so the config layer cannot ride every
    call. Tests flip it via :func:`reload_enabled`."""
    global _ENABLED
    if _ENABLED is None:
        try:
            from .config import truthy
            _ENABLED = truthy("telemetry.metrics_enabled")
        except Exception:  # noqa: BLE001 — metrics must not break imports
            _ENABLED = True
    return _ENABLED


def reload_enabled() -> None:
    """Re-read ``telemetry.metrics_enabled`` on the next record()."""
    global _ENABLED
    _ENABLED = None


def record(name: str, value, **attributes) -> None:
    if not _enabled():
        return
    REGISTRY.record(name, value, **attributes)
