"""Registry-driven metrics: declared instruments, validated recording.

Reference role: crates/sail-telemetry/src/metrics/ — a YAML registry of
every instrument (name/type/unit/attributes) from which the reference
generates typed Rust instruments (instruments.rs) at build time. The
same contract here is enforced at record time: a metric name or
attribute key outside the registry raises, so instruments cannot drift
from their declarations. Values are queryable in-process through the
``system.telemetry.metrics`` table, export as OTLP/HTTP JSON datapoints
(``/v1/metrics``) when an exporter is configured, and serve in
Prometheus text exposition from the pull-based ops endpoint
(``sail_tpu/obs_server.py`` ``/metrics``).

Instrument types:

- ``counter``   monotonic accumulate
- ``gauge``     last value wins
- ``histogram`` bounded exponential buckets (``HistogramState``):
  mergeable across processes (bucket counts + sum + count add), with
  p50/p95/p99 estimated by linear interpolation inside the bucket the
  quantile lands in — so live percentiles never require retaining raw
  samples.

Fleet aggregation: workers ship counter/histogram DELTAS piggybacked on
the control-plane heartbeat (``take_heartbeat_delta``); the driver
merges them into :data:`FLEET` keyed by worker id. A delta from the
driver's own process is skipped at merge time (the loopback thread-
worker topology shares this module's REGISTRY, so its increments are
already in the local view) — fleet totals never double-count.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

_REGISTRY_PATH = os.path.join(os.path.dirname(__file__),
                              "metrics_registry.yaml")

#: default exponential bucket ladder for latency histograms (seconds):
#: 1ms doubling to ~524s, +Inf overflow — 20 finite bounds
DEFAULT_BUCKETS = {"base": 0.001, "growth": 2.0, "count": 20}

#: quantiles the SLO surfaces report
SLO_QUANTILES = (0.50, 0.95, 0.99)


def exponential_bounds(base: float, growth: float,
                       count: int) -> Tuple[float, ...]:
    """Finite upper bounds ``base * growth**i`` for i in [0, count)."""
    base = float(base)
    growth = float(growth)
    count = max(1, int(count))
    return tuple(base * growth ** i for i in range(count))


@dataclass(frozen=True)
class MetricDef:
    name: str
    description: str
    type: str                      # counter | gauge | histogram
    value_type: str
    unit: str = ""
    attributes: Tuple[str, ...] = ()
    # histogram only: finite bucket upper bounds (ascending); the
    # overflow (+Inf) bucket is implicit
    bounds: Tuple[float, ...] = ()


class HistogramState:
    """One (metric, attribute-set) histogram: bucket counts over the
    declared bounds plus an implicit +Inf overflow bucket, with running
    sum/count. Mergeable (bucket-wise add) and subtractable (windowed
    percentiles between two snapshots)."""

    __slots__ = ("bounds", "counts", "sum", "count")

    def __init__(self, bounds: Tuple[float, ...],
                 counts: Optional[List[int]] = None,
                 total: float = 0.0, count: int = 0):
        self.bounds = bounds
        self.counts = list(counts) if counts is not None \
            else [0] * (len(bounds) + 1)
        self.sum = float(total)
        self.count = int(count)

    def observe(self, value: float) -> None:
        value = float(value)
        lo, hi = 0, len(self.bounds)
        while lo < hi:                    # first bound >= value
            mid = (lo + hi) // 2
            if value <= self.bounds[mid]:
                hi = mid
            else:
                lo = mid + 1
        self.counts[lo] += 1
        self.sum += value
        self.count += 1

    def copy(self) -> "HistogramState":
        return HistogramState(self.bounds, self.counts, self.sum,
                              self.count)

    def merge(self, other: "HistogramState") -> None:
        for i, c in enumerate(other.counts[:len(self.counts)]):
            self.counts[i] += int(c)
        self.sum += other.sum
        self.count += other.count

    def subtract(self, other: "HistogramState") -> "HistogramState":
        """Window between two snapshots of the SAME instrument
        (self - other); negative residue clamps to zero."""
        counts = [max(0, a - b) for a, b in zip(self.counts,
                                                other.counts)]
        return HistogramState(self.bounds, counts,
                              max(0.0, self.sum - other.sum),
                              max(0, self.count - other.count))

    def quantile(self, q: float) -> Optional[float]:
        """Estimate the q-quantile by linear interpolation inside the
        bucket the rank lands in; the overflow bucket clamps to the
        last finite bound (the estimate's resolution IS the bucket)."""
        if self.count <= 0:
            return None
        q = min(1.0, max(0.0, float(q)))
        rank = q * self.count
        seen = 0.0
        for i, c in enumerate(self.counts):
            if c <= 0:
                continue
            if seen + c >= rank:
                if i >= len(self.bounds):          # overflow bucket
                    return self.bounds[-1] if self.bounds else None
                lower = self.bounds[i - 1] if i > 0 else 0.0
                upper = self.bounds[i]
                frac = (rank - seen) / c
                return lower + (upper - lower) * min(1.0, max(0.0, frac))
            seen += c
        return self.bounds[-1] if self.bounds else None

    def percentiles(self) -> Dict[str, Optional[float]]:
        return {f"p{int(q * 100)}": self.quantile(q)
                for q in SLO_QUANTILES}

    def fraction_above(self, threshold: float) -> float:
        """Fraction of observations above ``threshold`` — the SLO
        burn-rate numerator — interpolating linearly inside the bucket
        the threshold lands in. Overflow-bucket observations all count
        as above (they exceed the last finite bound; the estimate's
        resolution IS the bucket, as with :meth:`quantile`)."""
        if self.count <= 0:
            return 0.0
        threshold = float(threshold)
        above = 0.0
        for i, c in enumerate(self.counts):
            if c <= 0:
                continue
            if i >= len(self.bounds):
                above += c
                continue
            upper = self.bounds[i]
            lower = self.bounds[i - 1] if i > 0 else 0.0
            if threshold <= lower:
                above += c
            elif threshold < upper:
                above += c * (upper - threshold) / (upper - lower)
        return above / self.count

    def to_wire(self) -> dict:
        return {"counts": list(self.counts), "sum": self.sum,
                "count": self.count}

    @classmethod
    def from_wire(cls, bounds: Tuple[float, ...],
                  d: dict) -> "HistogramState":
        counts = [int(c) for c in (d.get("counts") or ())]
        counts = (counts + [0] * (len(bounds) + 1))[:len(bounds) + 1]
        return cls(bounds, counts, float(d.get("sum", 0.0)),
                   int(d.get("count", 0)))


#: key of one recorded series: (metric name, sorted attribute pairs)
SeriesKey = Tuple[str, Tuple[Tuple[str, str], ...]]

#: process-unique origin token for heartbeat deltas — pid equality is
#: not collision-free across hosts, this is
PROCESS_TOKEN = uuid.uuid4().hex


def _series_key(name: str, attributes: Dict[str, object]) -> SeriesKey:
    return (name, tuple(sorted(
        (k, str(v)) for k, v in attributes.items())))


class MetricsRegistry:
    def __init__(self, defs: List[MetricDef]):
        self._defs: Dict[str, MetricDef] = {d.name: d for d in defs}
        self._values: Dict[SeriesKey, float] = {}
        self._hists: Dict[SeriesKey, HistogramState] = {}
        self._lock = threading.Lock()
        self._dirty = False
        # heartbeat delta cursor: last-shipped counter values /
        # histogram snapshots / gauge values (one per-process shipper)
        self._delta_counters: Dict[SeriesKey, float] = {}
        self._delta_hists: Dict[SeriesKey, HistogramState] = {}
        self._delta_gauges: Dict[SeriesKey, float] = {}

    @classmethod
    def from_yaml(cls, path: str = _REGISTRY_PATH) -> "MetricsRegistry":
        import yaml

        with open(path, "r", encoding="utf-8") as f:
            raw = yaml.safe_load(f) or []
        defs = []
        for e in raw:
            mtype = str(e.get("type", "counter")).lower()
            bounds: Tuple[float, ...] = ()
            if mtype == "histogram":
                spec = dict(DEFAULT_BUCKETS)
                spec.update(e.get("buckets") or {})
                bounds = exponential_bounds(
                    spec["base"], spec["growth"], spec["count"])
            defs.append(MetricDef(
                name=e["name"], description=e.get("description", ""),
                type=mtype,
                value_type=str(e.get("value_type", "u64")),
                unit=e.get("unit", ""),
                attributes=tuple(e.get("attributes") or ()),
                bounds=bounds))
        return cls(defs)

    def definitions(self) -> List[MetricDef]:
        return list(self._defs.values())

    def definition(self, name: str) -> Optional[MetricDef]:
        return self._defs.get(name)

    def record(self, name: str, value, **attributes) -> None:
        """Counter: accumulate. Gauge: last value wins. Histogram: one
        observation. Unknown metric names or attribute keys are
        declaration drift and raise."""
        d = self._defs.get(name)
        if d is None:
            raise KeyError(f"metric {name!r} is not in the registry")
        unknown = set(attributes) - set(d.attributes)
        if unknown:
            raise KeyError(
                f"metric {name!r} does not declare attributes "
                f"{sorted(unknown)}")
        key = _series_key(name, attributes)
        with self._lock:
            if d.type == "histogram":
                h = self._hists.get(key)
                if h is None:
                    h = self._hists[key] = HistogramState(d.bounds)
                h.observe(value)
            elif d.type == "counter":
                self._values[key] = self._values.get(key, 0) + value
            else:
                self._values[key] = value
            self._dirty = True

    def histogram_state(self, name: str,
                        **attributes) -> Optional[HistogramState]:
        """Snapshot one histogram series (copy), None if never recorded."""
        key = _series_key(name, attributes)
        with self._lock:
            h = self._hists.get(key)
            return h.copy() if h is not None else None

    def histogram_sum(self, name: str) -> float:
        """Sum of one histogram metric's observations across EVERY
        attribute series (0.0 if never recorded) — a cheap monotone
        total for rate signals read against a delta cursor (the
        autoscaler's credit-stall input)."""
        with self._lock:
            return float(sum(h.sum for (n, _a), h in self._hists.items()
                             if n == name))

    def snapshot(self) -> List[dict]:
        """One row per (metric, attribute-set) with its current value.
        Histogram rows report ``value`` = sum (backward-compatible with
        the counter it replaced) plus ``count`` and estimated
        p50/p95/p99."""
        with self._lock:
            items = list(self._values.items())
            hists = [(k, h.copy()) for k, h in self._hists.items()]
        out = []
        for (name, attrs), value in items:
            d = self._defs[name]
            out.append({"name": name, "type": d.type, "unit": d.unit,
                        "description": d.description,
                        "attributes": json.dumps(dict(attrs)),
                        "value": float(value)})
        for (name, attrs), h in hists:
            d = self._defs[name]
            row = {"name": name, "type": d.type, "unit": d.unit,
                   "description": d.description,
                   "attributes": json.dumps(dict(attrs)),
                   "value": float(h.sum), "count": h.count}
            row.update(h.percentiles())
            out.append(row)
        return sorted(out, key=lambda r: (r["name"], r["attributes"]))

    def reset(self) -> None:
        with self._lock:
            self._values.clear()
            self._hists.clear()
            self._delta_counters.clear()
            self._delta_hists.clear()
            self._delta_gauges.clear()
            self._dirty = False

    def take_dirty(self) -> bool:
        """True once per batch of changes — the exporter posts only when
        something was recorded since the last flush."""
        with self._lock:
            d, self._dirty = self._dirty, False
            return d

    # -- heartbeat delta shipping (fleet aggregation) -------------------
    def take_heartbeat_delta(self) -> Optional[dict]:
        """Increments since the last call, as a JSON-able wire record:
        counter deltas, histogram bucket-increment deltas, and changed
        gauge values. One cursor per process — the worker heartbeat
        loop is the single shipper. Returns None when nothing changed
        (the heartbeat stays light)."""
        with self._lock:
            counters = []
            gauges = []
            for key, value in self._values.items():
                d = self._defs[key[0]]
                if d.type == "counter":
                    delta = value - self._delta_counters.get(key, 0.0)
                    if delta:
                        counters.append(
                            [key[0], dict(key[1]), float(delta)])
                        self._delta_counters[key] = value
                else:  # gauge: ship only when the value moved
                    if self._delta_gauges.get(key) != value:
                        gauges.append([key[0], dict(key[1]),
                                       float(value)])
                        self._delta_gauges[key] = value
            hists = []
            for key, h in self._hists.items():
                prev = self._delta_hists.get(key)
                delta = h.subtract(prev) if prev is not None else h
                if delta.count:
                    hists.append([key[0], dict(key[1]),
                                  delta.to_wire()])
                    self._delta_hists[key] = h.copy()
        if not counters and not hists and not gauges:
            return None
        return {"pid": os.getpid(), "src": PROCESS_TOKEN,
                "counters": counters, "gauges": gauges,
                "histograms": hists}

    # -- OTLP/HTTP JSON export (/v1/metrics) ----------------------------
    def otlp_payload(self, service_name: str = "sail-tpu") -> dict:
        now = str(time.time_ns())
        metrics = []
        by_name: Dict[str, List] = {}
        hist_by_name: Dict[str, List] = {}
        with self._lock:
            for (name, attrs), value in self._values.items():
                by_name.setdefault(name, []).append((attrs, value))
            for (name, attrs), h in self._hists.items():
                hist_by_name.setdefault(name, []).append(
                    (attrs, h.copy()))
        for name, points in sorted(by_name.items()):
            d = self._defs[name]
            dps = [{
                "timeUnixNano": now,
                "asDouble" if d.value_type.startswith("f")
                else "asInt": value if d.value_type.startswith("f")
                else str(int(value)),
                "attributes": [
                    {"key": k, "value": {"stringValue": v}}
                    for k, v in attrs],
            } for attrs, value in points]
            body = {"name": name, "description": d.description,
                    "unit": d.unit}
            if d.type == "counter":
                body["sum"] = {"dataPoints": dps, "isMonotonic": True,
                               "aggregationTemporality": 2}  # cumulative
            else:
                body["gauge"] = {"dataPoints": dps}
            metrics.append(body)
        for name, points in sorted(hist_by_name.items()):
            d = self._defs[name]
            # real OTLP histogram datapoints: bucket counts + explicit
            # bounds + sum + count, cumulative temporality — not the
            # flattened gauges the pre-histogram exporter would have sent
            dps = [{
                "timeUnixNano": now,
                "count": str(h.count),
                "sum": h.sum,
                "bucketCounts": [str(c) for c in h.counts],
                "explicitBounds": list(h.bounds),
                "attributes": [
                    {"key": k, "value": {"stringValue": v}}
                    for k, v in attrs],
            } for attrs, h in points]
            metrics.append({
                "name": name, "description": d.description,
                "unit": d.unit,
                "histogram": {"dataPoints": dps,
                              "aggregationTemporality": 2}})
        return {"resourceMetrics": [{
            "resource": {"attributes": [
                {"key": "service.name",
                 "value": {"stringValue": service_name}}]},
            "scopeMetrics": [{"scope": {"name": "sail_tpu"},
                              "metrics": metrics}],
        }]}


class _TimerHandle:
    __slots__ = ("elapsed_s",)

    def __init__(self):
        self.elapsed_s = 0.0


def merge_heartbeat_deltas(base: Optional[dict],
                           inc: Optional[dict]) -> Optional[dict]:
    """Combine two wire deltas (an UNSENT one from a failed heartbeat
    and the next cycle's increments) so a transient RPC failure defers
    shipment instead of losing it: counters and histogram buckets add,
    gauges last-value-wins."""
    if base is None:
        return inc
    if inc is None:
        return base
    out = {"pid": inc.get("pid", base.get("pid")),
           "src": inc.get("src", base.get("src"))}
    counters: Dict[Tuple[str, str], float] = {}
    for entry in list(base.get("counters") or ()) + \
            list(inc.get("counters") or ()):
        name, attrs, value = entry
        key = (name, json.dumps(attrs or {}, sort_keys=True))
        counters[key] = counters.get(key, 0.0) + float(value)
    out["counters"] = [[name, json.loads(attrs), v]
                       for (name, attrs), v in counters.items()]
    gauges: Dict[Tuple[str, str], float] = {}
    for entry in list(base.get("gauges") or ()) + \
            list(inc.get("gauges") or ()):
        name, attrs, value = entry
        gauges[(name, json.dumps(attrs or {},
                                 sort_keys=True))] = float(value)
    out["gauges"] = [[name, json.loads(attrs), v]
                     for (name, attrs), v in gauges.items()]
    hists: Dict[Tuple[str, str], dict] = {}
    for entry in list(base.get("histograms") or ()) + \
            list(inc.get("histograms") or ()):
        name, attrs, wire = entry
        key = (name, json.dumps(attrs or {}, sort_keys=True))
        cur = hists.get(key)
        if cur is None:
            hists[key] = {"counts": list(wire.get("counts") or ()),
                          "sum": float(wire.get("sum", 0.0)),
                          "count": int(wire.get("count", 0))}
        else:
            counts = list(wire.get("counts") or ())
            merged = [a + b for a, b in zip(
                cur["counts"] + [0] * len(counts),
                counts + [0] * len(cur["counts"]))]
            cur["counts"] = merged[:max(len(counts),
                                        len(cur["counts"]))]
            cur["sum"] += float(wire.get("sum", 0.0))
            cur["count"] += int(wire.get("count", 0))
    out["histograms"] = [[name, json.loads(attrs), wire]
                         for (name, attrs), wire in hists.items()]
    return out


# ---------------------------------------------------------------------------
# fleet view: per-worker merged deltas on the cluster driver
# ---------------------------------------------------------------------------

class FleetMetrics:
    """Driver-side merge of worker metric deltas, keyed by worker id.

    Counters and histograms accumulate (deltas add); gauges keep the
    worker's last shipped value. The LOCAL process is not stored here —
    readers union these entries with the live :data:`REGISTRY` under
    the reserved worker id ``"driver"`` — and a delta originating from
    the driver's own pid is skipped by the caller, so loopback thread
    workers (which share the process registry) never double-count."""

    #: per-worker entries retained; beyond it the STALEST worker's
    #: series drop (worker churn in an elastic pool must not grow the
    #: driver's fleet view — and every /metrics scrape — forever)
    MAX_WORKERS = 128

    def __init__(self, defs: Optional[Dict[str, MetricDef]] = None):
        self._lock = threading.Lock()
        self._defs = defs
        # worker -> series key -> float | HistogramState
        self._workers: Dict[str, Dict[SeriesKey, object]] = {}
        self._updated: Dict[str, float] = {}

    def _def(self, name: str) -> Optional[MetricDef]:
        defs = self._defs if self._defs is not None else REGISTRY._defs
        return defs.get(name)

    def merge(self, worker_id: str, delta: dict) -> None:
        """Merge one shipped delta. Unknown metric names are dropped —
        a version-skewed worker must not poison the fleet view."""
        if not isinstance(delta, dict):
            return
        with self._lock:
            store = self._workers.setdefault(worker_id, {})
            self._updated[worker_id] = time.time()
            while len(self._workers) > self.MAX_WORKERS:
                stalest = min(self._updated, key=self._updated.get)
                self._workers.pop(stalest, None)
                self._updated.pop(stalest, None)
            for entry in delta.get("counters") or ():
                name, attrs, value = entry
                if self._def(name) is None:
                    continue
                key = _series_key(name, attrs or {})
                store[key] = float(store.get(key, 0.0)) + float(value)
            for entry in delta.get("gauges") or ():
                name, attrs, value = entry
                if self._def(name) is None:
                    continue
                store[_series_key(name, attrs or {})] = float(value)
            for entry in delta.get("histograms") or ():
                name, attrs, wire = entry
                d = self._def(name)
                if d is None or d.type != "histogram":
                    continue
                key = _series_key(name, attrs or {})
                inc = HistogramState.from_wire(d.bounds, wire or {})
                cur = store.get(key)
                if isinstance(cur, HistogramState):
                    cur.merge(inc)
                else:
                    store[key] = inc

    def drop_worker_gauges(self, worker_id: str) -> None:
        """A worker left the pool (eviction/crash): its GAUGE series
        are stale point-in-time values and must stop being served;
        counters and histograms are monotonic history and stay (a
        readmitted worker resumes merging into them)."""
        with self._lock:
            store = self._workers.get(worker_id)
            if not store:
                return
            for key in [k for k, v in store.items()
                        if not isinstance(v, HistogramState)
                        and (self._def(k[0]) is None
                             or self._def(k[0]).type == "gauge")]:
                store.pop(key, None)
            if not store:
                self._workers.pop(worker_id, None)
                self._updated.pop(worker_id, None)

    def clear(self) -> None:
        with self._lock:
            self._workers.clear()
            self._updated.clear()

    def worker_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._workers)

    def snapshot(self) -> List[dict]:
        """Fleet rows: one per (worker, metric, attribute-set) — the
        local process appears as worker ``"driver"`` with the live
        registry values, remote workers with their merged deltas."""
        rows = []
        for r in REGISTRY.snapshot():
            row = dict(r)
            row["worker"] = "driver"
            rows.append(row)
        with self._lock:
            # histogram states must COPY under the lock: merge()
            # mutates them in place on the heartbeat path
            workers = {
                wid: {k: (v.copy() if isinstance(v, HistogramState)
                          else v) for k, v in store.items()}
                for wid, store in self._workers.items()}
        for wid in sorted(workers):
            for (name, attrs), value in sorted(workers[wid].items()):
                d = self._def(name)
                if d is None:
                    continue
                row = {"name": name, "type": d.type, "unit": d.unit,
                       "description": d.description,
                       "attributes": json.dumps(dict(attrs)),
                       "worker": wid}
                if isinstance(value, HistogramState):
                    row["value"] = float(value.sum)
                    row["count"] = value.count
                    row.update(value.percentiles())
                else:
                    row["value"] = float(value)
                rows.append(row)
        return rows

    def series(self) -> List[Tuple[str, Dict[str, str], str, object]]:
        """Raw fleet series for exposition: (name, attributes, worker,
        value-or-HistogramState), local process first as ``driver``."""
        out: List[Tuple[str, Dict[str, str], str, object]] = []
        with REGISTRY._lock:
            local = list(REGISTRY._values.items())
            local_h = [(k, h.copy()) for k, h in
                       REGISTRY._hists.items()]
        for (name, attrs), value in local:
            out.append((name, dict(attrs), "driver", float(value)))
        for (name, attrs), h in local_h:
            out.append((name, dict(attrs), "driver", h))
        with self._lock:
            workers = {wid: dict(store)
                       for wid, store in self._workers.items()}
        for wid in sorted(workers):
            for (name, attrs), value in sorted(
                    workers[wid].items(),
                    key=lambda kv: (kv[0][0], kv[0][1])):
                if isinstance(value, HistogramState):
                    out.append((name, dict(attrs), wid, value.copy()))
                else:
                    out.append((name, dict(attrs), wid, float(value)))
        return out

    def histogram_states(self, name: str) -> List[Tuple[
            str, Dict[str, str], HistogramState]]:
        """Every (worker, attributes, state) of one histogram across
        the fleet, local process included."""
        d = self._def(name)
        if d is None or d.type != "histogram":
            return []
        out = []
        with REGISTRY._lock:
            local = [(k, h.copy()) for k, h in REGISTRY._hists.items()
                     if k[0] == name]
        for (_, attrs), h in local:
            out.append(("driver", dict(attrs), h))
        with self._lock:
            for wid, store in self._workers.items():
                for (n, attrs), value in store.items():
                    if n == name and isinstance(value, HistogramState):
                        out.append((wid, dict(attrs), value.copy()))
        return out


# ---------------------------------------------------------------------------
# Prometheus exposition naming
# ---------------------------------------------------------------------------

_PROM_LEGAL_FIRST = set("abcdefghijklmnopqrstuvwxyz"
                        "ABCDEFGHIJKLMNOPQRSTUVWXYZ_:")
_PROM_LEGAL = _PROM_LEGAL_FIRST | set("0123456789")


def prometheus_name(name: str, mtype: str = "") -> str:
    """Registry name → Prometheus metric name: ``sail_`` prefix, dots
    become underscores, counters get the ``_total`` convention suffix.
    The ``metrics`` lint validates every declared instrument through
    this same translation."""
    base = "sail_" + name.replace(".", "_")
    if mtype == "counter" and not base.endswith("_total"):
        base += "_total"
    return base


def is_legal_prometheus_name(name: str) -> bool:
    return bool(name) and name[0] in _PROM_LEGAL_FIRST and \
        all(ch in _PROM_LEGAL for ch in name)


REGISTRY = MetricsRegistry.from_yaml()

#: cluster driver's fleet view (remote worker deltas; local process
#: joins at read time as worker "driver")
FLEET = FleetMetrics()

_ENABLED: "bool | None" = None


def _enabled() -> bool:
    """``telemetry.metrics_enabled`` gate, read once per process —
    record() sits on hot paths, so the config layer cannot ride every
    call. Tests flip it via :func:`reload_enabled`."""
    global _ENABLED
    if _ENABLED is None:
        try:
            from .config import truthy
            _ENABLED = truthy("telemetry.metrics_enabled")
        except Exception:  # noqa: BLE001 — metrics must not break imports
            _ENABLED = True
    return _ENABLED


def reload_enabled() -> None:
    """Re-read ``telemetry.metrics_enabled`` on the next record()."""
    global _ENABLED
    _ENABLED = None


def record(name: str, value, **attributes) -> None:
    if not _enabled():
        return
    REGISTRY.record(name, value, **attributes)


@contextmanager
def timer(name: Optional[str] = None, **attributes):
    """Time a block; record the elapsed seconds into ``name`` (a
    latency instrument, histogram by declaration). The canonical
    replacement for hand-rolled ``t0 = time.monotonic(); ...;
    record(name, delta)`` call sites. ALWAYS measures — the handle's
    ``elapsed_s`` feeds profiles even when metrics are disabled or
    ``name`` is None (conditional-recording sites); only the registry
    write is gated."""
    handle = _TimerHandle()
    t0 = time.perf_counter()
    try:
        yield handle
    except BaseException:
        # an aborted block still measures (the handle feeds error-path
        # accounting) but records NOTHING — a failed commit/compile
        # must not pollute the success-latency distribution
        handle.elapsed_s = time.perf_counter() - t0
        raise
    else:
        handle.elapsed_s = time.perf_counter() - t0
        if name and _enabled():
            try:
                REGISTRY.record(name, handle.elapsed_s, **attributes)
            except Exception:  # noqa: BLE001 — timing must never raise
                pass
