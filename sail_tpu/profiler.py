"""Per-query profiler + in-process flight recorder.

Reference role: the compile/data-movement accounting that Flare and
Theseus show is the prerequisite for optimizing a native/accelerator
query engine (PAPERS.md), grafted onto sail's telemetry surface. One
``QueryProfile`` is threaded from the session entry point through the
planner and both executors, recording

- phase wall times in execution order: parse, resolve, optimize,
  compile, execute, fetch. Parse/resolve/optimize/execute/fetch are
  disjoint; compile is accounted *inside* execute — it is the JIT wall
  time of operator cache misses — so it does not sum with the others;
- JIT accounting from the compiled-operator cache: hits, misses, and
  per-key compile wall time (also exported through the registry as
  ``execution.compile.{cache_hit_count,cache_miss_count,compile_time}``);
- device-transfer and spill bytes;
- per-operator metrics (under EXPLAIN ANALYZE) and, in cluster mode,
  per-task operator metrics merged per {stage, partition}.

Completed profiles land in a bounded flight-recorder ring (newest N),
plus a slow-query log that retains queries above
``spark.sail.telemetry.slowQueryMs`` even after the ring evicts them.
Both surfaces are SQL-queryable via ``system.telemetry.query_profiles``
and ``system.telemetry.active_queries`` and ride the OTLP exporter as a
``query`` span with the phase breakdown as attributes.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import uuid
from collections import OrderedDict, deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .metrics import record as _record_metric

logger = logging.getLogger("sail_tpu.profiler")

#: canonical phase order for rendering (a profile only reports phases it
#: actually entered, in first-entry order)
PHASES = ("parse", "resolve", "optimize", "compile", "execute", "fetch")

_STATEMENT_MAX = 4096


@dataclass
class QueryProfile:
    query_id: str
    statement: str = ""
    session: str = ""
    # admission-control tenant the query billed to (multi-tenant
    # serving; "" for unattributed internal queries)
    tenant: str = ""
    start_time: float = 0.0
    end_time: float = 0.0
    status: str = "running"          # running | succeeded | failed
    error: str = ""
    # phase → accumulated wall ms, insertion-ordered by first entry
    phases: Dict[str, float] = field(default_factory=dict)
    compile_cache_hits: int = 0
    compile_cache_misses: int = 0
    compile_ms: float = 0.0
    # persistent (cross-process AOT) compiled-program cache: consults
    # that loaded a stored executable vs fell through to a JIT compile,
    # and the deserialization wall time the hits paid
    persistent_hits: int = 0
    persistent_misses: int = 0
    persistent_load_ms: float = 0.0
    # programs that actually traced + XLA-compiled this query (every
    # note_compile_time call) — NOT derivable from cache_misses minus
    # persistent_hits: misses count per key, persistent hits per
    # argument signature
    compiled_programs: int = 0
    # per-key compile events: [{"key": str, "ms": float, "source":
    # "trace" | "persistent"}] — one per stage program bound
    compile_events: List[dict] = field(default_factory=list)
    # retrace forensics (exec/retrace.py): every compile this query paid
    # attributed by typed cause. ``retrace_count``/``retrace_ms``
    # EXCLUDE first-ever (the benign cold compile) — they count
    # programs the process HAD and lost, or shape drift; the causes
    # dict keeps the full breakdown including first-ever
    retrace_count: int = 0
    retrace_ms: float = 0.0
    retrace_causes: Dict[str, int] = field(default_factory=dict)
    # plan fingerprint the baseline store and anomaly classifier key on
    # (session.py: sha of the structural plan key; "" when the plan is
    # unfingerprintable)
    plan_fingerprint: str = ""
    # anomaly classification (analysis/anomaly.py, set at finalize):
    # verdict ∈ events.VERDICT_CATEGORIES when the query was a
    # tail-latency outlier against its fingerprint baseline, else ""
    anomaly_verdict: str = ""
    anomaly_excess_ms: float = 0.0
    # admission-control queue wait this query paid before running
    admission_wait_ms: float = 0.0
    # per-stage backend routing decisions (exec/router.py):
    # [{"stage": int, "kind": str, "backend": str, "reason": str}]
    backend_routes: List[dict] = field(default_factory=list)
    transfer_bytes: int = 0
    spill_bytes: int = 0
    # runtime join filters: filters built / pushed into scans, probe+scan
    # rows pruned, and filter-build wall time for this query
    rtf_built: int = 0
    rtf_pushed: int = 0
    rtf_rows_pruned: int = 0
    rtf_build_ms: float = 0.0
    # cluster fault tolerance: task retries (failure/eviction/dispatch),
    # speculative duplicates launched and how many of those won
    ft_retries: int = 0
    ft_speculative_launched: int = 0
    ft_speculative_won: int = 0
    # shuffle data plane: raw vs compressed wire bytes published by this
    # query's distributed tasks, consumer-side fetch wait + IPC decode
    # time, and tasks the memory governor deferred for capacity
    shuffle_wire_bytes: int = 0
    shuffle_wire_compressed: int = 0
    shuffle_fetch_wait_ms: float = 0.0
    shuffle_decode_ms: float = 0.0
    governor_deferred: int = 0
    # adaptive query execution: stage-boundary replanning decisions the
    # driver took from observed shuffle statistics, plus the per-shuffle
    # skew ratios and per-channel size reports they were based on (the
    # skew surface records even when adaptive execution is off)
    adaptive_coalesced: int = 0
    adaptive_split: int = 0
    adaptive_broadcast: int = 0
    adaptive_reordered: int = 0
    adaptive_events: List[dict] = field(default_factory=list)
    skew: List[dict] = field(default_factory=list)
    shuffle_channels: List[dict] = field(default_factory=list)
    # plan-invariant validator walks that ran for this query (optimizer
    # pass boundaries + job-graph stage checks)
    validated_passes: int = 0
    # whole-stage fusion: pipeline stages the splitter produced, Filter/
    # Project operators inlined into a consumer's program, and pipelines
    # that declined fusion at execution time (host-only expressions)
    fusion_stages: int = 0
    fusion_fused_ops: int = 0
    fusion_fallbacks: int = 0
    # streaming: the epoch this profile's trigger executed, the wall
    # time of its commit protocol (stage → checkpoint → finalize →
    # marker), the keyed-state rows retained after it, and whether the
    # trigger was a marker-skipped replay (-1 epoch = not a streaming
    # trigger; the block is omitted from to_dict/render then)
    streaming_epoch: int = -1
    streaming_commit_ms: float = 0.0
    streaming_state_rows: int = 0
    streaming_replayed: bool = False
    # result/fragment cache (exec/result_cache.py): how this query's
    # data was served — "" = cache not consulted, else hit | miss |
    # shared-scan | view — plus the cache fragments substituted into
    # the plan, the bytes they served, and concurrent-scan sharing
    # attach counts (followers riding another query's decode pass)
    cache_status: str = ""
    cache_fragments: List[str] = field(default_factory=list)
    cache_bytes_served: int = 0
    scan_share_attached: int = 0
    scan_share_saved: int = 0
    rows_out: int = 0
    slow: bool = False
    # critical-path attribution derived from the query's event stream
    # (analysis/timeline.py): {"total_ms", "categories", "chain",
    # "top"} — set by the cluster runner after the job completes, None
    # for queries without a distributed task timeline
    critical_path: Optional[dict] = None
    # operator metric trees (dicts, telemetry.OperatorMetrics.to_dict)
    operators: List[dict] = field(default_factory=list)
    # cluster mode: per-task operator metrics, one entry per
    # {stage, partition} of the last distributed job
    tasks: List[dict] = field(default_factory=list)
    trace_id: Optional[str] = None
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False)
    # stack of phases currently OPEN on this profile (nested executors
    # re-enter "execute"; re-entry must not double-count)
    _open: List[str] = field(default_factory=list, repr=False)

    # -- recording -----------------------------------------------------
    def add_phase(self, name: str, ms: float) -> None:
        with self._lock:
            self.phases[name] = self.phases.get(name, 0.0) + ms

    @contextmanager
    def phase(self, name: str):
        with self._lock:
            reentered = name in self._open
            if not reentered:
                self._open.append(name)
        if reentered:
            # a nested executor re-opened the same phase (e.g. a scalar
            # subquery executing inside "execute"): the outer timer
            # already covers this wall time
            yield
            return
        from .metrics import timer as _metric_timer
        tm = None
        try:
            with _metric_timer() as tm:  # measure-only handle
                yield
        finally:
            with self._lock:
                if name in self._open:
                    self._open.remove(name)
            self.add_phase(name, tm.elapsed_s * 1000.0 if tm else 0.0)

    def is_open(self, name: str) -> bool:
        with self._lock:
            return name in self._open

    def note_compile(self, hit: bool) -> None:
        with self._lock:
            if hit:
                self.compile_cache_hits += 1
            else:
                self.compile_cache_misses += 1

    def note_compile_time(self, seconds: float, key: str = "",
                          source: str = "trace") -> None:
        ms = seconds * 1000.0
        with self._lock:
            self.compile_ms += ms
            self.compiled_programs += 1
            self.phases["compile"] = self.phases.get("compile", 0.0) + ms
            if len(self.compile_events) < 256:
                self.compile_events.append(
                    {"key": key[:120], "ms": round(ms, 3),
                     "source": source})

    def note_retrace(self, cause: str, seconds: float) -> None:
        """One attributed compile (exec/retrace.py). First-ever cold
        compiles ride the causes breakdown only."""
        with self._lock:
            self.retrace_causes[cause] = \
                self.retrace_causes.get(cause, 0) + 1
            if cause != "first-ever":
                self.retrace_count += 1
                self.retrace_ms += seconds * 1000.0

    def note_admission_wait(self, waited_ms: float) -> None:
        with self._lock:
            self.admission_wait_ms += float(waited_ms)

    def note_persistent(self, hit: bool, seconds: float = 0.0) -> None:
        with self._lock:
            if hit:
                self.persistent_hits += 1
                self.persistent_load_ms += seconds * 1000.0
            else:
                self.persistent_misses += 1

    def note_compile_loaded(self, seconds: float, key: str = "") -> None:
        """A persistent-cache hit bound a stored executable: record the
        per-stage event (source=persistent) WITHOUT charging the compile
        phase — nothing compiled."""
        with self._lock:
            if len(self.compile_events) < 256:
                self.compile_events.append(
                    {"key": key[:120], "ms": round(seconds * 1000.0, 3),
                     "source": "persistent"})

    def note_backend_routes(self, routes) -> None:
        with self._lock:
            room = 64 - len(self.backend_routes)
            if room > 0 and routes:
                self.backend_routes.extend(list(routes)[:room])

    def note_transfer(self, nbytes: int) -> None:
        with self._lock:
            self.transfer_bytes += int(nbytes)

    def note_spill(self, nbytes: int) -> None:
        with self._lock:
            self.spill_bytes += int(nbytes)

    def note_rtf(self, built: int = 0, pushed: int = 0,
                 rows_pruned: int = 0, build_ms: float = 0.0) -> None:
        with self._lock:
            self.rtf_built += int(built)
            self.rtf_pushed += int(pushed)
            self.rtf_rows_pruned += int(rows_pruned)
            self.rtf_build_ms += float(build_ms)

    def note_fault_tolerance(self, retries: int = 0,
                             speculative_launched: int = 0,
                             speculative_won: int = 0) -> None:
        with self._lock:
            self.ft_retries += int(retries)
            self.ft_speculative_launched += int(speculative_launched)
            self.ft_speculative_won += int(speculative_won)

    def note_validated(self, passes: int = 1) -> None:
        with self._lock:
            self.validated_passes += int(passes)

    def note_shuffle(self, wire_bytes: int = 0,
                     wire_bytes_compressed: int = 0,
                     fetch_wait_s: float = 0.0, decode_s: float = 0.0,
                     governor_deferred: int = 0) -> None:
        with self._lock:
            self.shuffle_wire_bytes += int(wire_bytes)
            self.shuffle_wire_compressed += int(wire_bytes_compressed)
            self.shuffle_fetch_wait_ms += float(fetch_wait_s) * 1000.0
            self.shuffle_decode_ms += float(decode_s) * 1000.0
            self.governor_deferred += int(governor_deferred)

    def note_adaptive(self, coalesced: int = 0, split: int = 0,
                      broadcast: int = 0, reordered: int = 0,
                      events=None) -> None:
        with self._lock:
            self.adaptive_coalesced += int(coalesced)
            self.adaptive_split += int(split)
            self.adaptive_broadcast += int(broadcast)
            self.adaptive_reordered += int(reordered)
            if events:
                room = 128 - len(self.adaptive_events)
                if room > 0:
                    self.adaptive_events.extend(list(events)[:room])

    def note_skew(self, entries) -> None:
        with self._lock:
            room = 32 - len(self.skew)
            if room > 0 and entries:
                self.skew.extend(list(entries)[:room])

    def note_shuffle_channels(self, entries) -> None:
        with self._lock:
            room = 32 - len(self.shuffle_channels)
            if room > 0 and entries:
                self.shuffle_channels.extend(list(entries)[:room])

    def note_fusion(self, stages: int = 0, fused_ops: int = 0,
                    fallbacks: int = 0) -> None:
        with self._lock:
            self.fusion_stages += int(stages)
            self.fusion_fused_ops += int(fused_ops)
            self.fusion_fallbacks += int(fallbacks)

    def note_streaming(self, epoch: int, commit_ms: float = 0.0,
                       state_rows: int = 0,
                       replayed: bool = False) -> None:
        with self._lock:
            self.streaming_epoch = int(epoch)
            self.streaming_commit_ms = float(commit_ms)
            self.streaming_state_rows = int(state_rows)
            self.streaming_replayed = bool(replayed)

    def note_result_cache(self, status: str = "",
                          fragment: Optional[str] = None,
                          nbytes: int = 0, attached: int = 0,
                          saved: int = 0) -> None:
        """Result/fragment cache activity. Status precedence: a whole-
        query hit outranks a view read outranks a shared scan outranks
        a miss (fragment-only hits ride the fragments/bytes fields)."""
        order = {"": 0, "miss": 1, "shared-scan": 2, "view": 3, "hit": 4}
        with self._lock:
            if status and order.get(status, 0) >= \
                    order.get(self.cache_status, 0):
                self.cache_status = status
            if fragment and len(self.cache_fragments) < 32 \
                    and fragment not in self.cache_fragments:
                self.cache_fragments.append(fragment)
            self.cache_bytes_served += int(nbytes)
            self.scan_share_attached += int(attached)
            self.scan_share_saved += int(saved)

    def add_task(self, stage: int, partition: int, worker_id: str,
                 operators: List[dict], rows_out: int = 0) -> None:
        """Merge one distributed task's operator metrics (driver side)."""
        with self._lock:
            self.tasks = [t for t in self.tasks
                          if not (t["stage"] == stage
                                  and t["partition"] == partition)]
            self.tasks.append({
                "stage": int(stage), "partition": int(partition),
                "worker_id": worker_id, "rows_out": int(rows_out),
                "operators": operators})

    # -- shape ---------------------------------------------------------
    @property
    def total_ms(self) -> float:
        end = self.end_time or time.time()
        return max(0.0, (end - self.start_time) * 1000.0)

    def current_phase(self) -> str:
        with self._lock:
            if self._open:          # the phase actually RUNNING now
                return self._open[-1]
            names = [n for n in self.phases if n != "compile"]
        return names[-1] if names else "submitted"

    def phase_items(self) -> List:
        """(name, ms) in canonical order, then any custom phases."""
        with self._lock:
            phases = dict(self.phases)
        out = [(n, phases.pop(n)) for n in PHASES if n in phases]
        out.extend(sorted(phases.items()))
        return out

    def critical_path_summary(self) -> Optional[dict]:
        """Per-category wall-time attribution for the bench artifact:
        the event-derived critical path when the query ran distributed,
        else a phase-derived approximation for the local path (execute
        split into compile / fetch-wait / compute)."""
        if self.critical_path:
            return {"derived": False,
                    "categories": dict(
                        self.critical_path.get("categories", {}))}
        phases = {n: ms for n, ms in self.phase_items()}
        if not phases:
            return None
        execute = float(phases.get("execute", 0.0))
        compile_ms = min(execute, float(phases.get("compile", 0.0)))
        fetch_wait = min(execute - compile_ms,
                         float(self.shuffle_fetch_wait_ms))
        cats = {"compute": round(execute - compile_ms - fetch_wait, 3),
                "compile": round(compile_ms, 3),
                "fetch-wait": round(fetch_wait, 3)}
        return {"derived": True,
                "categories": {c: ms for c, ms in cats.items() if ms}}

    def to_dict(self) -> dict:
        return {
            "query_id": self.query_id,
            "statement": self.statement,
            "session": self.session,
            "tenant": self.tenant,
            "status": self.status,
            "error": self.error,
            "start_time": self.start_time,
            "total_ms": round(self.total_ms, 3),
            "phases": {n: round(ms, 3) for n, ms in self.phase_items()},
            "compile": {
                "cache_hits": self.compile_cache_hits,
                "cache_misses": self.compile_cache_misses,
                "persistent_hits": self.persistent_hits,
                "persistent_misses": self.persistent_misses,
                "persistent_load_ms": round(self.persistent_load_ms, 3),
                "compiled_programs": self.compiled_programs,
                "time_ms": round(self.compile_ms, 3),
                "events": list(self.compile_events),
            },
            "plan_fingerprint": self.plan_fingerprint,
            "retraces": {
                "count": self.retrace_count,
                "ms": round(self.retrace_ms, 3),
                "causes": dict(self.retrace_causes),
            },
            "admission_wait_ms": round(self.admission_wait_ms, 3),
            "anomaly_verdict": self.anomaly_verdict,
            "anomaly_excess_ms": round(self.anomaly_excess_ms, 3),
            "backends": list(self.backend_routes),
            "transfer_bytes": self.transfer_bytes,
            "spill_bytes": self.spill_bytes,
            "runtime_filter": {
                "built": self.rtf_built,
                "pushed": self.rtf_pushed,
                "rows_pruned": self.rtf_rows_pruned,
                "build_ms": round(self.rtf_build_ms, 3),
            },
            "fault_tolerance": {
                "retries": self.ft_retries,
                "speculative_launched": self.ft_speculative_launched,
                "speculative_won": self.ft_speculative_won,
            },
            "shuffle": {
                "wire_bytes": self.shuffle_wire_bytes,
                "wire_bytes_compressed": self.shuffle_wire_compressed,
                "fetch_wait_ms": round(self.shuffle_fetch_wait_ms, 3),
                "decode_ms": round(self.shuffle_decode_ms, 3),
                "governor_deferred": self.governor_deferred,
                "channels": list(self.shuffle_channels),
            },
            "adaptive": {
                "coalesced": self.adaptive_coalesced,
                "split": self.adaptive_split,
                "broadcast": self.adaptive_broadcast,
                "reordered": self.adaptive_reordered,
                "events": list(self.adaptive_events),
            },
            "skew": list(self.skew),
            "validated_passes": self.validated_passes,
            "fusion": {
                "stages": self.fusion_stages,
                "fused_ops": self.fusion_fused_ops,
                "fallbacks": self.fusion_fallbacks,
            },
            "streaming": {
                "epoch": self.streaming_epoch,
                "commit_ms": round(self.streaming_commit_ms, 3),
                "state_rows": self.streaming_state_rows,
                "replayed": self.streaming_replayed,
            } if self.streaming_epoch >= 0 else None,
            "result_cache": {
                "status": self.cache_status,
                "fragments": list(self.cache_fragments),
                "bytes_served": self.cache_bytes_served,
                "scan_share_attached": self.scan_share_attached,
                "scan_share_saved": self.scan_share_saved,
            } if self.cache_status or self.cache_fragments
            or self.scan_share_attached else None,
            "rows_out": self.rows_out,
            "slow": self.slow,
            "critical_path": self.critical_path,
            "operators": list(self.operators),
            "tasks": list(self.tasks),
            "trace_id": self.trace_id,
        }

    def render(self) -> str:
        """Human text: the EXPLAIN ANALYZE phase header."""
        lines = [f"total: {self.total_ms:.1f}ms"]
        for name, ms in self.phase_items():
            extra = ""
            if name == "compile":
                extra = (f" (cache hits={self.compile_cache_hits} "
                         f"misses={self.compile_cache_misses})")
            lines.append(f"phase {name}: {ms:.1f}ms{extra}")
        if (self.compile_cache_hits or self.compile_cache_misses
                or self.persistent_hits):
            # the compiled-program cache ladder per stage program:
            # in-memory hit (nothing bound) → persistent hit (stored
            # executable deserialized) → miss (trace + XLA compile;
            # counted directly — key-level cache misses and
            # signature-level persistent hits don't subtract)
            line = (f"compile: memory_hits={self.compile_cache_hits} "
                    f"persistent_hits={self.persistent_hits} "
                    f"misses={self.compiled_programs}")
            if self.persistent_hits:
                line += f" load={self.persistent_load_ms:.1f}ms"
            lines.append(line)
        if self.retrace_causes:
            causes = " ".join(
                f"{c}={n}"
                for c, n in sorted(self.retrace_causes.items()))
            lines.append(f"retraces: {self.retrace_count} "
                         f"({causes}) {self.retrace_ms:.1f}ms")
        if self.anomaly_verdict:
            lines.append(f"anomaly: {self.anomaly_verdict} "
                         f"(+{self.anomaly_excess_ms:.1f}ms vs baseline)")
        if self.admission_wait_ms:
            lines.append(
                f"admission wait: {self.admission_wait_ms:.1f}ms")
        if self.backend_routes:
            routed = " ".join(
                f"s{r.get('stage')}={r.get('backend')}"
                f"({r.get('reason')})" for r in self.backend_routes)
            lines.append(f"backend: {routed}")
        if self.transfer_bytes:
            lines.append(f"device transfer: {self.transfer_bytes} bytes")
        if self.spill_bytes:
            lines.append(f"spill: {self.spill_bytes} bytes")
        if self.rtf_built or self.rtf_rows_pruned:
            lines.append(
                f"runtime filters: built={self.rtf_built} "
                f"pushed={self.rtf_pushed} "
                f"rows_pruned={self.rtf_rows_pruned} "
                f"build={self.rtf_build_ms:.1f}ms")
        if self.ft_retries or self.ft_speculative_launched:
            lines.append(
                f"fault tolerance: retries={self.ft_retries} "
                f"speculative={self.ft_speculative_launched} "
                f"won={self.ft_speculative_won}")
        if self.shuffle_wire_bytes or self.shuffle_fetch_wait_ms:
            ratio = (self.shuffle_wire_bytes
                     / self.shuffle_wire_compressed) \
                if self.shuffle_wire_compressed else 0.0
            line = (f"shuffle: wire={self.shuffle_wire_bytes}B "
                    f"compressed={self.shuffle_wire_compressed}B")
            if ratio:
                line += f" ({ratio:.2f}x)"
            line += (f" fetch_wait={self.shuffle_fetch_wait_ms:.1f}ms "
                     f"decode={self.shuffle_decode_ms:.1f}ms")
            if self.governor_deferred:
                line += f" governor_deferred={self.governor_deferred}"
            lines.append(line)
        for entry in self.skew:
            lines.append(
                f"skew: stage {entry.get('stage')} max/median="
                f"{entry.get('ratio')}x (max={entry.get('max_bytes')}B "
                f"median={entry.get('median_bytes')}B over "
                f"{entry.get('channels')} channels)")
        if (self.adaptive_coalesced or self.adaptive_split
                or self.adaptive_broadcast or self.adaptive_reordered):
            lines.append(
                f"adaptive: coalesced={self.adaptive_coalesced} "
                f"split={self.adaptive_split} "
                f"broadcast={self.adaptive_broadcast} "
                f"reordered={self.adaptive_reordered}")
        if self.fusion_stages:
            extra = f" ({self.fusion_fused_ops} ops inlined"
            if self.fusion_fallbacks:
                extra += f", {self.fusion_fallbacks} fallbacks"
            extra += ")"
            lines.append(f"fused: {self.fusion_stages} stages{extra}")
        if self.streaming_epoch >= 0:
            line = (f"streaming: epoch={self.streaming_epoch} "
                    f"commit={self.streaming_commit_ms:.1f}ms "
                    f"state_rows={self.streaming_state_rows}")
            if self.streaming_replayed:
                line += " (replayed)"
            lines.append(line)
        if self.cache_status or self.cache_fragments \
                or self.scan_share_attached:
            line = f"cache: {self.cache_status or 'miss'}"
            if self.cache_fragments:
                line += " fragments=" + ",".join(self.cache_fragments)
            if self.cache_bytes_served:
                line += f" bytes={self.cache_bytes_served}"
            if self.scan_share_attached:
                line += (f" attached={self.scan_share_attached} "
                         f"saved={self.scan_share_saved}")
            lines.append(line)
        if self.validated_passes:
            lines.append(f"validated: {self.validated_passes} passes")
        if self.critical_path:
            from .analysis.timeline import render_critical_path
            line = render_critical_path(self.critical_path)
            if line:
                lines.append(line)
        if self.tasks:
            from .telemetry import OperatorMetrics
            lines.append(f"tasks: {len(self.tasks)}")
            for t in sorted(self.tasks, key=lambda t: (t["stage"],
                                                       t["partition"])):
                lines.append(f"  stage {t['stage']} partition "
                             f"{t['partition']} ({t['worker_id']}) "
                             f"rows={t['rows_out']}")
                for op in t["operators"]:
                    lines.append(
                        OperatorMetrics.from_dict(op).render(indent=2))
        return "\n".join(lines)


class FlightRecorder:
    """Bounded in-process store of completed profiles.

    ``capacity`` newest profiles ride the ring; queries whose total time
    exceeded the slow threshold are retained separately in a
    ``slow_capacity``-bounded log so a burst of fast queries cannot
    evict the evidence of a slow one."""

    def __init__(self, capacity: int = 128, slow_capacity: int = 64):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(1, int(capacity)))
        self._slow: deque = deque(maxlen=max(1, int(slow_capacity)))
        self._active: "OrderedDict[str, QueryProfile]" = OrderedDict()

    def start(self, profile: QueryProfile) -> None:
        with self._lock:
            self._active[profile.query_id] = profile
            while len(self._active) > 1024:  # leak guard
                self._active.popitem(last=False)

    def finish(self, profile: QueryProfile) -> None:
        with self._lock:
            self._active.pop(profile.query_id, None)
            self._ring.append(profile)
            if profile.slow:
                self._slow.append(profile)

    def discard(self, profile: QueryProfile) -> None:
        with self._lock:
            self._active.pop(profile.query_id, None)

    def profiles(self) -> List[QueryProfile]:
        """Completed profiles, newest first: ring ∪ retained slow log."""
        with self._lock:
            seen = set()
            out = []
            for p in list(self._ring)[::-1] + list(self._slow)[::-1]:
                if id(p) not in seen:
                    seen.add(id(p))
                    out.append(p)
        return out

    def active(self) -> List[QueryProfile]:
        with self._lock:
            return list(self._active.values())

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._slow.clear()
            self._active.clear()


def _recorder_from_config() -> FlightRecorder:
    from .config import get as config_get
    try:
        cap = int(config_get("telemetry.profile_ring_capacity", 128))
        slow_cap = int(config_get("telemetry.slow_log_capacity", 64))
    except (TypeError, ValueError):
        cap, slow_cap = 128, 64
    return FlightRecorder(cap, slow_cap)


FLIGHT_RECORDER = _recorder_from_config()

_local = threading.local()

#: default slow-query threshold when the session conf doesn't set
#: spark.sail.telemetry.slowQueryMs (0 disables the slow log)
DEFAULT_SLOW_QUERY_MS = 1000.0


def current_profile() -> Optional[QueryProfile]:
    return getattr(_local, "profile", None)


def _slow_threshold_ms(conf) -> float:
    value = None
    if conf is not None:
        get = getattr(conf, "get", None)
        if get is not None:
            value = get("spark.sail.telemetry.slowQueryMs")
    if value is None:
        from .config import get as config_get
        value = config_get("telemetry.slow_query_ms",
                           DEFAULT_SLOW_QUERY_MS)
    try:
        return float(value)
    except (TypeError, ValueError):
        return DEFAULT_SLOW_QUERY_MS


@contextmanager
def profile_query(statement: str = "", session: str = "", conf=None,
                  enabled: bool = True, tenant: str = ""):
    """Open (or join) the thread's query profile.

    The OUTERMOST caller owns the profile: nested entries (commands that
    re-enter ``_execute_query``, subqueries, the cluster runner inside a
    session query) accumulate into the active profile instead of
    fragmenting one query into many records.

    ``enabled=False`` yields a detached throwaway profile that is never
    recorded — used for fetches of already-profiled results (a command's
    LocalRelation output) so they don't pollute the flight recorder."""
    existing = current_profile()
    if existing is not None:
        yield existing
        return
    if not enabled:
        yield QueryProfile(query_id="", statement=statement,
                           start_time=time.time())
        return
    profile = QueryProfile(
        query_id=uuid.uuid4().hex[:16],
        statement=(statement or "")[:_STATEMENT_MAX],
        session=session, tenant=tenant, start_time=time.time())
    from . import tracing as tr
    profile.trace_id = tr.current_trace_id()
    _local.profile = profile
    FLIGHT_RECORDER.start(profile)
    try:
        from . import events as _events
        _events.emit(_events.EventType.QUERY_START,
                     query_id=profile.query_id,
                     trace_id=profile.trace_id,
                     statement=profile.statement[:200],
                     session=profile.session, tenant=profile.tenant)
    except Exception:  # noqa: BLE001 — telemetry must never break queries
        pass
    try:
        yield profile
    except BaseException as e:
        profile.status = "failed"
        profile.error = f"{type(e).__name__}: {e}"[:512]
        raise
    else:
        profile.status = "succeeded"
    finally:
        _local.profile = None
        profile.end_time = time.time()
        threshold = _slow_threshold_ms(conf)
        profile.slow = bool(threshold > 0
                            and profile.total_ms >= threshold)
        FLIGHT_RECORDER.finish(profile)
        _finalize(profile, threshold)


def _finalize(profile: QueryProfile, threshold_ms: float) -> None:
    """Post-completion export: registry counter, slow-query log line,
    and an OTLP ``query`` span carrying the phase breakdown. Must never
    raise into the query path."""
    try:
        _record_metric("execution.query_count", 1,
                       session=profile.session or "default")
    except Exception:  # noqa: BLE001 — telemetry must never break queries
        pass
    try:
        # live SLO source: one query.latency observation per phase the
        # query entered plus the end-to-end wall under phase=total —
        # the histograms the per-tenant p50/p95/p99 surfaces
        # (system.telemetry.tenant_slo, /metrics) are computed from
        tenant = profile.tenant or "default"
        for name, ms in profile.phase_items():
            _record_metric("query.latency", ms / 1000.0,
                           tenant=tenant, phase=name)
        _record_metric("query.latency", profile.total_ms / 1000.0,
                       tenant=tenant, phase="total")
    except Exception:  # noqa: BLE001
        pass
    try:
        from . import events as _events
        _events.emit(_events.EventType.QUERY_END,
                     query_id=profile.query_id,
                     trace_id=profile.trace_id, status=profile.status,
                     rows_out=profile.rows_out,
                     total_ms=round(profile.total_ms, 3),
                     fingerprint=profile.plan_fingerprint,
                     spill_bytes=profile.spill_bytes,
                     cache_status=profile.cache_status)
    except Exception:  # noqa: BLE001
        pass
    try:
        # classify AFTER the query_end emit: the classifier cuts the
        # event stream at the query_end record, so the evidence set it
        # sees is exactly what a durable-log replay sees (events
        # racing in from workers after the cut are excluded on BOTH
        # sides). It still observes the profile into its baseline only
        # after classifying — an outlier must not pollute the baseline
        # it was judged against. The OTLP span below carries the
        # verdict.
        from .analysis import anomaly as _anomaly
        _anomaly.on_profile_complete(profile)
    except Exception:  # noqa: BLE001
        pass
    try:
        if profile.slow:
            logger.warning(
                "slow query %s: %.0fms (threshold %.0fms): %s",
                profile.query_id, profile.total_ms, threshold_ms,
                profile.statement[:200])
        from . import tracing as tr
        if tr._exporter() is not None:
            attrs = {"query.id": profile.query_id,
                     "query.status": profile.status,
                     "query.rows_out": profile.rows_out,
                     "query.compile.cache_hits":
                         profile.compile_cache_hits,
                     "query.compile.cache_misses":
                         profile.compile_cache_misses,
                     "query.transfer_bytes": profile.transfer_bytes,
                     "query.spill_bytes": profile.spill_bytes,
                     "query.runtime_filter.built": profile.rtf_built,
                     "query.runtime_filter.rows_pruned":
                         profile.rtf_rows_pruned,
                     "query.adaptive.coalesced":
                         profile.adaptive_coalesced,
                     "query.adaptive.split": profile.adaptive_split,
                     "query.adaptive.broadcast":
                         profile.adaptive_broadcast,
                     "query.adaptive.reordered":
                         profile.adaptive_reordered,
                     "query.plan_fingerprint": profile.plan_fingerprint,
                     "query.retrace_count": profile.retrace_count,
                     "query.anomaly.verdict": profile.anomaly_verdict,
                     "query.anomaly.excess_ms":
                         round(profile.anomaly_excess_ms, 3)}
            if profile.cache_status or profile.cache_fragments \
                    or profile.scan_share_attached:
                attrs["query.result_cache.status"] = \
                    profile.cache_status or "miss"
                attrs["query.result_cache.bytes_served"] = \
                    profile.cache_bytes_served
                attrs["query.result_cache.fragments"] = \
                    ",".join(profile.cache_fragments)
                attrs["query.scan_share.attached"] = \
                    profile.scan_share_attached
            for name, ms in profile.phase_items():
                attrs[f"query.phase.{name}_ms"] = round(ms, 3)
            if profile.critical_path:
                # the gating chain rides the query span so the OTLP
                # view and the event log cross-reference
                attrs["query.critical_path"] = json.dumps(
                    profile.critical_path, default=str)
            start_ns = int(profile.start_time * 1e9)
            end_ns = int((profile.end_time or profile.start_time) * 1e9)
            span = tr.Span(
                trace_id=profile.trace_id or uuid.uuid4().hex,
                span_id=uuid.uuid4().hex[:16], parent_id=None,
                name="query", start_ns=start_ns, end_ns=end_ns,
                attributes=attrs,
                status_ok=profile.status == "succeeded")
            tr._exporter().add(span)
    except Exception:  # noqa: BLE001
        pass


# ---------------------------------------------------------------------------
# recording helpers for the executors (cheap no-ops without a profile)
# ---------------------------------------------------------------------------

@contextmanager
def maybe_phase(name: str):
    """Time a phase on the current profile; transparent without one."""
    profile = current_profile()
    if profile is None:
        yield
        return
    with profile.phase(name):
        yield


def note_compile_cache(hit: bool) -> None:
    try:
        _record_metric("execution.compile.cache_hit_count" if hit
                       else "execution.compile.cache_miss_count", 1)
    except Exception:  # noqa: BLE001
        pass
    profile = current_profile()
    if profile is not None:
        profile.note_compile(hit)


def note_compile_time(seconds: float, key: str = "") -> None:
    try:
        _record_metric("execution.compile.compile_time", float(seconds))
    except Exception:  # noqa: BLE001
        pass
    try:
        from . import events as _events
        _events.emit(_events.EventType.COMPILE, key=key[:120],
                     ms=round(float(seconds) * 1000.0, 3),
                     source="trace")
    except Exception:  # noqa: BLE001
        pass
    profile = current_profile()
    if profile is not None:
        profile.note_compile_time(seconds, key, source="trace")


def note_persistent_cache(hit: bool, seconds: float = 0.0) -> None:
    """One persistent compiled-program cache consult (exec/pcache.py):
    a hit loaded a stored AOT executable, a miss fell through to JIT."""
    profile = current_profile()
    if profile is not None:
        profile.note_persistent(hit, seconds)


def note_compile_event(key: str, seconds: float,
                       source: str = "persistent") -> None:
    """A stage program was bound WITHOUT compiling (persistent-cache
    load): the per-stage compile event stream and the flight recorder
    see it, but no compile time is charged."""
    try:
        from . import events as _events
        _events.emit(_events.EventType.COMPILE, key=key[:120],
                     ms=round(float(seconds) * 1000.0, 3),
                     source=source)
    except Exception:  # noqa: BLE001
        pass
    profile = current_profile()
    if profile is not None:
        profile.note_compile_loaded(seconds, key)


def note_retrace(cause: str, seconds: float) -> None:
    """One attributed compile (exec/retrace.py) on the current query;
    transparent without a profile (the event/metric surfaces still
    record it)."""
    profile = current_profile()
    if profile is not None:
        profile.note_retrace(cause, seconds)


def note_admission_wait(waited_ms: float) -> None:
    """Admission-queue wall time the current query paid before running
    (exec/admission.py)."""
    profile = current_profile()
    if profile is not None:
        profile.note_admission_wait(waited_ms)


def note_plan_fingerprint(fp: str) -> None:
    """Stamp the plan fingerprint the baseline/anomaly plane keys on."""
    profile = current_profile()
    if profile is not None and fp:
        profile.plan_fingerprint = fp


def note_backend_routes(routes) -> None:
    """Per-stage backend routing decisions (exec/router.py) taken for
    the current query's plan."""
    profile = current_profile()
    if profile is not None:
        profile.note_backend_routes(routes)


def note_result_cache(status: str = "", fragment: Optional[str] = None,
                      nbytes: int = 0, attached: int = 0,
                      saved: int = 0) -> None:
    """Result/fragment cache activity on the current query (scan-path
    executors call this; transparent without a profile)."""
    profile = current_profile()
    if profile is not None:
        profile.note_result_cache(status, fragment=fragment,
                                  nbytes=nbytes, attached=attached,
                                  saved=saved)


def note_transfer_bytes(nbytes: int) -> None:
    profile = current_profile()
    if profile is not None:
        profile.note_transfer(nbytes)


def note_spill_bytes(nbytes: int) -> None:
    profile = current_profile()
    if profile is not None:
        profile.note_spill(nbytes)


def note_runtime_filter(built: int = 0, pushed: int = 0,
                        rows_pruned: int = 0,
                        build_ms: float = 0.0) -> None:
    profile = current_profile()
    if profile is not None:
        profile.note_rtf(built=built, pushed=pushed,
                         rows_pruned=rows_pruned, build_ms=build_ms)


def note_plan_validated(passes: int = 1) -> None:
    """One plan-invariant validator walk completed for this query."""
    profile = current_profile()
    if profile is not None:
        profile.note_validated(passes)


def note_fusion(stages: int = 0, fused_ops: int = 0,
                fallbacks: int = 0) -> None:
    """Whole-stage fusion accounting for the current query."""
    profile = current_profile()
    if profile is not None:
        profile.note_fusion(stages=stages, fused_ops=fused_ops,
                            fallbacks=fallbacks)


def last_profile() -> Optional[QueryProfile]:
    """Most recently completed profile (bench / tests convenience)."""
    profiles = FLIGHT_RECORDER.profiles()
    return profiles[0] if profiles else None
