"""Driver-side elastic autoscaler: a pure, replayable scaling policy.

The fleet must grow on sustained admission pressure and shrink on idle
WITHOUT ever failing a query (ROADMAP item 3). Every input signal
already exists — admission queue depth and shed rate (PR 11),
continuous credit-stall time (PR 15), per-worker occupancy and idle
time — this module closes the loop with a policy that is a pure
function of a recorded signal snapshot:

- ``FleetSignals``  one tick's observations (gathered by the driver in
  ``cluster.DriverActor._autoscaler_signals``; this module never reads
  live state)
- ``PolicyState``   the few counters that carry across ticks (streaks,
  cooldown) — evolved deterministically by :func:`evaluate`
- ``evaluate(cfg, state, signals) -> (Decision, PolicyState)``

Determinism contract: the decision ``detail`` (canonical sort_keys
JSON, same convention as ``adaptive_applied``/``anomaly`` events)
embeds the config, the input state, and the full signal snapshot —
:func:`replay_record` re-derives the decision from the detail ALONE
and must reproduce action/worker/reason bit-identically. The chaos
determinism test replays every recorded ``autoscaler_decision`` event
through it.

Tenant-weight modulation: scale-UP pressure is weight-capped per
tenant — one tenant's contribution to the effective queue depth (and
to the effective shed count) saturates at ``weight × threshold``, and
the trigger is STRICTLY above the threshold. A single weight-1 tenant
flooding its queue therefore buys sheds (PR 11's admission path), not
fleet growth; broad multi-tenant pressure, or a high-weight tenant
with paid-for headroom, exceeds the threshold and grows the pool.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

# decision taxonomy (the README table mirrors these)
SCALE_UP = "scale_up"
SCALE_DOWN = "scale_down"
HOLD = "hold"

UP_REASONS = ("queue_pressure", "shed_pressure", "credit_stall")
DOWN_REASONS = ("fleet_idle",)
HOLD_REASONS = ("disabled", "steady", "cooldown", "hysteresis",
                "at_max", "at_min", "no_candidate", "draining")


@dataclass(frozen=True)
class AutoscalerConfig:
    """``cluster.autoscaler.*`` knobs (see config/application.yaml)."""

    enabled: bool = False
    tick_secs: float = 4.0
    # scale-UP triggers: strictly-above thresholds per tick window
    up_queue_depth: int = 2
    up_shed_count: int = 1
    up_stall_secs: float = 1.0
    # scale-DOWN gates
    down_idle_secs: float = 30.0
    down_occupancy: float = 0.25
    # damping
    hysteresis_ticks: int = 2
    cooldown_ticks: int = 5
    # drain lifecycle (consumed by the driver, carried here so the
    # decision record is self-contained)
    drain_timeout_secs: float = 60.0
    hard_reap: bool = False

    @classmethod
    def load(cls) -> "AutoscalerConfig":
        from ..config import get as config_get
        from ..config import truthy as _on

        def _num(key, default, cast=float):
            try:
                return cast(config_get(key, default))
            except (TypeError, ValueError):
                return default

        d = cls()
        return cls(
            enabled=_on("cluster.autoscaler.enabled"),
            tick_secs=max(0.1, _num("cluster.autoscaler.tick_secs",
                                    d.tick_secs)),
            up_queue_depth=_num("cluster.autoscaler.up_queue_depth",
                                d.up_queue_depth, int),
            up_shed_count=_num("cluster.autoscaler.up_shed_count",
                               d.up_shed_count, int),
            up_stall_secs=_num("cluster.autoscaler.up_stall_secs",
                               d.up_stall_secs),
            down_idle_secs=_num("cluster.autoscaler.down_idle_secs",
                                d.down_idle_secs),
            down_occupancy=_num("cluster.autoscaler.down_occupancy",
                                d.down_occupancy),
            hysteresis_ticks=max(1, _num(
                "cluster.autoscaler.hysteresis_ticks",
                d.hysteresis_ticks, int)),
            cooldown_ticks=max(0, _num(
                "cluster.autoscaler.cooldown_ticks",
                d.cooldown_ticks, int)),
            drain_timeout_secs=max(1.0, _num(
                "cluster.autoscaler.drain_timeout_secs",
                d.drain_timeout_secs)),
            hard_reap=_on("cluster.autoscaler.hard_reap"),
        )

    def to_dict(self) -> dict:
        return {
            "enabled": self.enabled,
            "tick_secs": self.tick_secs,
            "up_queue_depth": self.up_queue_depth,
            "up_shed_count": self.up_shed_count,
            "up_stall_secs": self.up_stall_secs,
            "down_idle_secs": self.down_idle_secs,
            "down_occupancy": self.down_occupancy,
            "hysteresis_ticks": self.hysteresis_ticks,
            "cooldown_ticks": self.cooldown_ticks,
            "drain_timeout_secs": self.drain_timeout_secs,
            "hard_reap": self.hard_reap,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "AutoscalerConfig":
        base = cls()
        return cls(**{k: d.get(k, getattr(base, k))
                      for k in base.to_dict()})


@dataclass(frozen=True)
class WorkerSignals:
    """One worker's occupancy snapshot at the tick."""

    worker_id: str
    tasks: int            # running/resident tasks assigned
    slots: int
    idle_secs: float      # 0.0 while busy
    resident: bool        # hosts resident continuous stage tasks
    live_output: bool     # hosts sealed shuffle output a live job needs
    stoppable: bool       # the elastic manager owns it (can retire it)

    def to_dict(self) -> dict:
        return {"worker_id": self.worker_id, "tasks": self.tasks,
                "slots": self.slots,
                "idle_secs": round(self.idle_secs, 3),
                "resident": self.resident,
                "live_output": self.live_output,
                "stoppable": self.stoppable}

    @classmethod
    def from_dict(cls, d: dict) -> "WorkerSignals":
        return cls(worker_id=d["worker_id"], tasks=int(d["tasks"]),
                   slots=int(d["slots"]),
                   idle_secs=float(d["idle_secs"]),
                   resident=bool(d["resident"]),
                   live_output=bool(d["live_output"]),
                   stoppable=bool(d["stoppable"]))


@dataclass(frozen=True)
class FleetSignals:
    """Everything one policy tick observes, as plain data."""

    pool: int                       # live workers NOT draining
    draining: int
    pending_starts: int
    min_workers: int
    max_workers: int
    queued: Dict[str, int]          # admission queue depth per tenant
    shed: Dict[str, int]            # sheds per tenant since last tick
    weights: Dict[str, float]       # admission weights per tenant seen
    stall_secs: float               # credit-stall seconds since last tick
    workers: Tuple[WorkerSignals, ...]

    def to_dict(self) -> dict:
        return {
            "pool": self.pool, "draining": self.draining,
            "pending_starts": self.pending_starts,
            "min_workers": self.min_workers,
            "max_workers": self.max_workers,
            "queued": dict(sorted(self.queued.items())),
            "shed": dict(sorted(self.shed.items())),
            "weights": {t: round(float(w), 6)
                        for t, w in sorted(self.weights.items())},
            "stall_secs": round(self.stall_secs, 3),
            "workers": [w.to_dict()
                        for w in sorted(self.workers,
                                        key=lambda s: s.worker_id)],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "FleetSignals":
        return cls(
            pool=int(d["pool"]), draining=int(d["draining"]),
            pending_starts=int(d["pending_starts"]),
            min_workers=int(d["min_workers"]),
            max_workers=int(d["max_workers"]),
            queued={t: int(v) for t, v in d.get("queued", {}).items()},
            shed={t: int(v) for t, v in d.get("shed", {}).items()},
            weights={t: float(v)
                     for t, v in d.get("weights", {}).items()},
            stall_secs=float(d.get("stall_secs", 0.0)),
            workers=tuple(WorkerSignals.from_dict(w)
                          for w in d.get("workers", ())))


@dataclass
class PolicyState:
    """Cross-tick damping counters; evolved only by :func:`evaluate`."""

    up_streak: int = 0
    down_streak: int = 0
    cooldown_left: int = 0

    def to_dict(self) -> dict:
        return {"up_streak": self.up_streak,
                "down_streak": self.down_streak,
                "cooldown_left": self.cooldown_left}

    @classmethod
    def from_dict(cls, d: dict) -> "PolicyState":
        return cls(up_streak=int(d.get("up_streak", 0)),
                   down_streak=int(d.get("down_streak", 0)),
                   cooldown_left=int(d.get("cooldown_left", 0)))


@dataclass(frozen=True)
class Decision:
    action: str                 # scale_up | scale_down | hold
    worker: str                 # drain target ("" unless scale_down)
    reason: str
    detail: dict = field(default_factory=dict)

    def detail_json(self) -> str:
        """Canonical encoding — the replayable event payload."""
        return json.dumps(self.detail, sort_keys=True,
                          separators=(",", ":"))


def weighted_pressure(counts: Dict[str, int], weights: Dict[str, float],
                      threshold: float) -> float:
    """Weight-capped effective pressure: each tenant contributes at
    most ``weight × threshold``, so a single flooding tenant saturates
    AT the trigger threshold (strict > never fires on it alone) while
    broad pressure across tenants, or a high-weight tenant, exceeds
    it."""
    total = 0.0
    for tenant, count in counts.items():
        w = max(float(weights.get(tenant, 1.0)), 0.0)
        total += min(float(count), w * float(threshold))
    return total


def _up_pressure(cfg: AutoscalerConfig,
                 s: FleetSignals) -> Tuple[Optional[str], dict]:
    """First matching scale-up reason plus the derived numbers."""
    eff_depth = weighted_pressure(s.queued, s.weights,
                                  cfg.up_queue_depth)
    eff_shed = weighted_pressure(s.shed, s.weights, cfg.up_shed_count)
    derived = {"eff_queue_depth": round(eff_depth, 3),
               "eff_shed": round(eff_shed, 3),
               "stall_secs": round(s.stall_secs, 3)}
    if eff_depth > cfg.up_queue_depth:
        return "queue_pressure", derived
    if eff_shed > cfg.up_shed_count:
        return "shed_pressure", derived
    if s.stall_secs > cfg.up_stall_secs:
        return "credit_stall", derived
    return None, derived


def _down_candidate(cfg: AutoscalerConfig,
                    s: FleetSignals) -> Tuple[Optional[str], dict]:
    """Pick the drain target: fleet occupancy must be at/below the
    shrink threshold, and the victim must be a stoppable worker idle
    past ``down_idle_secs``. Cheapest drain first (no resident stages,
    no live output to hand off), then longest idle; worker id breaks
    ties so the choice is deterministic."""
    live = [w for w in s.workers]
    slots = sum(w.slots for w in live) or 1
    busy = sum(w.tasks for w in live)
    occupancy = busy / slots
    derived = {"occupancy": round(occupancy, 4)}
    if occupancy > cfg.down_occupancy:
        return None, derived
    idle = [w for w in live
            if w.stoppable and w.tasks == 0
            and w.idle_secs >= cfg.down_idle_secs]
    if not idle:
        return None, derived
    idle.sort(key=lambda w: (w.resident, w.live_output,
                             -round(w.idle_secs, 3), w.worker_id))
    return idle[0].worker_id, derived


def evaluate(cfg: AutoscalerConfig, state: PolicyState,
             signals: FleetSignals) -> Tuple[Decision, PolicyState]:
    """One policy tick. Pure: (cfg, state, signals) fully determine
    the decision and the successor state."""
    nxt = PolicyState(state.up_streak, state.down_streak,
                      max(0, state.cooldown_left - 1))

    def record(action: str, worker: str, reason: str,
               derived: dict) -> Decision:
        detail = {
            "action": action, "worker": worker, "reason": reason,
            "cfg": cfg.to_dict(), "state_in": state.to_dict(),
            "state_out": nxt.to_dict(), "derived": derived,
            "signals": signals.to_dict(),
        }
        return Decision(action, worker, reason, detail)

    if not cfg.enabled:
        return record(HOLD, "", "disabled", {}), nxt

    up_reason, up_derived = _up_pressure(cfg, signals)
    down_wid, down_derived = _down_candidate(cfg, signals)
    derived = dict(up_derived)
    derived.update(down_derived)

    # streaks advance on raw pressure, before capacity/cooldown gates:
    # damping measures how SUSTAINED the signal is, not how often we
    # were allowed to act on it
    nxt.up_streak = nxt.up_streak + 1 if up_reason else 0
    # up-pressure vetoes shrink outright (and resets its streak): the
    # two signals disagreeing means the fleet is NOT safely idle
    nxt.down_streak = 0 if (up_reason or down_wid is None) \
        else nxt.down_streak + 1

    if up_reason:
        if signals.pool + signals.pending_starts + signals.draining \
                >= signals.max_workers:
            return record(HOLD, "", "at_max", derived), nxt
        if nxt.up_streak < cfg.hysteresis_ticks:
            return record(HOLD, "", "hysteresis", derived), nxt
        if nxt.cooldown_left > 0:
            return record(HOLD, "", "cooldown", derived), nxt
        nxt.up_streak = 0
        nxt.cooldown_left = cfg.cooldown_ticks
        return record(SCALE_UP, "", up_reason, derived), nxt

    if down_wid is not None:
        if signals.draining > 0:
            # one drain at a time: handoff + relaunch must finish (and
            # be observed) before the next victim is chosen
            return record(HOLD, "", "draining", derived), nxt
        if signals.pool + signals.pending_starts \
                <= signals.min_workers:
            return record(HOLD, "", "at_min", derived), nxt
        if nxt.down_streak < cfg.hysteresis_ticks:
            return record(HOLD, "", "hysteresis", derived), nxt
        if nxt.cooldown_left > 0:
            return record(HOLD, "", "cooldown", derived), nxt
        nxt.down_streak = 0
        nxt.cooldown_left = cfg.cooldown_ticks
        return record(SCALE_DOWN, down_wid, "fleet_idle", derived), nxt

    return record(HOLD, "", "steady", derived), nxt


def replay_record(detail: dict) -> Decision:
    """Re-derive one decision from its recorded detail ALONE (the
    flight-recorder replay contract): rebuild cfg/state/signals from
    the detail and re-run :func:`evaluate`. The result must match the
    recorded action/worker/reason bit-identically — the determinism
    test asserts it for every recorded decision."""
    cfg = AutoscalerConfig.from_dict(detail["cfg"])
    state = PolicyState.from_dict(detail["state_in"])
    signals = FleetSignals.from_dict(detail["signals"])
    decision, _ = evaluate(cfg, state, signals)
    return decision


def replay_log(records: List[dict]) -> List[dict]:
    """Replay a list of ``autoscaler_decision`` event records (as
    loaded by ``events.load_event_log``) and return the re-derived
    ``{"action", "worker", "reason"}`` triples, in order."""
    out = []
    for rec in records:
        attrs = rec.get("attributes", rec)
        detail = attrs.get("detail")
        if isinstance(detail, str):
            detail = json.loads(detail)
        d = replay_record(detail)
        out.append({"action": d.action, "worker": d.worker,
                    "reason": d.reason})
    return out
