"""Persistent cross-process compiled-program cache (AOT executables).

Reference role: Flare's observation that a whole-stage-compiled program
is a reusable artifact worth persisting (arXiv:1703.08219) applied to
the serving problem PR 11/12 created: a fleet promising per-tenant p99s
cannot afford per-process XLA warmup, yet every worker re-JITs every
fused stage on first sight.

Entries are XLA executables serialized via jax's AOT path
(``jax.jit(fn).lower(*args).compile()`` +
``jax.experimental.serialize_executable``), so a load skips BOTH the
trace and the XLA compile — the two components of cold-start latency.
The on-disk store lives under ``compile_cache.dir``
(``compile_cache.{enabled,dir,max_mb}``; session override
``spark.sail.compileCache.enabled``) and is shared by concurrent
workers and across restarts:

- **Keying.** An entry digest covers the structural cache key the
  in-memory operator cache already uses (PR 6's
  ``stage_fingerprint``/``plan_fingerprint`` vocabulary), the CONTENT
  of every dictionary baked into the compiled closure (the in-memory
  cache verifies dictionaries by identity; across processes only
  content equality means anything), the abstract shapes/dtypes of the
  call arguments, and the environment fingerprint (jax + jaxlib
  version, backend platform, device count, x64 flag). Any skew lands
  on a different digest and reads as a miss, never a wrong program.
- **Writes** are tmp + atomic ``os.replace`` with per-writer tmp names,
  so concurrent multi-process writers can race on the same digest and
  readers always see a complete entry or none.
- **Eviction** under ``compile_cache.max_mb`` is LRU weighted by the
  observed compile time recorded in each entry's header: cheap-to-
  recompile entries evict first (ascending ``compile_s``, then oldest
  access), so the cache's value density stays high.
- **Failure policy.** Any load failure — corrupt or truncated entry,
  version-skewed key, unpicklable payload, injected ``io.cache`` fault
  — falls back to JIT compilation, silently but counted
  (``execution.compile.persistent_load_error_count``). A cache problem
  can slow a query down; it can never change a result.

Programs whose lowered module embeds a host callback
(``pure_callback`` UDFs) are never stored: a serialized callback
handle is meaningless in another process.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import threading
import time
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger("sail_tpu.pcache")

#: bump when the on-disk entry layout changes incompatibly; old entries
#: then read as misses and age out via eviction
FORMAT_VERSION = 1

_MAGIC = b"SAILPC1\n"
_SUFFIX = ".sailpc"

#: distinct argument signatures one program wrapper binds before it
#: stops persisting new shapes (chunked scans produce a handful of
#: rounded capacities; unbounded growth would be a leak)
_MAX_SIGS = 32

#: age after which an orphaned writer tmp file (killed mid-store) is
#: reaped by the next store-directory scan
_TMP_REAP_S = 600.0

_LOCK = threading.Lock()
_CONF: Optional[Tuple[bool, str, int]] = None
#: running estimate of the store's size, so each store does NOT pay a
#: directory-wide header scan: the full scan runs once to seed the
#: estimate and again only when the estimate crosses the budget
#: (concurrent writers make it approximate — eviction re-measures)
_APPROX_BYTES: Optional[int] = None
#: in-process accounting for /debug/compile_cache: digest -> [hits,
#: compile_s_saved_per_hit, site] (hits observed by THIS process)
_HIT_TALLY: Dict[str, List] = {}
#: hits not yet merged into the on-disk prewarm manifest (same shape);
#: flushed time-debounced so the ranking survives restarts
_TALLY_DELTA: Dict[str, List] = {}
_TALLY_LAST_FLUSH: float = 0.0
#: executables AOT-loaded by the startup prewarm, waiting for their
#: first caller (PersistentProgram._bind pops them: first traffic for a
#: prewarmed program pays neither trace+compile NOR a disk read)
_PRELOADED: Dict[str, object] = {}
_PREWARM_STARTED = False
#: manifest entries kept, ranked by compile-time saved
_MANIFEST_MAX = 512


# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------

def _load_conf() -> Tuple[bool, str, int]:
    from ..config import get as config_get
    from ..config import truthy
    try:
        enabled = truthy("compile_cache.enabled", default="true")
        d = str(config_get("compile_cache.dir", "") or "")
        max_mb = int(float(config_get("compile_cache.max_mb", 512)))
    except Exception:  # noqa: BLE001 — config trouble = cache off
        return False, "", 512
    return enabled and bool(d), d, max(1, max_mb)


_XLA_DIR: Optional[str] = None


def _sync_xla_cache(conf: Tuple[bool, str, int]) -> None:
    """Point jax's own persistent compilation cache at ``<dir>/xla``
    (or detach it when the store is off): it covers every XLA program
    OUTSIDE the AOT store — the many small eager-op dispatches and
    stray jits a cold process otherwise compiles one by one.
    Thresholds drop to zero because exactly those small programs are
    the cold-start long tail. Best-effort: an older jax without these
    knobs just skips them."""
    global _XLA_DIR
    target = os.path.join(conf[1], "xla") if conf[0] else None
    if target == _XLA_DIR:
        return
    import jax
    updates = [("jax_compilation_cache_dir", target)]
    if target is not None:
        updates += [("jax_persistent_cache_min_compile_time_secs", 0.0),
                    ("jax_persistent_cache_min_entry_size_bytes", 0)]
    for opt, value in updates:
        try:
            jax.config.update(opt, value)
        except Exception:  # noqa: BLE001 — knob unavailable: skip
            pass
    try:
        # jax latches the cache decision at the FIRST compile; module
        # imports usually compile something before the config layer is
        # consulted, so the latch must be reset for the dir to take
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except Exception:  # noqa: BLE001 — internal API moved: best effort
        pass
    _XLA_DIR = target


def _conf() -> Tuple[bool, str, int]:
    global _CONF
    c = _CONF
    if c is None:
        with _LOCK:
            c = _CONF
            if c is None:
                c = _CONF = _load_conf()
        _sync_xla_cache(c)
    return c


def enabled() -> bool:
    """Process-wide gate: ``compile_cache.enabled`` AND a configured
    ``compile_cache.dir`` (an empty dir means no store to share)."""
    return _conf()[0]


def cache_dir() -> str:
    return _conf()[1]


def max_bytes() -> int:
    return _conf()[2] * (1 << 20)


def reload() -> None:
    """Re-read ``compile_cache.*`` and re-sync jax's compilation-cache
    binding eagerly (tests, bench A/B knobs, cluster entry points
    after env changes)."""
    global _CONF, _APPROX_BYTES, _PREWARM_STARTED, _TALLY_LAST_FLUSH
    with _LOCK:
        _CONF = None
        _APPROX_BYTES = None
        _HIT_TALLY.clear()
        _TALLY_DELTA.clear()
        _PRELOADED.clear()
        _PREWARM_STARTED = False
        _TALLY_LAST_FLUSH = 0.0
    _conf()


# ---------------------------------------------------------------------------
# digests
# ---------------------------------------------------------------------------

def env_fingerprint() -> Tuple:
    """Everything that can invalidate a serialized executable between
    processes: jax/jaxlib version, backend platform, device topology,
    and the x64 flag (it changes every integer aval)."""
    import jax
    import jaxlib
    try:
        devices = jax.devices()
        platform = devices[0].platform if devices else "none"
        count = len(devices)
    except Exception:  # noqa: BLE001 — no backend = no cache
        platform, count = "none", 0
    return (FORMAT_VERSION, jax.__version__, jaxlib.__version__,
            platform, count, bool(jax.config.jax_enable_x64))


def signature(args) -> Optional[Tuple]:
    """Hashable abstract signature of a call: the pytree structure plus
    per-leaf (shape, dtype, weak_type). Non-array leaves contribute
    their type only (jit traces them as weak-typed scalars)."""
    import jax
    try:
        leaves, treedef = jax.tree_util.tree_flatten(args)
        sig = []
        for x in leaves:
            if hasattr(x, "shape") and hasattr(x, "dtype"):
                sig.append((tuple(x.shape), str(x.dtype),
                            bool(getattr(x, "weak_type", False))))
            else:
                sig.append(("py", type(x).__name__))
        return (treedef, tuple(sig))
    except Exception:  # noqa: BLE001 — unflattenable args: no persistence
        return None


def content_digest(objs) -> Optional[str]:
    """Content hash of the host objects baked into a compiled closure
    (dictionary arrays). The in-memory caches verify these by identity;
    across processes only content equality is meaningful. Returns None
    when any object has no canonical byte form (e.g. whole memory
    tables on the mesh path) — the program is then not persistable."""
    import pyarrow as pa
    h = hashlib.sha256()
    for obj in objs:
        if isinstance(obj, pa.ChunkedArray):
            obj = obj.combine_chunks()
        if not isinstance(obj, pa.Array):
            return None
        try:
            sink = pa.BufferOutputStream()
            batch = pa.record_batch([obj], names=["d"])
            with pa.ipc.new_stream(sink, batch.schema) as w:
                w.write_batch(batch)
            buf = sink.getvalue()
            h.update(len(buf).to_bytes(8, "little"))
            h.update(buf)
        except Exception:  # noqa: BLE001 — undigestable = unpersistable
            return None
    return h.hexdigest()


def entry_digest(key_repr: str, dict_digest: str, sig) -> Optional[str]:
    """The on-disk identity of one compiled program. ``key_repr`` must
    be a content-bearing repr: anything carrying a memory address means
    the key is identity-based and cannot name a cross-process entry."""
    if " at 0x" in key_repr:
        return None
    h = hashlib.sha256()
    h.update(repr(env_fingerprint()).encode())
    h.update(b"\x00")
    h.update(key_repr.encode())
    h.update(b"\x00")
    h.update(dict_digest.encode())
    h.update(b"\x00")
    h.update(repr(sig).encode())
    return h.hexdigest()


# ---------------------------------------------------------------------------
# metrics / accounting plumbing
# ---------------------------------------------------------------------------

def _count(name: str, value=1, **attrs) -> None:
    try:
        from ..metrics import record as _record_metric
        _record_metric(name, value, **attrs)
    except Exception:  # noqa: BLE001 — accounting never breaks execution
        pass


def _note_profile(hit: bool, seconds: float = 0.0) -> None:
    try:
        from .. import profiler
        profiler.note_persistent_cache(hit, seconds)
    except Exception:  # noqa: BLE001
        pass


def _gauge_bytes(total: int) -> None:
    _count("execution.compile.persistent_cache_bytes", total)


# ---------------------------------------------------------------------------
# the store
# ---------------------------------------------------------------------------

def _entry_path(digest: str) -> str:
    return os.path.join(cache_dir(), digest + _SUFFIX)


def _read_header(path: str) -> Optional[dict]:
    """The JSON header line of one entry (bounded read); None when the
    file is not a complete entry."""
    try:
        with open(path, "rb") as f:
            if f.read(len(_MAGIC)) != _MAGIC:
                return None
            line = f.readline(1 << 16)
            if not line.endswith(b"\n"):
                return None
            return json.loads(line)
    except (OSError, ValueError):
        return None


def _marker_path(digest: str) -> str:
    return os.path.join(cache_dir(), digest + ".bad")


def _poison(digest: str) -> None:
    """An INTACT entry whose executable cannot deserialize in a fresh
    process (some CPU programs reference JIT-resident symbols —
    'Symbols not found'): mark the digest so later processes neither
    retry the load nor re-store the same undeserializable program."""
    try:
        with open(_marker_path(digest), "w", encoding="utf-8") as f:
            f.write("undeserializable\n")
    except OSError:
        pass


def load(digest: str, site: str = "op"):
    """Fetch + deserialize one entry; returns a callable executing the
    stored program, or None (miss / any failure, counted). Corrupt
    entries are deleted (a later store repairs them); intact-but-
    undeserializable ones are poison-marked so no process retries."""
    return _load(digest, site=site)[0]


def _load(digest: str, site: str = "op", _tally: bool = True):
    """:func:`load` with the miss TYPED for retrace attribution:
    returns ``(callable_or_None, reason)``, reason ∈ {``hit``,
    ``absent``, ``poison``, ``skew``, ``error``} — poison covers both
    the pre-existing marker and a fresh intact-but-undeserializable
    entry; skew an entry refused for env/header mismatch; error an
    unreadable or corrupt blob."""
    from .. import faults
    path = _entry_path(digest)
    if os.path.exists(_marker_path(digest)):
        _count("execution.compile.persistent_miss_count")
        _note_profile(False)
        return None, "poison"
    try:
        faults.inject("io.cache", key=f"load:{site}:{digest[:12]}")
        t0 = time.perf_counter()
        with open(path, "rb") as f:
            blob = f.read()
    except FileNotFoundError:
        _count("execution.compile.persistent_miss_count")
        _note_profile(False)
        return None, "absent"
    except (OSError, faults.FaultInjectedError):
        _count("execution.compile.persistent_load_error_count")
        _count("execution.compile.persistent_miss_count")
        _note_profile(False)
        return None, "error"
    intact = False
    reason = "error"
    try:
        if not blob.startswith(_MAGIC):
            raise ValueError("bad magic")
        nl = blob.index(b"\n", len(_MAGIC))
        header = json.loads(blob[len(_MAGIC):nl + 1])
        if header.get("v") != FORMAT_VERSION or \
                header.get("digest") != digest or \
                header.get("env") != list(env_fingerprint()):
            reason = "skew"
            raise ValueError("entry/key skew")
        from jax.experimental import serialize_executable as se
        payload, in_tree, out_tree = pickle.loads(blob[nl + 1:])
        intact = True     # bytes parsed; only the runtime load remains
        loaded = se.deserialize_and_load(payload, in_tree, out_tree)
    except Exception:  # noqa: BLE001 — corrupt/truncated/skewed: JIT instead
        _count("execution.compile.persistent_load_error_count")
        _count("execution.compile.persistent_miss_count")
        _note_profile(False)
        if intact:
            _poison(digest)
            return None, "poison"
        try:  # useless bytes: drop them so a later store repairs
            os.unlink(path)
        except OSError:
            pass
        return None, reason
    seconds = time.perf_counter() - t0
    if not _tally:
        return loaded, "hit"
    _count("execution.compile.persistent_hit_count")
    _note_profile(True, seconds)
    compile_s = float(header.get("compile_s", 0.0))
    with _LOCK:
        tally = _HIT_TALLY.setdefault(digest, [0, compile_s,
                                               header.get("site", site)])
        tally[0] += 1
        while len(_HIT_TALLY) > 1024:
            _HIT_TALLY.pop(next(iter(_HIT_TALLY)))
        delta = _TALLY_DELTA.setdefault(digest, [0, compile_s,
                                                 header.get("site", site)])
        delta[0] += 1
    _maybe_flush_tally()
    try:
        # refresh recency for the compile-time-weighted LRU
        os.utime(path, None)
    except OSError:
        pass
    try:
        from .. import profiler
        profiler.note_compile_event(key=f"{site}:{digest[:12]}",
                                    seconds=seconds, source="persistent")
    except Exception:  # noqa: BLE001
        pass
    return loaded, "hit"


def store(digest: str, compiled, compile_s: float,
          site: str = "op") -> bool:
    """Serialize one AOT-compiled program under ``digest``. Best-effort:
    any failure leaves the store unchanged and the caller keeps its
    in-memory program."""
    from .. import faults
    d = cache_dir()
    if os.path.exists(_marker_path(digest)):
        return False  # known-undeserializable program: do not re-store
    try:
        faults.inject("io.cache", key=f"store:{site}:{digest[:12]}")
        from jax.experimental import serialize_executable as se
        triple = se.serialize(compiled)
        payload = pickle.dumps(triple)
    except Exception:  # noqa: BLE001 — unserializable program: skip
        return False
    header = {"v": FORMAT_VERSION, "digest": digest,
              "env": list(env_fingerprint()),
              "compile_s": round(float(compile_s), 6),
              "site": site, "created": time.time()}
    path = _entry_path(digest)
    tmp = os.path.join(
        d, f".tmp-{os.getpid()}-{threading.get_ident()}-{digest[:12]}")
    try:
        os.makedirs(d, exist_ok=True)
        with open(tmp, "wb") as f:
            f.write(_MAGIC)
            f.write(json.dumps(header,
                               separators=(",", ":")).encode() + b"\n")
            f.write(payload)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False
    _note_written(len(payload) + 256)
    return True


def _note_written(nbytes: int) -> None:
    global _APPROX_BYTES
    with _LOCK:
        if _APPROX_BYTES is None:
            seed = True
        else:
            _APPROX_BYTES += nbytes
            seed = False
    if seed:
        entries = _scan_entries()
        with _LOCK:
            _APPROX_BYTES = sum(e[1] for e in entries)
        _gauge_bytes(_APPROX_BYTES)
    if (_APPROX_BYTES or 0) > max_bytes():
        _evict_to_budget()


def _scan_entries() -> List[Tuple[str, int, float, float, dict]]:
    """[(path, size, mtime, compile_s, header)] for every complete
    entry currently in the store — the AOT ``.sailpc`` entries plus
    jax's own compilation-cache files under ``xla/`` (those carry no
    compile-time header; they evict first, cheapest assumed)."""
    out = []
    try:
        names = os.listdir(cache_dir())
    except OSError:
        return out
    now = time.time()
    for name in names:
        if name.startswith(".tmp-"):
            # a writer killed mid-store leaves its tmp file behind; no
            # live writer holds one longer than a serialize+write, so
            # anything old is garbage — reap it here (every budget /
            # stats scan) or the shared dir outgrows max_mb unseen
            path = os.path.join(cache_dir(), name)
            try:
                if now - os.stat(path).st_mtime > _TMP_REAP_S:
                    os.unlink(path)
            except OSError:
                pass
            continue
        if not name.endswith(_SUFFIX):
            continue
        path = os.path.join(cache_dir(), name)
        try:
            st = os.stat(path)
        except OSError:
            continue  # concurrently evicted
        header = _read_header(path) or {}
        out.append((path, st.st_size, st.st_mtime,
                    float(header.get("compile_s", 0.0)), header))
    xla_dir = os.path.join(cache_dir(), "xla")
    try:
        xla_names = os.listdir(xla_dir)
    except OSError:
        xla_names = []
    for name in xla_names:
        path = os.path.join(xla_dir, name)
        try:
            st = os.stat(path)
            if not os.path.isfile(path):
                continue
        except OSError:
            continue
        out.append((path, st.st_size, st.st_mtime, 0.0, {}))
    return out


def _evict_to_budget() -> None:
    """Drop entries until the store fits ``compile_cache.max_mb``.
    Eviction order is ascending observed compile time (cheap-to-
    recompile first — the profiler's accounting is the value model),
    oldest access breaking ties. Concurrent evictors racing on the same
    entry are harmless (ENOENT ignored)."""
    global _APPROX_BYTES
    entries = _scan_entries()
    total = sum(e[1] for e in entries)
    budget = max_bytes()
    if total > budget:
        for path, size, _mtime, _cs, _hdr in sorted(
                entries, key=lambda e: (e[3], e[2])):
            if total <= budget:
                break
            try:
                os.unlink(path)
            except OSError:
                continue
            total -= size
            _count("execution.compile.persistent_evict_count")
    with _LOCK:
        _APPROX_BYTES = max(0, total)
    _gauge_bytes(max(0, total))


# ---------------------------------------------------------------------------
# prewarm: persisted compile-time-saved ranking + startup AOT loading
# ---------------------------------------------------------------------------

def _manifest_path() -> str:
    return os.path.join(cache_dir(), "prewarm.json")


def _prewarm_conf() -> Tuple[bool, int, float, float]:
    """(enabled, top_n, budget_s, flush_interval_s) from
    ``compile_cache.prewarm.*``."""
    from ..config import get as config_get, truthy
    try:
        on = truthy("compile_cache.prewarm.enabled", default="true")
        top_n = max(0, int(config_get("compile_cache.prewarm.top_n", 32)))
        budget_s = max(0.0, float(config_get(
            "compile_cache.prewarm.budget_s", 5.0)))
        flush_s = max(0.5, float(config_get(
            "compile_cache.prewarm.flush_interval_s", 30.0)))
    except Exception:  # noqa: BLE001 — config trouble = prewarm off
        return False, 0, 0.0, 30.0
    return on, top_n, budget_s, flush_s


def _read_manifest() -> Dict[str, List]:
    """digest -> [hits, compile_s, site] merged across every process
    that ever flushed (best-effort: unreadable manifest = empty)."""
    if not enabled():
        return {}
    try:
        with open(_manifest_path(), "r", encoding="utf-8") as f:
            raw = json.load(f)
        return {str(d): [int(v[0]), float(v[1]), str(v[2])]
                for d, v in raw.items()}
    except (OSError, ValueError, TypeError, KeyError, IndexError):
        return {}


def _flush_tally() -> None:
    """Merge this process's unflushed hit deltas into the on-disk
    manifest (read-merge-replace under a tmp rename; concurrent
    flushers may lose each other's last delta — the ranking is
    advisory, not accounting)."""
    global _TALLY_LAST_FLUSH
    if not enabled():
        return
    with _LOCK:
        if not _TALLY_DELTA:
            _TALLY_LAST_FLUSH = time.time()
            return
        delta = {d: list(v) for d, v in _TALLY_DELTA.items()}
        _TALLY_DELTA.clear()
        _TALLY_LAST_FLUSH = time.time()
    merged = _read_manifest()
    for d, (hits, compile_s, site) in delta.items():
        cur = merged.get(d)
        if cur is None:
            merged[d] = [hits, compile_s, site]
        else:
            cur[0] += hits
            cur[1] = max(cur[1], compile_s)
    if len(merged) > _MANIFEST_MAX:
        ranked = sorted(merged.items(), key=lambda kv: -kv[1][0] * kv[1][1])
        merged = dict(ranked[:_MANIFEST_MAX])
    path = _manifest_path()
    tmp = f"{path}.tmp-{os.getpid()}-{threading.get_ident()}"
    try:
        os.makedirs(cache_dir(), exist_ok=True)
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(merged, f, separators=(",", ":"))
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def _maybe_flush_tally() -> None:
    on, _top, _budget, flush_s = _prewarm_conf()
    if not on:
        return
    if time.time() - _TALLY_LAST_FLUSH >= flush_s:
        _flush_tally()


def _merged_tally() -> Dict[str, List]:
    """Manifest ⊕ this process's unflushed deltas — the ranking
    ``top_by_saved`` and the prewarm loader both consume, so the view
    survives restarts."""
    merged = _read_manifest()
    with _LOCK:
        for d, (hits, compile_s, site) in _TALLY_DELTA.items():
            cur = merged.get(d)
            if cur is None:
                merged[d] = [hits, compile_s, site]
            else:
                cur[0] += hits
                cur[1] = max(cur[1], compile_s)
    return merged


def prewarm() -> Tuple[int, int]:
    """AOT-load the top-N manifest programs by compile-time saved into
    :data:`_PRELOADED` (budget-bounded wall time). Returns
    ``(loaded, skipped)`` and records
    ``execution.compile.prewarm_{loaded,skipped}_count``."""
    on, top_n, budget_s, _flush = _prewarm_conf()
    if not on or not enabled() or top_n <= 0:
        return 0, 0
    ranked = sorted(_merged_tally().items(),
                    key=lambda kv: -kv[1][0] * kv[1][1])
    loaded = skipped = 0
    deadline = time.monotonic() + budget_s
    for i, (digest, (_hits, _cs, site)) in enumerate(ranked):
        if i >= top_n or time.monotonic() > deadline:
            skipped += len(ranked) - i
            break
        with _LOCK:
            already = digest in _PRELOADED
        if already:
            continue
        fn, reason = _load(digest, site=str(site), _tally=False)
        if fn is None:
            skipped += 1
            continue
        with _LOCK:
            _PRELOADED[digest] = fn
        loaded += 1
    if loaded:
        _count("execution.compile.prewarm_loaded_count", loaded)
    if skipped:
        _count("execution.compile.prewarm_skipped_count", skipped)
    return loaded, skipped


def start_prewarm(wait: bool = False) -> None:
    """Session/cluster-startup hook: run :func:`prewarm` once per
    process on a background daemon thread (startup latency unaffected);
    ``wait=True`` runs it inline (tests, bench)."""
    global _PREWARM_STARTED
    on, top_n, _budget, _flush = _prewarm_conf()
    if not on or not enabled() or top_n <= 0:
        return
    with _LOCK:
        if _PREWARM_STARTED:
            return
        _PREWARM_STARTED = True
    if not os.path.exists(_manifest_path()):
        return  # nothing ranked yet: skip the thread entirely
    if wait:
        prewarm()
        return
    t = threading.Thread(target=prewarm, name="sail-pcache-prewarm",
                         daemon=True)
    t.start()


def stats(top_n: int = 10) -> dict:
    """Store snapshot for ``/debug/compile_cache``: entry count, bytes,
    this process's hit tally, and the top-N entries by compile time
    saved (hits × the compile seconds the entry's header records).
    Never serializes configuration or environment values beyond the
    cache directory path itself."""
    entries = _scan_entries()
    with _LOCK:
        process_hits = sum(v[0] for v in _HIT_TALLY.values())
        preloaded = len(_PRELOADED)
    tally = _merged_tally()
    top = sorted(
        ({"digest": d[:16], "hits": v[0],
          "compile_s": round(v[1], 4), "site": v[2],
          "saved_s": round(v[0] * v[1], 4)}
         for d, v in tally.items()),
        key=lambda e: -e["saved_s"])[:max(0, top_n)]
    return {
        "enabled": enabled(),
        "dir": cache_dir(),
        "entries": len(entries),
        "bytes": sum(e[1] for e in entries),
        "max_mb": _conf()[2],
        "process_hits": process_hits,
        "prewarm_preloaded": preloaded,
        "top_by_saved": top,
    }


def clear() -> None:
    """Wipe the store, poison markers included (tests / bench resets)."""
    for path, _s, _m, _c, _h in _scan_entries():
        try:
            os.unlink(path)
        except OSError:
            pass
    try:
        for name in os.listdir(cache_dir()):
            if name.endswith(".bad") or name.startswith(".tmp-"):
                try:
                    os.unlink(os.path.join(cache_dir(), name))
                except OSError:
                    pass
    except OSError:
        pass
    try:
        os.unlink(_manifest_path())
    except OSError:
        pass
    global _APPROX_BYTES
    with _LOCK:
        _APPROX_BYTES = None
        _HIT_TALLY.clear()
        _TALLY_DELTA.clear()
        _PRELOADED.clear()


# ---------------------------------------------------------------------------
# the per-program wrapper installed by the executors
# ---------------------------------------------------------------------------

def _has_host_callback(lowered) -> bool:
    """True when the lowered module embeds a host python callback
    (pure_callback UDFs): its custom-call handle is process-local, so
    the executable must never be persisted."""
    try:
        return "callback" in lowered.as_text()
    except Exception:  # noqa: BLE001 — undeterminable: do not persist
        return True


class PersistentProgram:
    """Shape-dispatching callable over one structural cache key.

    First call per argument signature: try the on-disk store
    (load-before-trace); on miss, AOT-compile
    (``jit(fn).lower(args).compile()`` — the same trace+compile a plain
    ``jax.jit`` first call pays, timed and charged identically) and
    persist the executable. Subsequent calls dispatch straight to the
    bound executable. Lives inside the in-memory operator cache, so the
    hot path (in-memory hit) never touches this class's slow paths."""

    __slots__ = ("_fn", "_key", "_key_repr", "_dict_objs", "_fused",
                 "_site", "_per_sig", "_dict_digest", "_jit_fallback",
                 "_fast")

    def __init__(self, fn, key, dict_objs: Tuple, fused: bool = False,
                 site: str = "op"):
        self._fn = fn
        self._key = key
        self._key_repr = repr(key)
        self._dict_objs = tuple(dict_objs)
        self._fused = fused
        self._site = site
        self._per_sig: Dict = {}
        self._dict_digest: Optional[str] = ""   # "" = not yet computed
        self._jit_fallback = None
        # single-signature fast path: once exactly one signature is
        # bound, calls dispatch straight to its executable (which
        # validates input avals itself) without recomputing the
        # abstract signature per call
        self._fast = None

    def _digest_base(self) -> Optional[str]:
        if self._dict_digest == "":
            self._dict_digest = content_digest(self._dict_objs)
        return self._dict_digest

    def _jit(self):
        """Plain-jit fallback for signatures that cannot persist (the
        exact pre-cache behavior, compile-timing included)."""
        if self._jit_fallback is None:
            import jax
            from .local import _compile_timed
            self._jit_fallback = _compile_timed(
                jax.jit(self._fn), self._key, fused=self._fused)
        return self._jit_fallback

    def _bind(self, sig, args):
        import jax

        from .. import profiler
        from ..metrics import timer as _metric_timer
        from . import retrace

        digest = None
        reason = None
        if sig is not None and self._digest_base() is not None:
            digest = entry_digest(self._key_repr, self._dict_digest, sig)
        if digest is not None:
            with _LOCK:
                pre = _PRELOADED.pop(digest, None)
            if pre is not None:
                # prewarmed: first traffic pays neither compile nor a
                # disk read; counted as a persistent hit so ratios and
                # the saved-time ranking stay honest
                _count("execution.compile.persistent_hit_count")
                _note_profile(True, 0.0)
                with _LOCK:
                    t = _HIT_TALLY.setdefault(digest, [0, 0.0, self._site])
                    t[0] += 1
                    d = _TALLY_DELTA.setdefault(digest,
                                                [0, 0.0, self._site])
                    d[0] += 1
                retrace.LEDGER.note_digest(digest)
                retrace.LEDGER.note_bound(self._key, sig)
                return pre
            loaded, reason = _load(digest, site=self._site)
            if loaded is not None:
                # bound without compiling: remember the signature (and
                # that this process held the digest) so a later
                # recompile attributes as an eviction, not a cold miss
                retrace.LEDGER.note_digest(digest)
                retrace.LEDGER.note_bound(self._key, sig)
                return loaded
        elif enabled():
            # unpersistable program (identity key / opaque host data):
            # count the consult so hit ratios stay honest
            _count("execution.compile.persistent_miss_count")
            _note_profile(False)
        with _metric_timer("execution.fusion.compile_time"
                           if self._fused else None) as tm:
            lowered = jax.jit(self._fn).lower(*args)
            compiled = lowered.compile()
        key_repr = repr(self._key[0]) if isinstance(self._key, tuple) \
            and self._key else self._key_repr
        profiler.note_compile_time(tm.elapsed_s, key=key_repr)
        retrace.attribute(self._key, sig, tm.elapsed_s, site="pcache",
                          pcache_reason=reason, digest=digest)
        if digest is not None and not _has_host_callback(lowered):
            if store(digest, compiled, tm.elapsed_s, site=self._site):
                retrace.LEDGER.note_digest(digest)
        return compiled

    def __call__(self, *args):
        fast = self._fast
        if fast is not None:
            try:
                return fast(*args)
            except (TypeError, ValueError):
                # aval mismatch (new shape) — or a genuine error from
                # the program, which the slow path re-raises by
                # dispatching to the same executable
                pass
        sig = signature(args)
        entry = self._per_sig.get(sig)
        if entry is None:
            if sig is None or len(self._per_sig) >= _MAX_SIGS:
                return self._jit()(*args)
            entry = self._bind(sig, args)
            self._per_sig[sig] = entry
        self._fast = entry if len(self._per_sig) == 1 else None
        return entry(*args)


def wrap(fn, key, dict_objs: Tuple, fused: bool = False,
         site: str = "op"):
    """Executor hook: persistent-cache-aware compiled program when the
    store is enabled, else None (caller keeps the plain jit path)."""
    if not enabled() or key is None:
        return None
    return PersistentProgram(fn, key, dict_objs, fused=fused, site=site)
