"""Retrace forensics: typed attribution of every compile miss.

ROADMAP item 2's question — "retraces-per-minute, by cause" — needs
every trace+compile the process pays to say WHY it happened. Following
Flare's thesis that compiled-program churn is the serving tail's
dominant cost (arXiv:1703.08219), this module keeps a bounded
per-program-fingerprint ledger fed from the two compile decision sites
(``exec/local.py:_compile_timed`` for the plain-jit path,
``exec/pcache.py:PersistentProgram._bind`` for the persistent store)
and classifies each miss into one of :data:`events.RETRACE_CAUSES`:

- ``first-ever`` — this process never compiled the program fingerprint
  (the benign cold compile; counted so rates stay honest, but EXPLAIN
  and the anomaly classifier exclude it from "retraces");
- ``new-aval-signature`` — a genuinely new argument structure/dtype/
  shape for a known program;
- ``capacity-bucket`` — the signature matches a previously-compiled one
  except in leading (padded row-capacity) dimensions: the
  ``round_capacity`` churn item 2 blames for the continuous-join p99;
- ``eviction`` — this exact signature compiled before in-process, so
  the in-memory operator cache (or jit cache it anchored) dropped it;
- ``pcache-eviction`` / ``pcache-poison`` / ``env-skew`` — the
  persistent store had (or refused) the entry, by load reason.

Every attribution fans out to the flight recorder (``retrace`` event),
the metric plane (``execution.compile.retrace_count{cause}``), and the
active query profile (the ``retraces:`` EXPLAIN ANALYZE line) — one
classification, three surfaces, replayable from the durable log alone.
The ``slo-taxonomy`` lint pins the cause literals here to the declared
tuple in events.py.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

__all__ = [
    "program_fingerprint", "sig_invariant", "RetraceLedger", "LEDGER",
    "attribute", "clear",
]


def program_fingerprint(key) -> str:
    """Stable (within-process) identity of one compiled program: the
    structural cache key's repr, hashed. Identity-bearing reprs
    (" at 0x") are fine here — the ledger is process-local; only the
    pcache digest needs cross-process stability."""
    return hashlib.sha256(repr(key).encode()).hexdigest()[:16]


def sig_invariant(sig) -> Optional[str]:
    """The signature with every array leaf's LEADING dimension erased —
    two signatures sharing an invariant differ only in padded row
    capacity (``columnar.batch.round_capacity`` bucket churn), the
    capacity-bucket retrace cause."""
    if sig is None:
        return None
    try:
        treedef, leaves = sig
        inv = []
        for leaf in leaves:
            if leaf and isinstance(leaf[0], tuple) and len(leaf) == 3:
                shape, dtype, weak = leaf
                inv.append((len(shape), tuple(shape[1:]), dtype, weak))
            else:
                inv.append(leaf)
        return repr((treedef, tuple(inv)))
    except Exception:  # noqa: BLE001 — unshaped signature: no invariant
        return None


class _Program:
    """Ledger state for one program fingerprint."""

    __slots__ = ("fp", "key_repr", "sigs", "invariants", "causes",
                 "first_ts", "last_ts", "compiles", "evictions")

    def __init__(self, fp: str, key_repr: str):
        self.fp = fp
        self.key_repr = key_repr
        self.sigs: set = set()
        self.invariants: set = set()
        self.causes: Dict[str, int] = {}
        self.first_ts = time.time()
        self.last_ts = self.first_ts
        self.compiles = 0
        self.evictions = 0


class RetraceLedger:
    """Bounded LRU of per-program compile history + the process's
    known-pcache-digest set. All mutation under one lock — compile
    sites run on worker threads concurrently."""

    MAX_PROGRAMS = 512
    MAX_RECENT = 1024
    MAX_DIGESTS = 4096
    _KEY_CHARS = 160   # key reprs can be whole plan structures

    def __init__(self):
        self._lock = threading.Lock()
        self._programs: "OrderedDict[str, _Program]" = OrderedDict()
        self._digests: set = set()
        self._recent: deque = deque(maxlen=self.MAX_RECENT)
        self._totals: Dict[str, int] = {}

    # -- bookkeeping -----------------------------------------------------
    def _entry(self, fp: str, key_repr: str) -> _Program:
        # under self._lock
        e = self._programs.get(fp)
        if e is None:
            e = _Program(fp, key_repr[:self._KEY_CHARS])
            while len(self._programs) >= self.MAX_PROGRAMS:
                self._programs.popitem(last=False)
            self._programs[fp] = e
        else:
            self._programs.move_to_end(fp)
        return e

    def note_digest(self, digest: Optional[str]) -> None:
        """A pcache digest this process stored or loaded — its later
        absence from the store is a pcache eviction, not a cold miss."""
        if not digest:
            return
        with self._lock:
            if len(self._digests) >= self.MAX_DIGESTS:
                self._digests.clear()
            self._digests.add(digest)

    def digest_known(self, digest: Optional[str]) -> bool:
        if not digest:
            return False
        with self._lock:
            return digest in self._digests

    def note_bound(self, key, sig) -> None:
        """A program bound WITHOUT compiling (pcache load hit): remember
        the signature so a later recompile of it reads as eviction, not
        first-ever."""
        fp = program_fingerprint(key)
        sig_repr = repr(sig) if sig is not None else None
        inv = sig_invariant(sig)
        with self._lock:
            e = self._entry(fp, repr(key))
            if sig_repr is not None:
                e.sigs.add(sig_repr)
            if inv is not None:
                e.invariants.add(inv)

    def note_eviction(self, key) -> None:
        """The in-memory operator cache dropped this key's entry
        (observability only — classification derives eviction from the
        signature history, which survives the drop)."""
        fp = program_fingerprint(key)
        with self._lock:
            e = self._programs.get(fp)
            if e is not None:
                e.evictions += 1

    # -- classification --------------------------------------------------
    def classify_memory(self, fp: str, sig) -> str:
        """Attribute an in-memory compile miss from the signature
        history alone. Caller must not have noted ``sig`` yet."""
        sig_repr = repr(sig) if sig is not None else None
        inv = sig_invariant(sig)
        with self._lock:
            e = self._programs.get(fp)
            if e is None or e.compiles == 0 and not e.sigs:
                return "first-ever"
            if sig_repr is not None and sig_repr in e.sigs:
                return "eviction"
            if inv is not None and inv in e.invariants:
                return "capacity-bucket"
            return "new-aval-signature"

    def classify_pcache(self, fp: str, sig, reason: Optional[str],
                        digest: Optional[str]) -> str:
        """Attribute a persistent-store miss: the load reason wins when
        it names the store itself; an absent entry this process once
        held is a store eviction; otherwise fall back to the in-memory
        history (a cold store says nothing beyond it)."""
        if reason == "poison":
            return "pcache-poison"
        if reason == "skew":
            return "env-skew"
        if reason == "error":
            return "pcache-eviction"
        if reason == "absent" and self.digest_known(digest):
            return "pcache-eviction"
        return self.classify_memory(fp, sig)

    # -- the one entry point compile sites call --------------------------
    def attribute(self, key, sig, seconds: float, site: str,
                  pcache_reason: Optional[str] = None,
                  digest: Optional[str] = None) -> str:
        """Classify one compile, update the ledger, and fan the
        attribution out to the event log, the metric plane, and the
        active query profile. Returns the cause."""
        fp = program_fingerprint(key)
        if pcache_reason is not None or digest is not None:
            cause = self.classify_pcache(fp, sig, pcache_reason, digest)
        else:
            cause = self.classify_memory(fp, sig)
        ts = time.time()
        sig_repr = repr(sig) if sig is not None else None
        inv = sig_invariant(sig)
        key_repr = repr(key)
        with self._lock:
            e = self._entry(fp, key_repr)
            e.compiles += 1
            e.last_ts = ts
            e.causes[cause] = e.causes.get(cause, 0) + 1
            self._totals[cause] = self._totals.get(cause, 0) + 1
            if sig_repr is not None:
                e.sigs.add(sig_repr)
            if inv is not None:
                e.invariants.add(inv)
            self._recent.append(
                {"ts": ts, "fp": fp, "cause": cause,
                 "ms": round(seconds * 1000.0, 3), "site": site,
                 "key": key_repr[:self._KEY_CHARS]})
        ms = round(seconds * 1000.0, 3)
        try:
            from .. import events
            events.emit(events.EventType.RETRACE,
                        key=key_repr[:self._KEY_CHARS], fp=fp,
                        cause=cause, ms=ms, site=site)
        except Exception:  # noqa: BLE001 — forensics never break compile
            pass
        try:
            from ..metrics import record as _record_metric
            _record_metric("execution.compile.retrace_count", 1,
                           cause=cause)
        except Exception:  # noqa: BLE001
            pass
        try:
            from .. import profiler
            profiler.note_retrace(cause, seconds)
        except Exception:  # noqa: BLE001
            pass
        return cause

    # -- surfaces --------------------------------------------------------
    def totals(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._totals)

    def recent(self) -> List[dict]:
        with self._lock:
            return list(self._recent)

    def snapshot(self) -> List[dict]:
        """One row per (program fingerprint, cause) for
        ``system.telemetry.retraces``."""
        rows: List[dict] = []
        with self._lock:
            for e in self._programs.values():
                for cause, n in sorted(e.causes.items()):
                    rows.append({
                        "fingerprint": e.fp, "key": e.key_repr,
                        "cause": cause, "count": int(n),
                        "signatures": len(e.sigs),
                        "evictions": int(e.evictions),
                        "first_ts": e.first_ts, "last_ts": e.last_ts})
        return rows

    def clear(self) -> None:
        with self._lock:
            self._programs.clear()
            self._digests.clear()
            self._recent.clear()
            self._totals.clear()


LEDGER = RetraceLedger()


def attribute(key, sig, seconds: float, site: str,
              pcache_reason: Optional[str] = None,
              digest: Optional[str] = None) -> str:
    """Module-level convenience over the process ledger."""
    return LEDGER.attribute(key, sig, seconds, site,
                            pcache_reason=pcache_reason, digest=digest)


def clear() -> None:
    LEDGER.clear()
