"""Execution: local executor now; distributed driver/worker/shuffle layers
on top (reference role: sail-execution)."""
