"""Result + materialized-fragment cache and continuously-maintained views.

The serving workload this targets is thousands of near-identical
dashboard queries over slowly-changing tables: with the AOT program
cache (pcache) hot, first-scan decode/upload dominates cold latency.
Three reuse tiers sit above the scan path:

- **result tier** (``ResultCache``): whole-query results keyed by
  ``plan_fingerprint`` (plan/stages.py) + a *version vector* over every
  scanned table — Delta log versions and file mtimes give precise
  invalidation for lakehouse tables, a DML-bumped counter versions
  memory tables. A hit skips resolution's downstream entirely (local,
  mesh and cluster paths alike).
- **fragment tier** (``FragmentCache``): decoded, device-resident scan
  batches — the successor of exec/local.py's ``_SCAN_CACHE`` — with
  byte-budgeted, cost-weighted eviction mirroring pcache's
  compile-time-weighted scheme (evict ascending (decode cost, last
  access): cheapest-to-rebuild, coldest first). Fragment stores feed
  ``join_reorder.note_observed_rows`` so AQE/join ordering treat cached
  fragments as grounded, observed-exact inputs.
- **view tier** (``MaterializedViewManager``): ``CACHE MATERIALIZED``
  declares a defining query a continuously-maintained view. Base-table
  DML folds change deltas through the incremental keyed-state store
  (streaming_state.KeyedStateStore — the PR 15 machinery) into the
  cached fragment at marker cadence; non-mergeable plans fall back to
  full recompute per marker. Reads resolve against the materialized
  memory table and never rescan base data.

Invalidation contract: ``bump_table_version`` is the single hook every
write path calls (memory DML via ``Session._table_mutated``, Delta
``Transaction.commit``, Iceberg metadata writes). It versions the
table, drops file-listing cache entries for the written root, evicts
dependent result/fragment entries, and triggers view maintenance.

Staleness soundness: memory tables are snapshot-by-identity (DML
replaces ``entry.data`` wholesale; cached entries pin the old object,
so an id match implies the exact snapshot), Delta versions are
monotonic and read at probe time. A store racing a commit can only
serve data *fresher* than its key claims — never stale.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from typing import Dict, FrozenSet, List, NamedTuple, Optional, Tuple

import pyarrow as pa

from ..metrics import record as _record_metric

# ---------------------------------------------------------------------------
# table-version registry
# ---------------------------------------------------------------------------

_VERSIONS_LOCK = threading.Lock()
_TABLE_VERSIONS: Dict[str, int] = {}


def memory_table_key(name) -> str:
    """Dependency key for a memory table (dotted name, lowercased)."""
    if isinstance(name, (tuple, list)):
        name = ".".join(str(p) for p in name)
    return "mem:" + str(name).lower()


def entry_table_key(entry) -> Tuple[str, Optional[str]]:
    """``(dependency key, filesystem root)`` for a catalog TableEntry.
    Path-backed tables key on their root path (shared with the Delta/
    Iceberg commit hooks); memory tables on their dotted name."""
    if entry.paths:
        root = entry.paths[0]
        return root, root
    return memory_table_key(entry.name), None


def table_version(key: str) -> int:
    with _VERSIONS_LOCK:
        return _TABLE_VERSIONS.get(key, 0)


def bump_table_version(key: str, root: Optional[str] = None) -> None:
    """The write hook: version the table, clear file listings for the
    written root (nested partition-directory adds would otherwise ride
    out the listing TTL), and proactively evict dependent entries."""
    with _VERSIONS_LOCK:
        _TABLE_VERSIONS[key] = _TABLE_VERSIONS.get(key, 0) + 1
    if root is not None:
        from ..io.cache import invalidate_listings
        invalidate_listings(root)
    RESULT_CACHE.invalidate_table(key)
    FRAGMENT_CACHE.invalidate_table(key)


# ---------------------------------------------------------------------------
# cacheability probe
# ---------------------------------------------------------------------------

#: scalar functions whose value depends on execution time, process
#: state or an RNG drawn at EXECUTION time (exec/host_interp.py) — a
#: result-cache hit would freeze them, so plans calling any are
#: uncacheable. ``__pyudf`` covers arbitrary Python UDFs.
NONDETERMINISTIC_FNS = frozenset({
    "rand", "randn", "random", "uuid", "shuffle",
    "now", "current_timestamp", "localtimestamp", "current_date",
    "current_timezone", "unix_timestamp",
    "monotonically_increasing_id", "spark_partition_id",
    "input_file_name", "__pyudf",
})


def _value_nondeterministic(value) -> bool:
    """Walk a plan-node field value's Rex trees for nondeterministic
    calls. PlanNode children are skipped — walk_plan visits those."""
    from ..plan import nodes as pn
    from ..plan import rex as rx
    stack = [value]
    while stack:
        v = stack.pop()
        if isinstance(v, pn.PlanNode):
            continue
        if isinstance(v, rx.RCall) and \
                str(v.fn).lower() in NONDETERMINISTIC_FNS:
            return True
        if isinstance(v, (tuple, list)):
            stack.extend(v)
        elif dataclasses.is_dataclass(v) and not isinstance(v, type):
            for f in dataclasses.fields(v):
                stack.append(getattr(v, f.name))
    return False


def plan_deterministic(node) -> bool:
    from ..plan import nodes as pn
    for n in pn.walk_plan(node):
        for f in dataclasses.fields(n):
            if _value_nondeterministic(getattr(n, f.name)):
                return False
    return True


def _scan_leaf_version(scan) -> Optional[Tuple[str, tuple]]:
    """``(dependency key, version-vector part)`` for one ScanExec leaf,
    or ``None`` when the leaf makes the plan uncacheable (user python
    data sources, system tables materialized fresh per resolve)."""
    import os
    if scan.format == "python_ds":
        return None
    if scan.source is not None:
        if not scan.table_name:
            # system tables: a fresh pa.Table per resolve, no identity
            return None
        key = memory_table_key(scan.table_name)
        return key, ("mem", key, id(scan.source), table_version(key))
    if not scan.paths:
        return None
    root = scan.paths[0]
    if scan.format == "delta":
        try:
            from ..lakehouse.delta import DeltaLog
            ver = DeltaLog(root).latest_version()
        except Exception:  # noqa: BLE001 — unreadable log: don't cache
            return None
        return root, ("delta", root, ver, table_version(root))
    try:
        from ..io.formats import expand_paths
        files = tuple(expand_paths(scan.paths))
        mtimes = tuple(int(os.path.getmtime(f) * 1e6) for f in files)
    except Exception:  # noqa: BLE001 — unlistable paths: don't cache
        return None
    return root, ("file", files, mtimes, table_version(root))


class CacheProbe(NamedTuple):
    """A cacheable resolved plan: the full cache key (fingerprint +
    version vector + session knobs), the table keys the entry depends
    on, and the memory-table objects to pin and identity-verify."""

    key: tuple
    depends: FrozenSet[str]
    sources: Tuple[object, ...]


def probe(node, session_key: tuple = ()) -> Optional[CacheProbe]:
    """Classify a RESOLVED plan for result caching. ``None`` means
    uncacheable: no scans (constant plans are cheap), a nondeterministic
    expression, an unversionable leaf, or an unhashable fingerprint."""
    from ..plan import nodes as pn
    from ..plan.stages import plan_fingerprint
    scans = [n for n in pn.walk_plan(node) if isinstance(n, pn.ScanExec)]
    if not scans:
        return None
    if not plan_deterministic(node):
        return None
    depends = set()
    versions = []
    for s in scans:
        leaf = _scan_leaf_version(s)
        if leaf is None:
            return None
        dep, part = leaf
        depends.add(dep)
        versions.append(part)
    try:
        fp_key, sources = plan_fingerprint(node)
        full = (fp_key, tuple(versions), tuple(session_key))
        hash(full)
    except Exception:  # noqa: BLE001 — unhashable fingerprint
        return None
    return CacheProbe(full, frozenset(depends), tuple(sources))


# ---------------------------------------------------------------------------
# result tier
# ---------------------------------------------------------------------------

_FRAGMENT_IDS = itertools.count(1)


def _budget_bytes(value, default_mb: float) -> int:
    try:
        return int(float(value) * 1024 * 1024)
    except (TypeError, ValueError):
        return int(default_mb * 1024 * 1024)


@dataclasses.dataclass
class _ResultEntry:
    fragment_id: str
    key: tuple
    table: pa.Table
    sources: Tuple[object, ...]
    depends: FrozenSet[str]
    nbytes: int
    build_ms: float
    created: float
    last_access: float
    hits: int = 0


class ResultCache:
    """Whole-query results keyed by ``CacheProbe.key``. Byte-budgeted
    (``cache.result.max_mb``); eviction ascending (build cost, last
    access) — the pcache compile-time-weighted precedent."""

    tier = "result"

    def __init__(self, max_mb: Optional[float] = None):
        self._lock = threading.Lock()
        self._entries: Dict[tuple, _ResultEntry] = {}
        self._max_mb = max_mb
        self._budget_cached: Optional[int] = None

    def _budget(self) -> int:
        if self._max_mb is not None:
            return _budget_bytes(self._max_mb, 256)
        if self._budget_cached is None:
            from ..config import get as config_get
            self._budget_cached = _budget_bytes(
                config_get("cache.result.max_mb", 256), 256)
        return self._budget_cached

    def _verify(self, e: Optional[_ResultEntry],
                p: CacheProbe) -> Optional[_ResultEntry]:
        if e is None or len(e.sources) != len(p.sources):
            return None
        if not all(a is b for a, b in zip(e.sources, p.sources)):
            return None
        return e

    def lookup(self, p: CacheProbe) -> Optional[_ResultEntry]:
        with self._lock:
            e = self._verify(self._entries.get(p.key), p)
            if e is not None:
                e.hits += 1
                e.last_access = time.time()
        if e is None:
            _record_metric("execution.result_cache.miss_count", 1,
                           tier="result")
            return None
        _record_metric("execution.result_cache.hit_count", 1,
                       tier="result")
        _record_metric("execution.result_cache.bytes_served", e.nbytes,
                       tier="result")
        return e

    def peek(self, p: CacheProbe) -> Optional[_ResultEntry]:
        """Non-counting lookup for EXPLAIN: no hit bump, no metrics."""
        with self._lock:
            return self._verify(self._entries.get(p.key), p)

    def store(self, p: CacheProbe, table: pa.Table,
              build_ms: float) -> Optional[_ResultEntry]:
        try:
            nbytes = int(table.nbytes)
        except Exception:  # noqa: BLE001 — size is advisory
            nbytes = 0
        budget = self._budget()
        if budget <= 0 or nbytes > budget // 4:
            # dashboard results are small; one bulk export must not
            # churn the whole tier
            return None
        now = time.time()
        e = _ResultEntry("rc-%d" % next(_FRAGMENT_IDS), p.key, table,
                         p.sources, p.depends, nbytes, build_ms, now, now)
        with self._lock:
            self._entries[p.key] = e
            evicted = self._evict_over_budget(budget, keep=p.key)
        if evicted:
            _record_metric("execution.result_cache.evicted_count",
                           evicted, tier="result")
        return e

    def _evict_over_budget(self, budget: int, keep: tuple) -> int:
        total = sum(e.nbytes for e in self._entries.values())
        if total <= budget:
            return 0
        order = sorted(self._entries.values(),
                       key=lambda e: (e.build_ms, e.last_access))
        n = 0
        for e in order:
            if total <= budget:
                break
            if e.key == keep:
                continue
            del self._entries[e.key]
            total -= e.nbytes
            n += 1
        return n

    def invalidate_table(self, key: str) -> None:
        with self._lock:
            doomed = [k for k, e in self._entries.items()
                      if key in e.depends]
            for k in doomed:
                del self._entries[k]
        if doomed:
            _record_metric("execution.result_cache.invalidated_count",
                           len(doomed), tier="result")

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._budget_cached = None

    def snapshot(self) -> List[dict]:
        with self._lock:
            entries = list(self._entries.values())
        return [{"tier": "result", "id": e.fragment_id,
                 "key": repr(e.key[0])[:200],
                 "tables": sorted(e.depends),
                 "bytes": e.nbytes, "rows": e.table.num_rows,
                 "hit_count": e.hits, "cost_ms": e.build_ms,
                 "versions": repr(e.key[1]),
                 "last_access": e.last_access} for e in entries]


# ---------------------------------------------------------------------------
# fragment tier
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _FragmentEntry:
    fragment_id: str
    key: tuple
    source: Optional[object]       # memory-table pin, identity-verified
    batch: object                  # device-resident HostBatch
    rtf_stats: Optional[tuple]
    table_key: Optional[str]
    nbytes: int
    rows: int
    decode_ms: float
    created: float
    last_access: float
    hits: int = 0


class FragmentCache:
    """Decoded device-resident scan fragments, keyed by the scan cache
    key vocabulary of exec/local.py (_exec_ScanExec). Count-bounded by
    ``runtime.scan_cache_size`` (compat with the _SCAN_CACHE it
    replaces) and byte-budgeted by ``cache.fragment.max_mb`` with
    (decode cost, last access)-ascending eviction."""

    tier = "fragment"

    def __init__(self, max_mb: Optional[float] = None):
        self._lock = threading.Lock()
        self._entries: Dict[tuple, _FragmentEntry] = {}
        self._max_mb = max_mb
        self._budget_cached: Optional[int] = None
        self._count_cached: Optional[int] = None

    def _budget(self) -> int:
        if self._max_mb is not None:
            return _budget_bytes(self._max_mb, 8192)
        if self._budget_cached is None:
            from ..config import get as config_get
            self._budget_cached = _budget_bytes(
                config_get("cache.fragment.max_mb", 8192), 8192)
        return self._budget_cached

    def _count_bound(self) -> int:
        if self._count_cached is None:
            try:
                from ..config import get as config_get
                self._count_cached = max(
                    1, int(config_get("runtime.scan_cache_size", 64)))
            except (TypeError, ValueError, ImportError):
                self._count_cached = 64
        return self._count_cached

    def get(self, key: tuple, source) -> Optional[_FragmentEntry]:
        with self._lock:
            e = self._entries.get(key)
            if e is not None and source is not None \
                    and e.source is not source:
                e = None
            if e is not None:
                e.hits += 1
                e.last_access = time.time()
        if e is None:
            _record_metric("execution.result_cache.miss_count", 1,
                           tier="fragment")
            return None
        _record_metric("execution.result_cache.hit_count", 1,
                       tier="fragment")
        _record_metric("execution.result_cache.bytes_served", e.nbytes,
                       tier="fragment")
        return e

    def put(self, key: tuple, source, batch, rtf_stats, *,
            table_key: Optional[str] = None, nbytes: int = 0,
            rows: int = 0, decode_ms: float = 0.0) -> _FragmentEntry:
        now = time.time()
        e = _FragmentEntry("fg-%d" % next(_FRAGMENT_IDS), key, source,
                           batch, rtf_stats, table_key, int(nbytes),
                           int(rows), decode_ms, now, now)
        evicted = 0
        with self._lock:
            self._entries[key] = e
            while len(self._entries) > self._count_bound():
                victim = next(iter(self._entries))
                if victim == key:
                    break
                del self._entries[victim]
                evicted += 1
            budget = self._budget()
            if budget > 0:
                total = sum(x.nbytes for x in self._entries.values())
                if total > budget:
                    order = sorted(self._entries.values(),
                                   key=lambda x: (x.decode_ms,
                                                  x.last_access))
                    for x in order:
                        if total <= budget:
                            break
                        if x.key == key:
                            continue  # never the just-decoded fragment
                        del self._entries[x.key]
                        total -= x.nbytes
                        evicted += 1
        if evicted:
            _record_metric("execution.result_cache.evicted_count",
                           evicted, tier="fragment")
        return e

    def invalidate_table(self, key: str) -> None:
        with self._lock:
            doomed = [k for k, e in self._entries.items()
                      if e.table_key == key]
            for k in doomed:
                del self._entries[k]
        if doomed:
            _record_metric("execution.result_cache.invalidated_count",
                           len(doomed), tier="fragment")

    def drop_mem(self, table_id: int) -> None:
        """Drop entries pinning one memory table by id (chunked scans
        evict their slice entries to avoid pinning device memory)."""
        with self._lock:
            doomed = [k for k in self._entries
                      if k and k[0] == "mem" and k[1] == table_id]
            for k in doomed:
                del self._entries[k]

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._budget_cached = None
            self._count_cached = None

    def snapshot(self) -> List[dict]:
        with self._lock:
            entries = list(self._entries.values())
        return [{"tier": "fragment", "id": e.fragment_id,
                 "key": repr(e.key)[:200],
                 "tables": [e.table_key] if e.table_key else [],
                 "bytes": e.nbytes, "rows": e.rows,
                 "hit_count": e.hits, "cost_ms": e.decode_ms,
                 "versions": "", "last_access": e.last_access}
                for e in entries]


# ---------------------------------------------------------------------------
# view tier: continuously-maintained materialized views
# ---------------------------------------------------------------------------

def _collect_read_names(plan) -> List[Tuple[str, ...]]:
    from ..spec import plan as sp
    names: List[Tuple[str, ...]] = []
    stack = [plan]
    while stack:
        v = stack.pop()
        if isinstance(v, sp.ReadNamedTable):
            names.append(tuple(v.name))
        if isinstance(v, (tuple, list)):
            stack.extend(v)
        elif dataclasses.is_dataclass(v) and not isinstance(v, type):
            for f in dataclasses.fields(v):
                stack.append(getattr(v, f.name))
    return names


def _substitute_read(plan, name_lower: str, replacement):
    """Replace every ReadNamedTable of ``name_lower`` in a SPEC plan
    (mirrors streaming.py's _substitute_source, sans stream leaves)."""
    from ..spec import plan as sp
    if isinstance(plan, sp.ReadNamedTable) and plan.name \
            and plan.name[-1].lower() == name_lower:
        return replacement
    for f in (dataclasses.fields(plan)
              if dataclasses.is_dataclass(plan) else []):
        v = getattr(plan, f.name)
        if isinstance(v, sp.QueryPlan):
            plan = dataclasses.replace(plan, **{
                f.name: _substitute_read(v, name_lower, replacement)})
    return plan


def _schema_of(table: pa.Table):
    from ..spec import data_type as dt
    from ..columnar.arrow_interop import arrow_type_to_spec
    return dt.StructType(tuple(
        dt.StructField(n, arrow_type_to_spec(c.type), True)
        for n, c in zip(table.column_names, table.columns)))


@dataclasses.dataclass
class MaterializedView:
    name: str
    plan: object                        # defining spec QueryPlan
    entry: object                       # catalog TableEntry serving reads
    catalog: object                     # owning CatalogManager
    depends: FrozenSet[str]
    base_name: Optional[str] = None     # single base (incremental mode)
    spec: object = None                 # streaming_state.AggSpec or None
    store: object = None                # KeyedStateStore or None
    marker: int = 0


class MaterializedViewManager:
    """``CACHE MATERIALIZED`` views. Maintenance runs synchronously in
    the mutating session's DML path (markers = commits): mergeable
    single-base aggregates fold just the appended delta through a
    KeyedStateStore and re-run the cheap residual plan; everything else
    recomputes the defining query. Reads resolve against the
    materialized memory table (a TableEntry with data, no view_plan) and
    never rescan base tables."""

    def __init__(self):
        self._lock = threading.Lock()
        self._views: Dict[str, MaterializedView] = {}

    # -- registry ------------------------------------------------------
    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._views)

    def is_view(self, table_name) -> bool:
        if not table_name:
            return False
        name = str(table_name).split(".")[-1].lower()
        with self._lock:
            return name in self._views

    def get(self, name: str) -> Optional[MaterializedView]:
        with self._lock:
            return self._views.get(str(name).lower())

    # -- lifecycle -----------------------------------------------------
    def create(self, session, name: str, plan) -> MaterializedView:
        from ..catalog.manager import TableEntry
        from .. import streaming_state as ss
        name = str(name).lower()
        cm = session.catalog_manager
        depends = set()
        base_names = []
        for nm in _collect_read_names(plan):
            entry = cm.lookup_table(nm)
            if entry is None:
                raise ValueError(
                    f"CACHE MATERIALIZED {name}: unknown base table "
                    f"{'.'.join(nm)}")
            key, _root = entry_table_key(entry)
            depends.add(key)
            base_names.append(nm[-1].lower())
        if not depends:
            raise ValueError(
                f"CACHE MATERIALIZED {name}: defining query reads no "
                f"base table")
        from ..config import get as config_get
        incremental_ok = bool(config_get("cache.view.incremental", True)) \
            and len(set(base_names)) == 1
        spec = ss.analyze_plan(plan) if incremental_ok else None
        store = None
        table = None
        if spec is not None:
            try:
                store = ss.KeyedStateStore(spec.merge_kinds)
                partial = session._execute_query(spec.agg)
                store.merge_delta(partial)
                emit = store.to_table()
                table = session._execute_query(ss.substitute_node(
                    plan, spec.agg, _local_relation(emit)))
            except Exception:  # noqa: BLE001 — fall back to full mode
                spec, store, table = None, None, None
        if table is None:
            table = session._execute_query(plan)
        entry = TableEntry((name,), _schema_of(table), table, (),
                           "memory")
        view = MaterializedView(name, plan, entry, cm,
                                frozenset(depends),
                                base_names[0] if spec else None,
                                spec, store)
        with self._lock:
            self._views[name] = view
        # the entry goes straight into temp_views: register_temp_view
        # would set view_plan and reads would re-run the defining query
        cm.temp_views[name] = entry
        bump_table_version(memory_table_key(name))
        return view

    def drop(self, catalog_manager, name: str,
             if_exists: bool = False) -> bool:
        name = str(name).lower()
        with self._lock:
            view = self._views.pop(name, None)
        if view is None:
            if not if_exists:
                raise ValueError(f"materialized view not found: {name}")
            return False
        catalog_manager.temp_views.pop(name, None)
        bump_table_version(memory_table_key(name))
        return True

    def clear(self) -> None:
        with self._lock:
            views = list(self._views.values())
            self._views.clear()
        for v in views:
            v.catalog.temp_views.pop(v.name, None)

    # -- maintenance ---------------------------------------------------
    def dependents(self, key: str) -> List[MaterializedView]:
        with self._lock:
            return [v for v in self._views.values() if key in v.depends]

    def on_mutation(self, key: str, session, kind: str = "append",
                    delta: Optional[pa.Table] = None) -> None:
        """Fold one base-table change into every dependent view. Runs
        in the mutating thread BEFORE the DML statement returns, so a
        committed write is visible to view reads at the next marker."""
        for view in self.dependents(key):
            with self._lock:
                view.marker += 1
            mode = "full"
            table = None
            if view.spec is not None and kind == "append" \
                    and delta is not None:
                try:
                    table = self._fold_delta(session, view, delta)
                    mode = "incremental"
                except Exception:  # noqa: BLE001 — delta fold failed
                    table = None
            if table is None:
                table = self._recompute(session, view)
            view.entry.data = table
            view.entry.schema = _schema_of(table)
            bump_table_version(memory_table_key(view.name))
            _record_metric("execution.result_cache.view_refresh_count",
                           1, mode=mode)

    def _fold_delta(self, session, view, delta: pa.Table) -> pa.Table:
        from .. import streaming_state as ss
        agg = view.spec.agg
        below = _substitute_read(agg.input, view.base_name,
                                 _local_relation(delta))
        partial = session._execute_query(
            dataclasses.replace(agg, input=below))
        view.store.merge_delta(partial)
        emit = view.store.to_table()
        return session._execute_query(ss.substitute_node(
            view.plan, agg, _local_relation(emit)))

    def _recompute(self, session, view) -> pa.Table:
        from .. import streaming_state as ss
        table = session._execute_query(view.plan)
        if view.spec is not None:
            # rebuild the fold state so later appends can go back to
            # the incremental path
            try:
                store = ss.KeyedStateStore(view.spec.merge_kinds)
                store.merge_delta(session._execute_query(view.spec.agg))
                view.store = store
            except Exception:  # noqa: BLE001 — stay on full recompute
                view.spec, view.store = None, None
        return table


def _local_relation(table: pa.Table):
    from ..spec import plan as sp
    return sp.LocalRelation(table, _schema_of(table))


# ---------------------------------------------------------------------------
# process singletons + the session-facing write hook
# ---------------------------------------------------------------------------

RESULT_CACHE = ResultCache()
FRAGMENT_CACHE = FragmentCache()
VIEWS = MaterializedViewManager()


def result_cache_enabled(conf) -> bool:
    """Process default ``cache.result.enabled`` with the per-session
    ``spark.sail.cache.result.enabled`` mirror on top."""
    mirror = conf.get("spark.sail.cache.result.enabled") \
        if conf is not None else None
    if mirror is not None and str(mirror) != "":
        return str(mirror).strip().lower() in ("1", "true", "yes")
    from ..config import get as config_get
    return bool(config_get("cache.result.enabled", True))


def table_mutated(session, entry, kind: str = "append",
                  delta: Optional[pa.Table] = None) -> None:
    """Single entry point for every session-side write: bump the
    version (which also invalidates listings + cached entries), then
    fold the change into dependent materialized views."""
    key, root = entry_table_key(entry)
    bump_table_version(key, root=root)
    if VIEWS.is_view(entry.name[-1] if entry.name else None):
        return  # a direct write INTO a view: no self-maintenance
    if delta is not None:
        delta = _align_delta(entry, delta)
    VIEWS.on_mutation(key, session, kind=kind, delta=delta)


def _align_delta(entry, delta: pa.Table) -> Optional[pa.Table]:
    """Cast an appended slice to the base table's declared schema —
    INSERT literals keep their parsed types (a `7.0` is decimal) while
    the stored column may be double, and folding the raw slice through
    the view's aggregate would drift its output types. None (→ full
    recompute) when the slice cannot be aligned."""
    target = None
    if getattr(entry, "data", None) is not None:
        target = entry.data.schema
    elif getattr(entry, "schema", None) is not None:
        from ..columnar.arrow_interop import spec_type_to_arrow
        target = pa.schema([(f.name, spec_type_to_arrow(f.data_type))
                            for f in entry.schema.fields])
    if target is None:
        return delta
    try:
        return delta.select(target.names).cast(target)
    except Exception:  # noqa: BLE001 — shape mismatch: recompute instead
        return None


def clear_all() -> None:
    """CLEAR CACHE semantics for the reuse tiers (views stay registered
    — they are named objects dropped via UNCACHE MATERIALIZED)."""
    RESULT_CACHE.clear()
    FRAGMENT_CACHE.clear()
