"""Minimal actor framework.

Reference role: sail-server's Actor trait + single-threaded message loop
(crates/sail-server/src/actor.rs:14-99) — the concurrency model for the
driver and workers: all mutable state lives inside an actor and is touched
only by its own loop thread; everything else communicates via messages.
"""

from __future__ import annotations

import queue
import threading
import traceback
from typing import Any, Callable, Optional


class Actor:
    """Subclass and implement receive(message); spawn with ActorSystem."""

    def __init__(self):
        self._mailbox: "queue.Queue" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()
        self.handle = ActorHandle(self)

    # -- lifecycle -------------------------------------------------------
    def start(self, name: str = "actor"):
        self._thread = threading.Thread(target=self._loop, name=name, daemon=True)
        self._thread.start()
        return self.handle

    def stop(self, join: bool = True):
        self._stopped.set()
        self._mailbox.put(_Stop)
        if join and self._thread is not None and \
                self._thread is not threading.current_thread():
            self._thread.join(timeout=10)

    # -- override points -------------------------------------------------
    def receive(self, message: Any) -> None:
        raise NotImplementedError

    def on_start(self) -> None:
        pass

    def on_stop(self) -> None:
        pass

    # -- internals -------------------------------------------------------
    def _loop(self):
        try:
            self.on_start()
        except Exception:
            traceback.print_exc()
        while not self._stopped.is_set():
            msg = self._mailbox.get()
            if msg is _Stop:
                break
            try:
                self.receive(msg)
            except Exception:
                traceback.print_exc()
        try:
            self.on_stop()
        except Exception:
            traceback.print_exc()


class _Stop:
    pass


class ActorHandle:
    def __init__(self, actor: Actor):
        self._actor = actor

    def send(self, message: Any) -> None:
        self._actor._mailbox.put(message)

    def ask(self, make_message: Callable[["_Reply"], Any], timeout: float = 30.0):
        """Request/response over the mailbox: make_message receives a Reply
        sink to pass inside the message."""
        reply = _Reply()
        self._actor._mailbox.put(make_message(reply))
        return reply.get(timeout)


class _Reply:
    def __init__(self):
        self._q: "queue.Queue" = queue.Queue(maxsize=1)

    def set(self, value):
        self._q.put(value)

    def get(self, timeout: float):
        return self._q.get(timeout=timeout)
