"""Multi-tenant admission control: quotas, weighted-fair queuing, shedding.

Reference role: the arbitration layer Tailwind (arXiv:2604.28079) frames
as the contract of a practical-accelerator serving system — admission +
per-tenant quotas keep one workload from starving another — with
Theseus (arXiv:2508.05029) motivating that the scarce resource to
arbitrate is projected data movement, not task slots. Everything built
through PR 10 optimizes one query at a time; this module arbitrates
ACROSS concurrent queries and jobs:

- :class:`SessionAdmission` — the process-wide gate on the session
  query path (``SparkSession._execute_query``): per-tenant concurrent-
  query caps, an optional global cap, bounded wait queues with
  weighted-fair wake order (lowest virtual time ``served/weight``
  first, FIFO within a tenant), queue timeouts, and per-query
  deadlines. Overflow or timeout sheds with a typed, retryable
  :class:`ResourceExhausted` — never a hang.
- :class:`JobAdmissionQueue` — the cluster driver's cross-job fair
  queue: jobs (not just tasks) are scheduled under deficit-round-robin
  where a job's cost is its stage-launch opportunities (total task
  launches), so a heavy job consumes more of its tenant's share than a
  light one. Per-tenant running-job concurrency caps, a global cap (the
  shared resource the weights arbitrate), bounded per-tenant queues
  with deterministic shedding, and a per-tenant memory-quota ledger the
  driver debits with the PR 7 governor's per-task byte projections —
  which are AQE's observed channel sizes, so real sizes replace
  estimates as producers complete.

Every decision (enqueue/admit/defer/shed/quota debit/deadline cancel)
is deterministic given arrival order — sorted tenant iteration, FIFO
per-tenant queues, integer deficit arithmetic — and lands in the PR 10
flight recorder as typed events, replayable by scripts/sail_timeline.py.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from .. import events
from ..events import EventType
from ..metrics import record as _record_metric

DEFAULT_TENANT = "default"


# ---------------------------------------------------------------------------
# typed client-facing errors
# ---------------------------------------------------------------------------

class AdmissionError(RuntimeError):
    """Base of the typed admission-control errors. ``retryable`` tells
    the client whether backing off and resubmitting can succeed."""

    code = "ADMISSION"
    retryable = False

    def __init__(self, message: str, tenant: str = "",
                 retry_after_ms: int = 0):
        super().__init__(message)
        self.tenant = tenant
        self.retry_after_ms = int(retry_after_ms)


class ResourceExhausted(AdmissionError):
    """Deterministic load shed: the tenant's admission queue is full or
    the query waited out its queue budget. Retryable by contract — the
    request was never partially executed (no partial shuffle output, no
    side effects), so resubmitting after ``retry_after_ms`` is safe."""

    code = "RESOURCE_EXHAUSTED"
    retryable = True


class DeadlineExceeded(AdmissionError):
    """The query's deadline elapsed (in queue, or mid-execution via the
    driver's cancel path). Not retryable as-is: the same deadline would
    expire again."""

    code = "DEADLINE_EXCEEDED"
    retryable = False


# ---------------------------------------------------------------------------
# tenant policy
# ---------------------------------------------------------------------------

class TenantPolicy:
    """Per-tenant knobs, defaulted from the ``admission.*`` config and
    overridable per tenant through ``admission.tenants``."""

    __slots__ = ("weight", "max_jobs", "max_queries",
                 "memory_quota_bytes")

    def __init__(self, weight: int, max_jobs: int, max_queries: int,
                 memory_quota_bytes: int):
        self.weight = max(1, int(weight))
        self.max_jobs = max(0, int(max_jobs))          # 0 = unlimited
        self.max_queries = max(0, int(max_queries))    # 0 = unlimited
        self.memory_quota_bytes = max(0, int(memory_quota_bytes))


def _num(value, default, cast=int):
    try:
        return cast(value)
    except (TypeError, ValueError):
        return default


def parse_tenant_overrides(spec: str) -> Dict[str, Dict[str, int]]:
    """``admission.tenants`` grammar — semicolon-separated per-tenant
    override groups::

        name:weight=2,memMb=256,maxJobs=2,maxQueries=4;other:weight=1

    Unknown fields and malformed groups are ignored (config typos must
    not take the admission layer down)."""
    out: Dict[str, Dict[str, int]] = {}
    for group in (spec or "").split(";"):
        group = group.strip()
        if not group or ":" not in group:
            continue
        name, _, body = group.partition(":")
        name = name.strip()
        if not name:
            continue
        fields: Dict[str, int] = {}
        for pair in body.split(","):
            k, _, v = pair.partition("=")
            k = k.strip()
            if k in ("weight", "memMb", "maxJobs", "maxQueries"):
                parsed = _num(v.strip(), None)
                if parsed is not None:
                    fields[k] = parsed
        out[name] = fields
    return out


class AdmissionConfig:
    """One snapshot of every ``admission.*`` key (see
    config/application.yaml), read at gate/queue construction."""

    def __init__(self):
        from ..config import get as config_get
        from ..config import truthy
        self.enabled = truthy("admission.enabled")
        self.default_tenant = str(
            config_get("admission.tenant", DEFAULT_TENANT)
            or DEFAULT_TENANT)
        self.default_weight = max(1, _num(
            config_get("admission.default_weight", 1), 1))
        self.max_concurrent_queries = max(0, _num(
            config_get("admission.max_concurrent_queries", 8), 8))
        self.max_concurrent_total = max(0, _num(
            config_get("admission.max_concurrent_total", 0), 0))
        self.max_queued_queries = max(0, _num(
            config_get("admission.max_queued_queries", 64), 64))
        self.max_concurrent_jobs = max(0, _num(
            config_get("admission.max_concurrent_jobs", 4), 4))
        self.max_concurrent_jobs_total = max(0, _num(
            config_get("admission.max_concurrent_jobs_total", 8), 8))
        self.max_queued_jobs = max(0, _num(
            config_get("admission.max_queued_jobs", 32), 32))
        self.queue_timeout_ms = max(0, _num(
            config_get("admission.queue_timeout_ms", 30000), 30000))
        self.default_deadline_ms = max(0, _num(
            config_get("admission.default_deadline_ms", 0), 0))
        self.memory_quota_bytes = max(0, _num(
            config_get("admission.memory_quota_mb", 0), 0)) << 20
        self.overrides = parse_tenant_overrides(
            str(config_get("admission.tenants", "") or ""))

    def policy(self, tenant: str) -> TenantPolicy:
        o = self.overrides.get(tenant, {})
        return TenantPolicy(
            weight=o.get("weight", self.default_weight),
            max_jobs=o.get("maxJobs", self.max_concurrent_jobs),
            max_queries=o.get("maxQueries", self.max_concurrent_queries),
            memory_quota_bytes=(o["memMb"] << 20) if "memMb" in o
            else self.memory_quota_bytes)


# ---------------------------------------------------------------------------
# cluster driver: cross-job fair queue
# ---------------------------------------------------------------------------

class JobAdmissionQueue:
    """Driver-side job admission: bounded per-tenant FIFO queues drained
    by deficit-round-robin. Called ONLY from the driver actor thread
    (submit/report/probe/cleanup messages), so state needs no lock.

    A job's DRR cost is its stage-launch opportunities (the sum of
    ``num_partitions`` over non-driver stages): each admission debits
    the winning tenant's deficit by that many launches, and every
    admission opportunity credits each backlogged tenant its weight —
    so over time tenants receive stage-launch opportunities
    proportional to their weights."""

    def __init__(self, conf: Optional[AdmissionConfig] = None):
        self.conf = conf or AdmissionConfig()
        self.enabled = self.conf.enabled
        self._queues: Dict[str, Deque] = {}
        self._deficit: Dict[str, float] = {}
        self._running: Dict[str, set] = {}
        self._mem_used: Dict[str, int] = {}
        # (job_id, stage, partition) -> (tenant, bytes) for live debits
        self._debits: Dict[Tuple[str, int, int], Tuple[str, int]] = {}
        self._total_running = 0
        # long-lived (continuous) jobs: job_id -> [tenant, cost,
        # last_charge_ts]. A resident pipeline's DRR cost was charged
        # once at admit but its tasks occupy workers indefinitely —
        # recharge() re-debits the tenant's deficit every
        # resident_recharge_secs so it keeps paying for the occupancy
        self._resident: Dict[str, List] = {}
        # monotone per-tenant shed totals: the autoscaler's tick reads
        # these against a delta cursor for its weight-capped shed-rate
        # scale-up signal
        self.shed_totals: Dict[str, int] = {}
        from ..config import get as config_get
        self.resident_recharge_s = max(0.1, _num(
            config_get("admission.resident_recharge_secs", 10.0), 10.0,
            float))

    # -- helpers ---------------------------------------------------------
    @staticmethod
    def job_cost(job) -> int:
        launches = sum(s.num_partitions for s in job.graph.stages
                       if not s.on_driver)
        return max(1, int(launches))

    def queue_depth(self, tenant: str) -> int:
        return len(self._queues.get(tenant, ()))

    def queued_depths(self) -> Dict[str, int]:
        """Non-empty per-tenant queue depths — the autoscaler's primary
        scale-up signal (weight-capped per tenant by the policy)."""
        return {t: len(q) for t, q in self._queues.items() if q}

    def total_queued(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def running_count(self, tenant: str) -> int:
        return len(self._running.get(tenant, ()))

    def quota_used(self, tenant: str) -> int:
        return self._mem_used.get(tenant, 0)

    def _can_run(self, tenant: str) -> bool:
        pol = self.conf.policy(tenant)
        if pol.max_jobs and self.running_count(tenant) >= pol.max_jobs:
            return False
        if self.conf.max_concurrent_jobs_total and \
                self._total_running >= self.conf.max_concurrent_jobs_total:
            return False
        if pol.memory_quota_bytes and self.running_count(tenant) and \
                self.quota_used(tenant) >= pol.memory_quota_bytes:
            return False
        return True

    # -- lifecycle -------------------------------------------------------
    def offer(self, job) -> str:
        """Enqueue one submitted job. Returns ``"queued"`` or
        ``"shed"`` (per-tenant queue full, or deadline already past) —
        admission itself happens in :meth:`drain`, so queue order and
        DRR state stay the single source of decision order."""
        tenant = job.tenant
        now = time.time()
        if not self.enabled:
            # pass-through: park in the tenant queue with no events or
            # accounting; drain() admits unconditionally
            self._queues.setdefault(tenant, deque()).append(job)
            return "queued"
        if job.deadline_ts is not None and now >= job.deadline_ts:
            self._shed(job, "deadline")
            return "shed"
        q = self._queues.setdefault(tenant, deque())
        if self.conf.max_queued_jobs and \
                len(q) >= self.conf.max_queued_jobs:
            self._shed(job, "queue_full")
            return "shed"
        job.adm_cost = self.job_cost(job)
        job.queued_ts = now
        q.append(job)
        _record_metric("cluster.admission.enqueued_count", 1,
                       tenant=tenant)
        _record_metric("cluster.admission.queue_depth", len(q),
                       tenant=tenant)
        events.emit(EventType.ADMISSION_ENQUEUE, query_id=job.query_id,
                    trace_id=_trace(job), job_id=job.job_id,
                    tenant=tenant, queue_depth=len(q),
                    cost=job.adm_cost)
        return "queued"

    def _shed(self, job, reason: str) -> None:
        tenant = job.tenant
        depth = self.queue_depth(tenant)
        self.shed_totals[tenant] = self.shed_totals.get(tenant, 0) + 1
        _record_metric("cluster.admission.shed_count", 1, tenant=tenant,
                       reason=reason)
        queued_ts = getattr(job, "queued_ts", None)
        _record_metric(
            "cluster.admission.shed_wait_time",
            max(0.0, time.time() - queued_ts) if queued_ts else 0.0,
            tenant=tenant, reason=reason)
        events.emit(EventType.ADMISSION_SHED, query_id=job.query_id,
                    trace_id=_trace(job), job_id=job.job_id,
                    tenant=tenant, reason=reason, queue_depth=depth)
        job.error_kind = "deadline" if reason == "deadline" else "shed"
        job.failed = (f"admission shed ({reason}): tenant "
                      f"{tenant!r} queue depth {depth}")
        job.done.set()

    def poll(self, now: Optional[float] = None) -> List:
        """Shed queued jobs whose queue budget or deadline expired.
        Returns the shed jobs (already failed + done)."""
        if not self.enabled:
            return []
        now = time.time() if now is None else now
        shed: List = []
        for tenant in sorted(self._queues):
            q = self._queues[tenant]
            keep = deque()
            while q:
                job = q.popleft()
                if job.done.is_set():
                    continue  # canceled while queued
                if job.deadline_ts is not None and now >= job.deadline_ts:
                    self._shed(job, "deadline")
                    shed.append(job)
                elif self.conf.queue_timeout_ms and \
                        (now - job.queued_ts) * 1000.0 >= \
                        self.conf.queue_timeout_ms:
                    self._shed(job, "queue_timeout")
                    shed.append(job)
                else:
                    keep.append(job)
            self._queues[tenant] = keep
        return shed

    def drain(self, now: Optional[float] = None) -> List:
        """Deficit-round-robin pop of every currently admissible queued
        job, in decision order. Each admission opportunity (a free
        launch slot) credits every backlogged admissible tenant its
        weight; the tenant with the highest deficit wins (ties broken
        by tenant name) and pays the admitted job's cost in stage-launch
        opportunities — so over a backlog, tenants receive launch
        opportunities proportional to their weights regardless of job
        sizes. The caller schedules each returned job (the admit event
        fires here, so the log IS the decision order).

        ``now`` is an injected signal (recorded in the admit event's
        ``waited_ms``): replay passes the recorded clock, the live path
        defaults — the arbitration itself never reads the wall clock."""
        now = time.time() if now is None else now
        admitted: List = []
        if not self.enabled:
            for tenant in sorted(self._queues):
                q = self._queues[tenant]
                while q:
                    job = q.popleft()
                    job.admitted = True
                    admitted.append(job)
            return admitted
        while True:
            cands = [t for t in sorted(self._queues)
                     if self._queues[t] and self._can_run(t)]
            if not cands:
                break
            for t in cands:
                self._deficit[t] = self._deficit.get(t, 0.0) \
                    + self.conf.policy(t).weight
            winner = min(cands,
                         key=lambda t: (-self._deficit.get(t, 0.0), t))
            q = self._queues[winner]
            job = q.popleft()
            if job.done.is_set():
                continue  # shed/canceled while queued
            # ALWAYS charge the admitted job's cost — a tenant that
            # trickles heavy jobs one at a time (queue emptying on
            # every pop) must not dodge its stage-launch debt — but an
            # emptied queue forfeits any positive surplus: an idle
            # tenant must not bank credit to burst with later
            self._deficit[winner] = self._deficit.get(winner, 0.0) \
                - job.adm_cost
            if not q:
                self._deficit[winner] = min(
                    self._deficit[winner], 0.0)
            self._admit(job, now)
            admitted.append(job)
        return admitted

    def _admit(self, job, now: float) -> None:
        tenant = job.tenant
        job.admitted = True
        self._running.setdefault(tenant, set()).add(job.job_id)
        self._total_running += 1
        waited_ms = round((now - job.queued_ts) * 1000.0, 3)
        _record_metric("cluster.admission.admitted_count", 1,
                       tenant=tenant)
        _record_metric("cluster.admission.queue_wait_time",
                       max(0.0, waited_ms) / 1000.0, tenant=tenant)
        _record_metric("cluster.admission.queue_depth",
                       self.queue_depth(tenant), tenant=tenant)
        events.emit(EventType.ADMISSION_ADMIT, query_id=job.query_id,
                    trace_id=_trace(job), job_id=job.job_id,
                    tenant=tenant, waited_ms=waited_ms)

    def release(self, job) -> None:
        """A job left the running set (done + cleanup): free its
        concurrency slot and any memory debits its tasks still hold.
        Idempotent — cleanup and probe can both observe the exit."""
        tenant = job.tenant
        running = self._running.get(tenant)
        if running is not None and job.job_id in running:
            running.discard(job.job_id)
            self._total_running = max(0, self._total_running - 1)
        for key in [k for k in self._debits if k[0] == job.job_id]:
            t, nbytes = self._debits.pop(key)
            self._mem_used[t] = max(0, self._mem_used.get(t, 0) - nbytes)
        if tenant in self._mem_used:
            _record_metric("cluster.quota.debited_bytes",
                           self._mem_used.get(tenant, 0), tenant=tenant)

    # -- long-lived (continuous) jobs ------------------------------------
    def admit_resident(self, job_id: str, tenant: str) -> bool:
        """Admission gate for a continuous pipeline: it occupies a
        concurrency slot like any running job, checked against the
        tenant's ``max_jobs`` and the global cap — a tenant at its cap
        cannot grab every worker with resident tasks that the batch
        caps would have refused. (Memory quota is not debited: resident
        tasks have no producer-size projections to debit from.)"""
        if not self.enabled:
            return True
        if not self._can_run(tenant):
            return False
        self._running.setdefault(tenant, set()).add(job_id)
        self._total_running += 1
        return True

    def note_resident(self, job_id: str, tenant: str,
                      cost: int) -> None:
        """Register a continuous job's resident-task occupancy for
        periodic DRR re-charging. The cost is its resident task count
        (the worker slots it holds), re-debited from the tenant's
        deficit every ``admission.resident_recharge_secs`` — without
        this, a continuous job charged stage-launch opportunities once
        at admit and then occupied workers forever, starving batch
        tenants of their fair share."""
        if not self.enabled:
            return
        self._resident[job_id] = [tenant, max(1, int(cost)),
                                  time.time()]

    def release_resident(self, job_id: str) -> None:
        self._resident.pop(job_id, None)
        # the concurrency slot frees independently of the recharge
        # registration (a dispatch failure can release between
        # admit_resident and note_resident)
        for running in self._running.values():
            if job_id in running:
                running.discard(job_id)
                self._total_running = max(0, self._total_running - 1)
                break

    def recharge(self, now: Optional[float] = None) -> int:
        """Debit every resident job's tenant its occupancy cost for
        each elapsed recharge interval — but ONLY while some OTHER
        tenant is backlogged: occupancy during idle capacity is free
        (nobody was displaced), so a continuous tenant cannot
        accumulate unbounded catch-up debt overnight and then starve
        for hours once it submits batch work. Returns intervals
        charged."""
        if not self.enabled or not self._resident:
            return 0
        now = time.time() if now is None else now
        charged = 0
        for job_id in sorted(self._resident):
            entry = self._resident[job_id]
            tenant, cost, last = entry
            n = int((now - last) / self.resident_recharge_s)
            if n <= 0:
                continue
            # the elapsed intervals are consumed either way (idle time
            # is never charged retroactively)
            entry[2] = last + n * self.resident_recharge_s
            contended = any(q for t, q in self._queues.items()
                            if t != tenant)
            if not contended:
                continue
            self._deficit[tenant] = self._deficit.get(tenant, 0.0) \
                - cost * n
            charged += n
            _record_metric("cluster.admission.resident_recharge_count",
                           n, tenant=tenant)
        return charged

    # -- ops surface -----------------------------------------------------
    def wedged(self, now: Optional[float] = None) -> bool:
        """A queued job sitting past TWICE its shed budget means the
        scheduling loop (poll + drain on the driver actor) has stopped
        turning — poll() would have shed or admitted it long ago. The
        ops endpoint's /readyz flips on this.

        Called from the HTTP thread while the driver actor mutates the
        queues (this class is otherwise actor-thread-only, so there is
        deliberately no lock): a torn iteration means the actor is
        actively processing — the opposite of wedged — so a racing
        read answers False rather than flapping a healthy /readyz."""
        if not self.enabled or not self.conf.queue_timeout_ms:
            # no queue budget configured = jobs may legitimately wait
            # indefinitely; there is no bound to detect a stall against
            return False
        now = time.time() if now is None else now
        budget_s = self.conf.queue_timeout_ms / 1000.0
        try:
            for q in list(self._queues.values()):
                for job in list(q):
                    queued_ts = getattr(job, "queued_ts", None)
                    if queued_ts and now - queued_ts > 2.0 * budget_s:
                        return True
        except RuntimeError:  # dict/deque resized mid-iteration
            return False
        return False

    def debug_snapshot(self) -> dict:
        """JSON-able state for /debug/admission (read cross-thread:
        point-in-time, best-effort — a torn read degrades to a partial
        snapshot, never an error page)."""
        try:
            return {
                "kind": "cluster_job_queue",
                "enabled": self.enabled,
                "queued": {t: len(q)
                           for t, q in list(self._queues.items())
                           if q},
                "running": {t: len(s)
                            for t, s in list(self._running.items())
                            if s},
                "total_running": self._total_running,
                "deficit": {t: round(v, 3)
                            for t, v in list(self._deficit.items())},
                "quota_used_bytes": dict(self._mem_used),
            }
        except RuntimeError:
            return {"kind": "cluster_job_queue",
                    "enabled": self.enabled, "racing": True}

    # -- memory quota ledger (PR 7 governor projections) ----------------
    def tenant_quota(self, tenant: str) -> int:
        """The tenant's memory quota in bytes (0 = none/disabled)."""
        if not self.enabled:
            return 0
        return self.conf.policy(tenant).memory_quota_bytes

    def quota_admit(self, tenant: str, nbytes: int) -> bool:
        """Would debiting ``nbytes`` keep the tenant under quota? A
        tenant with NOTHING debited always admits (progress guarantee:
        quota throttles, never deadlocks)."""
        if not self.enabled:
            return True
        pol = self.conf.policy(tenant)
        if not pol.memory_quota_bytes:
            return True
        used = self.quota_used(tenant)
        return used == 0 or used + nbytes <= pol.memory_quota_bytes

    def debit(self, job, stage: int, partition: int,
              nbytes: int) -> None:
        """Record one admitted task's projected bytes against its
        tenant's quota. The projection comes from producers' REPORTED
        channel sizes (the AQE-observed stats), so the ledger tracks
        real data movement, not static estimates."""
        if not self.enabled or nbytes <= 0:
            return
        tenant = job.tenant
        key = (job.job_id, stage, partition)
        prev = self._debits.pop(key, None)
        if prev is not None:
            self._mem_used[tenant] = max(
                0, self._mem_used.get(tenant, 0) - prev[1])
        self._debits[key] = (tenant, int(nbytes))
        used = self._mem_used.get(tenant, 0) + int(nbytes)
        self._mem_used[tenant] = used
        _record_metric("cluster.quota.debited_bytes", used,
                       tenant=tenant)
        events.emit(EventType.QUOTA_DEBIT, query_id=job.query_id,
                    trace_id=_trace(job), job_id=job.job_id,
                    tenant=tenant, stage=stage, partition=partition,
                    bytes=int(nbytes), used_bytes=used)

    def credit(self, job_id: str, stage: int, partition: int) -> None:
        """Release one task's debit (terminal report / task release)."""
        entry = self._debits.pop((job_id, stage, partition), None)
        if entry is None:
            return
        tenant, nbytes = entry
        used = max(0, self._mem_used.get(tenant, 0) - nbytes)
        self._mem_used[tenant] = used
        _record_metric("cluster.quota.debited_bytes", used,
                       tenant=tenant)


def _trace(job) -> Optional[str]:
    ctx = getattr(job, "trace_ctx", None)
    return ctx.trace_id if ctx is not None else None


# ---------------------------------------------------------------------------
# session path: process-wide concurrent-query gate
# ---------------------------------------------------------------------------

class _Ticket:
    """Handle returned by :meth:`SessionAdmission.acquire`; release()
    exactly once (idempotent) frees the slot and wakes the next waiter."""

    __slots__ = ("_gate", "_tenant", "_released")

    def __init__(self, gate: Optional["SessionAdmission"], tenant: str):
        self._gate = gate
        self._tenant = tenant
        self._released = False

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        if self._gate is not None:
            self._gate._release(self._tenant)


class _Waiter:
    __slots__ = ("tenant", "seq", "event", "admitted", "abandoned")

    def __init__(self, tenant: str, seq: int):
        self.tenant = tenant
        self.seq = seq
        self.event = threading.Event()
        self.admitted = False
        self.abandoned = False


class SessionAdmission:
    """Weighted-fair gate on the local query path. Admission order is
    deterministic given arrival + completion order: among tenants with
    eligible waiters the lowest virtual time goes first (ties broken by
    tenant name), FIFO within a tenant. Each admission advances the
    tenant's virtual time by ``1/weight``; a tenant entering the wait
    queue from idle is floored to the global virtual clock, so neither
    a newcomer nor a long-idle tenant banks credit it could use to
    starve established tenants."""

    def __init__(self, conf: Optional[AdmissionConfig] = None):
        self.conf = conf or AdmissionConfig()
        self.enabled = self.conf.enabled
        self._lock = threading.Lock()
        self._running: Dict[str, int] = {}
        self._total = 0
        self._vt: Dict[str, float] = {}
        self._vclock = 0.0
        self._waiters: Dict[str, Deque[_Waiter]] = {}
        self._seq = itertools.count()
        self._tls = threading.local()

    def _eligible(self, tenant: str) -> bool:  # guarded-by: _lock
        pol = self.conf.policy(tenant)
        if pol.max_queries and \
                self._running.get(tenant, 0) >= pol.max_queries:
            return False
        if self.conf.max_concurrent_total and \
                self._total >= self.conf.max_concurrent_total:
            return False
        return True

    def acquire(self, tenant: str, query_id: str = "",
                deadline_ms: Optional[float] = None) -> _Ticket:
        """Block until admitted, or raise a typed error. Re-entrant per
        thread: a nested ``_execute_query`` (commands running
        subqueries, streaming triggers inside a profiled query) rides
        the thread's existing ticket instead of double-queuing.
        Enforcement is process-wide (``admission.enabled``): there is
        deliberately no per-call opt-out a tenant could reach."""
        if not self.enabled:
            return _Ticket(None, tenant)
        depth = getattr(self._tls, "depth", 0)
        if depth:
            # nested: ride the held slot; release() just pops the depth
            self._tls.depth = depth + 1
            return _Ticket(self, tenant)
        waiter: Optional[_Waiter] = None
        shed_depth: Optional[int] = None
        # decide under the lock; emit (which may write the durable
        # event log) only AFTER releasing it — the gate must never
        # serialize every tenant's admissions on event-log I/O
        with self._lock:
            queued = self._waiters.get(tenant)
            if self._eligible(tenant) and not queued:
                self._admit_locked(tenant)
            else:
                depth_now = len(queued or ())
                if self.conf.max_queued_queries and \
                        depth_now >= self.conf.max_queued_queries:
                    shed_depth = depth_now
                else:
                    waiter = _Waiter(tenant, next(self._seq))
                    wq = self._waiters.setdefault(tenant, deque())
                    if not wq:
                        # entering the contest from idle: floor the
                        # virtual time to the global clock (no banked
                        # credit)
                        self._vt[tenant] = max(
                            self._vt.get(tenant, 0.0), self._vclock)
                    wq.append(waiter)
        if shed_depth is not None:
            _record_metric("cluster.admission.shed_count", 1,
                           tenant=tenant, reason="queue_full")
            _record_metric("cluster.admission.shed_wait_time", 0.0,
                           tenant=tenant, reason="queue_full")
            events.emit(EventType.ADMISSION_SHED, query_id=query_id,
                        job_id="", tenant=tenant, reason="queue_full",
                        queue_depth=shed_depth)
            raise ResourceExhausted(
                f"tenant {tenant!r} admission queue is full "
                f"({shed_depth} queued); retry after backoff",
                tenant=tenant,
                retry_after_ms=self.conf.queue_timeout_ms or 1000)
        if waiter is not None:
            # depth snapshot read outside the lock: telemetry only
            depth = len(self._waiters.get(tenant, ()))
            _record_metric("cluster.admission.enqueued_count", 1,
                           tenant=tenant)
            _record_metric("cluster.admission.queue_depth", depth,
                           tenant=tenant)
            events.emit(EventType.ADMISSION_ENQUEUE,
                        query_id=query_id, job_id="", tenant=tenant,
                        queue_depth=depth, cost=1)
        if waiter is None:
            events.emit(EventType.ADMISSION_ADMIT, query_id=query_id,
                        job_id="", tenant=tenant, waited_ms=0.0)
            _record_metric("cluster.admission.queue_wait_time", 0.0,
                           tenant=tenant)
            self._tls.depth = 1
            return _Ticket(self, tenant)
        t0 = time.time()
        timeout_s = self.conf.queue_timeout_ms / 1000.0 \
            if self.conf.queue_timeout_ms else None
        deadline_bound = deadline_ms is not None and deadline_ms > 0 and \
            (timeout_s is None or deadline_ms / 1000.0 < timeout_s)
        if deadline_bound:
            timeout_s = deadline_ms / 1000.0
        got = waiter.event.wait(timeout_s)
        if not got:
            with self._lock:
                if not waiter.admitted:
                    waiter.abandoned = True
                    try:
                        self._waiters.get(tenant, deque()).remove(waiter)
                    except ValueError:
                        pass
                    got = False
                else:
                    got = True  # admission raced the timeout: take it
            if not got:
                reason = "deadline" if deadline_bound else "queue_timeout"
                _record_metric("cluster.admission.shed_count", 1,
                               tenant=tenant, reason=reason)
                _record_metric("cluster.admission.shed_wait_time",
                               max(0.0, time.time() - t0),
                               tenant=tenant, reason=reason)
                events.emit(EventType.ADMISSION_SHED, query_id=query_id,
                            job_id="", tenant=tenant, reason=reason,
                            queue_depth=len(self._waiters.get(
                                tenant, ())))
                waited = round((time.time() - t0) * 1000.0, 1)
                if deadline_bound:
                    raise DeadlineExceeded(
                        f"query deadline ({deadline_ms:.0f}ms) elapsed "
                        f"after {waited}ms in the admission queue",
                        tenant=tenant)
                raise ResourceExhausted(
                    f"tenant {tenant!r} query waited {waited}ms in the "
                    f"admission queue (budget "
                    f"{self.conf.queue_timeout_ms}ms); retry after "
                    f"backoff", tenant=tenant,
                    retry_after_ms=self.conf.queue_timeout_ms or 1000)
        waited_ms = round((time.time() - t0) * 1000.0, 3)
        events.emit(EventType.ADMISSION_ADMIT, query_id=query_id,
                    job_id="", tenant=tenant, waited_ms=waited_ms)
        _record_metric("cluster.admission.queue_wait_time",
                       max(0.0, time.time() - t0), tenant=tenant)
        try:
            # the gate runs on the query thread: charge the wait to
            # the active profile (anomaly evidence + EXPLAIN ANALYZE)
            from .. import profiler
            profiler.note_admission_wait(waited_ms)
        except Exception:  # noqa: BLE001 — telemetry only
            pass
        self._tls.depth = 1
        return _Ticket(self, tenant)

    def debug_snapshot(self) -> dict:
        """JSON-able gate state for /debug/admission."""
        with self._lock:
            return {
                "kind": "session_gate",
                "enabled": self.enabled,
                "running": {t: n for t, n in self._running.items()
                            if n},
                "total_running": self._total,
                "queued": {t: len(q)
                           for t, q in self._waiters.items() if q},
                "virtual_time": {t: round(v, 4)
                                 for t, v in self._vt.items()},
            }

    def _admit_locked(self, tenant: str) -> None:  # guarded-by: _lock
        self._running[tenant] = self._running.get(tenant, 0) + 1
        self._total += 1
        start = self._vt.get(tenant, 0.0)
        self._vclock = max(self._vclock, start)
        self._vt[tenant] = start + 1.0 / self.conf.policy(tenant).weight
        _record_metric("cluster.admission.admitted_count", 1,
                       tenant=tenant)

    def _release(self, tenant: str) -> None:
        depth = getattr(self._tls, "depth", 0)
        if depth > 1:
            self._tls.depth = depth - 1
            return
        self._tls.depth = 0
        woken: List[_Waiter] = []
        with self._lock:
            self._running[tenant] = max(
                0, self._running.get(tenant, 0) - 1)
            self._total = max(0, self._total - 1)
            while True:
                cands = [t for t in sorted(self._waiters)
                         if self._waiters[t] and self._eligible(t)]
                if not cands:
                    break
                # lowest virtual time first, ties by tenant name
                t = min(cands, key=lambda name: (
                    self._vt.get(name, 0.0), name))
                w = self._waiters[t].popleft()
                if w.abandoned:
                    continue
                w.admitted = True
                self._admit_locked(t)
                woken.append(w)
        for w in woken:
            w.event.set()


# ---------------------------------------------------------------------------
# process-global session gate
# ---------------------------------------------------------------------------

_GATE: Optional[SessionAdmission] = None
_GATE_LOCK = threading.Lock()


def session_gate() -> SessionAdmission:
    global _GATE
    if _GATE is None:
        with _GATE_LOCK:
            if _GATE is None:
                _GATE = SessionAdmission()
    return _GATE


def reload() -> None:
    """Re-read the admission config (tests, bench A/B runs). In-flight
    tickets release against the OLD gate they hold a reference to."""
    global _GATE
    with _GATE_LOCK:
        _GATE = None
