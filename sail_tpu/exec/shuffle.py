"""Compressed streaming shuffle data plane.

Reference role: Theseus' thesis (arXiv:2508.05029, PAPERS.md) that at
scale a distributed engine is a data-movement scheduler — the wire and
spill formats, not the operators, dominate join/agg-heavy suites once
compute is fused. This module is the data-plane vocabulary shared by the
cluster runtime (exec/cluster.py):

- **Wire + spill format**: Arrow IPC streams with lz4/zstd body
  compression (``shuffle.compression``: lz4 | zstd | none, default lz4)
  applied uniformly to FetchStream responses, ``_StreamStore`` spill
  files, and broadcast/driver-result transfers. Compression is recorded
  per IPC message, so READERS AUTO-DETECT the codec from the stream —
  mixed-codec and A/B runs interoperate with no negotiation.
- **Chunked streaming**: tables encode in bounded record batches
  (``ENCODE_CHUNK_ROWS``) and decode incrementally off a chunk iterator
  (:class:`ChunkReader` + :func:`decode_stream`) instead of
  concatenating the whole byte stream first; the spill format IS the
  wire format, so a spilled channel serves straight from disk in
  bounded reads with no rehydration under the memory cap.
- **Observability**: ``execution.shuffle.{wire_bytes,
  wire_bytes_compressed, spill_bytes_compressed, fetch_wait_time,
  decode_time}`` make the movement plane as measurable as the compute
  plane; :class:`FetchStats` accumulates the same numbers per task so
  they ride task reports into the driver's query profile.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Iterable, Iterator, Optional

from ..metrics import record as _record_metric

#: serve-side chunk size for FetchStream responses and spill reads
CHUNK_BYTES = 1 << 20

#: record-batch granularity of encoded streams — the decode side's
#: working set per message is bounded by this many rows, not the table
ENCODE_CHUNK_ROWS = 1 << 16

_CODEC_NONE = ("none", "off", "uncompressed", "false", "0", "")


def wire_codec() -> Optional[str]:
    """Resolve ``shuffle.compression`` to a pyarrow IPC codec name
    (``lz4``/``zstd``) or None (uncompressed). Unknown spellings fall
    back to the lz4 default rather than failing the data plane."""
    from ..config import get as config_get
    value = str(config_get("shuffle.compression", "lz4") or "lz4")
    value = value.strip().lower()
    if value in _CODEC_NONE:
        return None
    if value not in ("lz4", "zstd"):
        return "lz4"
    return value


def fetch_concurrency() -> int:
    """``shuffle.fetch_concurrency``: concurrent stage-input fetches per
    task (0/1 = sequential)."""
    from ..config import get as config_get
    try:
        return max(0, int(config_get("shuffle.fetch_concurrency", 4)))
    except (TypeError, ValueError):
        return 4


_SENTINEL_CODEC = object()


def encode_table(table, codec=_SENTINEL_CODEC, record: bool = True) -> bytes:
    """Encode a table as a (possibly compressed) Arrow IPC stream in
    bounded record batches. Records the raw-vs-wire byte counters unless
    ``record`` is off (plan-fragment embedding is not data-plane
    traffic)."""
    import pyarrow as pa
    if codec is _SENTINEL_CODEC:
        codec = wire_codec()
    opts = pa.ipc.IpcWriteOptions(compression=codec)
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema, options=opts) as w:
        w.write_table(table, max_chunksize=ENCODE_CHUNK_ROWS)
    buf = sink.getvalue().to_pybytes()
    if record:
        _record_metric("execution.shuffle.wire_bytes", int(table.nbytes))
        _record_metric("execution.shuffle.wire_bytes_compressed", len(buf))
    return buf


class EpochLedger:
    """Seal bookkeeping for epoch-tagged shuffle channels.

    Streaming triggers run one epoch at a time through the cluster data
    plane; every producer task publishes its channels under
    ``(job_id, epoch)`` and *seals* that epoch for its partition in one
    atomic step. The barrier contract: a consumer may start epoch N only
    after every producer channel it reads has sealed N — the driver's
    stage scheduler enforces it in the control plane (locations are
    recorded only on success reports, which follow the seal), and the
    store enforces it in the data plane by serving NOTHING for an
    unsealed (or mismatched) epoch, which the consumer's NOT_FOUND
    fetch-failed path turns into a producer re-run. A crashed trigger's
    stale channels are therefore inert: the replay either overwrites
    them under the same epoch or never addresses them at all."""

    def __init__(self):
        self._sealed: dict = {}   # (job_id, stage, partition) -> epoch
        self._lock = threading.Lock()

    def seal(self, job_id: str, epoch: int, stage: int,
             partition: int) -> None:
        with self._lock:
            self._sealed[(job_id, stage, partition)] = int(epoch)

    def is_sealed(self, job_id: str, epoch: int, stage: int,
                  partition: int) -> bool:
        with self._lock:
            return self._sealed.get((job_id, stage, partition)) \
                == int(epoch)

    def unseal(self, job_id: str) -> None:
        """Drop every seal a job holds (job cleanup)."""
        with self._lock:
            for key in [k for k in self._sealed if k[0] == job_id]:
                del self._sealed[key]


@dataclass
class FetchStats:
    """Per-task fetch accounting, accumulated across concurrent fetch
    threads (hence the lock) and shipped on the task's success report so
    the driver's query profile sees the movement plane."""

    wire_bytes: int = 0       # compressed bytes off the wire
    decode_s: float = 0.0     # IPC decode time (excl. stream wait)
    wait_s: float = 0.0       # consumer blocked waiting on fetches
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False)

    def add(self, wire_bytes: int = 0, decode_s: float = 0.0,
            wait_s: float = 0.0) -> None:
        with self._lock:
            self.wire_bytes += int(wire_bytes)
            self.decode_s += float(decode_s)
            self.wait_s += float(wait_s)


class ChunkReader:
    """File-like adapter over an iterator of byte chunks, so pyarrow's
    IPC stream reader decodes record batches incrementally off a gRPC
    response stream (no ``b"".join`` of the whole channel first). Time
    blocked pulling the next chunk accrues to ``wait_s`` so decode time
    can be reported net of network wait."""

    def __init__(self, chunks: Iterable[bytes]):
        self._it = iter(chunks)
        self._buf = b""
        self.closed = False
        self.wait_s = 0.0
        self.nbytes = 0

    def readable(self) -> bool:
        return True

    def writable(self) -> bool:
        return False

    def seekable(self) -> bool:
        return False

    def close(self) -> None:
        self.closed = True
        self._it = iter(())

    def _pull(self) -> bool:
        t0 = time.perf_counter()
        try:
            chunk = next(self._it)
        except StopIteration:
            return False
        finally:
            self.wait_s += time.perf_counter() - t0
        self._buf += chunk
        self.nbytes += len(chunk)
        return True

    def read(self, n: int = -1) -> bytes:
        if n is None or n < 0:
            while self._pull():
                pass
            out, self._buf = self._buf, b""
            return out
        while len(self._buf) < n:
            if not self._pull():
                break
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def peek(self, n: int) -> bytes:
        """Up to ``n`` bytes WITHOUT consuming them — lets the decoder
        detect whether another concatenated IPC stream follows."""
        while len(self._buf) < n:
            if not self._pull():
                break
        return self._buf[:n]


def decode_stream(source, stats: Optional[FetchStats] = None):
    """Decode one or more CONCATENATED Arrow IPC streams (bytes,
    file-like, or :class:`ChunkReader`) into a table, record batch by
    record batch. Multiple streams arise from the all-channels fetch
    (``channel = -2``): the server serves every hash channel of one
    producer partition back to back, each a complete IPC stream with
    its own schema header and EOS marker, and the reader re-opens at
    each boundary. The codec is auto-detected per stream, so readers
    accept any producer codec. Decode wall time (net of chunk wait for
    a ChunkReader) lands in ``execution.shuffle.decode_time``."""
    import pyarrow as pa
    from ..metrics import timer as _metric_timer
    # measure-only timer handle: the recorded value is elapsed NET of
    # chunk wait, so the registry write happens below, not at exit
    with _metric_timer() as tm:
        if isinstance(source, (bytes, bytearray)):
            source = ChunkReader(iter([bytes(source)]))
        schema = None
        batches = []
        while True:
            reader = pa.ipc.open_stream(source)
            if schema is None:
                schema = reader.schema
            batches.extend(reader)
            if not isinstance(source, ChunkReader) or not source.peek(1):
                break  # single stream source, or no further stream
        table = pa.Table.from_batches(batches, schema=schema)
    wait = source.wait_s if isinstance(source, ChunkReader) else 0.0
    decode_s = max(0.0, tm.elapsed_s - wait)
    try:
        _record_metric("execution.shuffle.decode_time", decode_s)
    except Exception:  # noqa: BLE001 — telemetry never fails the fetch
        pass
    if stats is not None:
        wire = source.nbytes if isinstance(source, ChunkReader) \
            else len(source) if isinstance(source, (bytes, bytearray)) else 0
        stats.add(wire_bytes=wire, decode_s=decode_s)
    return table


def iter_buffer_chunks(buf: bytes,
                       chunk_bytes: int = CHUNK_BYTES) -> Iterator[bytes]:
    """Slice an in-memory channel into bounded wire chunks."""
    for off in range(0, max(len(buf), 1), chunk_bytes):
        yield buf[off:off + chunk_bytes]


def iter_file_chunks(f, chunk_bytes: int = CHUNK_BYTES) -> Iterator[bytes]:
    """Stream an open spill file in bounded reads; the file handle is
    closed when the iterator is exhausted or dropped. The file was
    opened BEFORE the first yield, so a concurrent unlink (clean_job)
    cannot turn a mid-stream read into a missing-channel error."""
    try:
        empty = True
        while True:
            chunk = f.read(chunk_bytes)
            if not chunk:
                break
            empty = False
            yield chunk
        if empty:
            yield b""
    finally:
        f.close()
