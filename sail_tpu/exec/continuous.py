"""Continuous record-at-a-time streaming execution for the cluster path.

Reference role: the reference Sail races Chandy–Lamport-style flow
markers through a *running* dataflow instead of aligning whole
micro-batch epochs (SURVEY.md §3.5) — ROADMAP item 4 names the epoch
granularity of PR 9 as the one remaining honest gap. Theseus
(arXiv:2508.05029) frames the missing piece as flow control: long-lived
flows need credit, not just placement; Tailwind (arXiv:2604.28079)
makes sub-second per-tenant latency promises that a trigger loop with a
one-job-dispatch-per-batch floor cannot keep.

Shape (gated by ``streaming.continuous.enabled``; OFF is bit-identical
to the epoch path — none of this module runs):

- **Long-lived stage tasks.** The driver dispatches every stage of a
  streaming job ONCE as resident tasks (``TaskDefinition.
  continuous_json``): a worker keeps the decoded fragment warm, pulls
  sequenced record batches from upstream as they arrive, and pushes
  results downstream through the compressed data plane (``PushRecords``
  unary RPCs carrying lz4/zstd Arrow IPC payloads).
- **Sequenced credit-based channels.** :class:`CreditInbox` generalizes
  the epoch-tagged ``_StreamStore`` channels into unbounded,
  sequence-numbered per-channel streams bounded by in-flight bytes:
  exhausted credit refuses the push, the sender stalls-and-retries, and
  the stall propagates upstream hop by hop to the source — surfaced as
  ``backpressure`` events and ``streaming.continuous.credit_stall_time``.
- **Mid-flight marker alignment.** Markers injected at the source ride
  the same channels as data. :class:`AlignedInput` aligns them at
  multi-input operators: an input that has seen marker N is drained
  into a bounded, spill-backed buffer until siblings catch up, so fast
  inputs keep their producers unblocked while slow siblings finish the
  interval. The committed unit stays the marker interval, so the PR 9
  commit protocol (two-phase sinks, publish-then-seal, pre-commit
  records) snapshots a RUNNING pipeline instead of quiescing it.
- **Attempt fencing.** Every push carries the pipeline generation; a
  relaunch (after worker loss the pipeline restarts from the last
  sealed marker) bumps it, and a zombie task's late pushes are refused
  by the receiver's attempt/sequence checks.
"""

from __future__ import annotations

import dataclasses
import os
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

from .. import events
from .. import faults
from ..events import EventType
from ..metrics import record as _record_metric
from ..plan import nodes as pn
from . import job_graph as jg
from . import shuffle as sh
from .proto import control_plane_pb2 as pb

#: sentinel src_stage for driver source injection
SOURCE_STAGE = -1

#: marker added to ScanExec.format for the streaming source leaf; the
#: resident task substitutes each pushed record batch into this scan
STREAM_FORMAT = "__stream__"


def conf() -> dict:
    """One snapshot of the ``streaming.continuous.*`` knobs."""
    from ..config import get as config_get
    from ..config import truthy

    def _num(key, default, cast=int):
        try:
            return cast(config_get(key, default))
        except (TypeError, ValueError):
            return default

    return {
        "enabled": truthy("streaming.continuous.enabled",
                          default="false"),
        "max_batch_rows": max(1, _num(
            "streaming.continuous.max_batch_rows", 4096)),
        "credit_bytes": max(1, _num(
            "streaming.continuous.channel_credit_kb", 1024)) << 10,
        "align_buffer_bytes": max(1, _num(
            "streaming.continuous.align_buffer_kb", 1024)) << 10,
        "marker_timeout_s": _num(
            "streaming.continuous.marker_timeout_s", 30.0, float),
        "start_timeout_s": _num(
            "streaming.continuous.start_timeout_s", 10.0, float),
    }


# ---------------------------------------------------------------------------
# Sequenced, credit-bounded push channel
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Entry:
    seq: int
    kind: str            # "batch" | "marker"
    marker: int
    data: bytes          # encoded Arrow IPC ("" for markers)


class CreditInbox:
    """One producer→consumer sequenced stream with credit-based flow
    control and attempt fencing.

    ``offer`` returns a code: ``ok`` (accepted), ``dup`` (an at-least-
    once retransmission of an already-accepted sequence — acknowledged,
    not re-enqueued), ``fenced`` (the push carries a stale generation:
    the sender is a zombie and must stop), ``credit`` (in-flight bytes
    would exceed the bound: the sender stalls and retries — this is the
    backpressure signal that propagates hop by hop to the source).
    A push from a NEWER generation is refused ``unready`` — inboxes
    are generation-pinned, and the relaunched task's FRESH inbox is
    the only valid receiver (an old inbox acknowledging new-generation
    entries would lose them when the task is replaced, leaving the
    sender permanently 'ahead' of the fresh stream)."""

    def __init__(self, attempt: int, credit_bytes: int,
                 cond: threading.Condition):
        self.attempt = attempt
        self.credit_bytes = credit_bytes
        self.cond = cond            # shared with the owning aligner
        self.entries: List[Entry] = []
        self.pending_bytes = 0
        self.next_seq = 0           # next sequence to accept

    def offer(self, attempt: int, seq: int, kind: str, marker: int,
              data: bytes) -> str:
        with self.cond:
            if attempt < self.attempt:
                return "fenced"
            if attempt > self.attempt:
                return "unready"  # the relaunch's fresh inbox owns it
            if seq < self.next_seq:
                return "dup"
            if seq > self.next_seq:
                # per-channel pushes are in order from one sender
                # thread; a gap means a retried earlier push is still
                # in flight — refuse so the sender re-sends in order
                return "ahead"
            if self.pending_bytes and \
                    self.pending_bytes + len(data) > self.credit_bytes:
                return "credit"
            self.entries.append(Entry(seq, kind, marker, data))
            self.pending_bytes += len(data)
            self.next_seq = seq + 1
            self.cond.notify_all()
            return "ok"

    def pop(self) -> Optional[Entry]:  # guarded-by: cond
        """Under ``self.cond``: take the next entry, releasing its
        credit."""
        if not self.entries:
            return None
        entry = self.entries.pop(0)
        self.pending_bytes -= len(entry.data)
        self.cond.notify_all()
        return entry


class _AlignBuffer:
    """Bounded in-memory buffer of post-marker entries from a blocked
    input, spilling encoded payloads to a temp file beyond the bound so
    a fast input can keep streaming while a slow sibling catches up."""

    def __init__(self, memory_bytes: int):
        self._cap = memory_bytes
        self._mem_bytes = 0
        self._items: List[object] = []    # Entry | ("spill", off, len, seq, kind, marker)
        self._spill_file = None
        self._spill_off = 0
        self.spill_count = 0
        self.buffered_bytes = 0

    def push(self, entry: Entry) -> None:
        self.buffered_bytes += len(entry.data)
        if self._mem_bytes + len(entry.data) > self._cap and entry.data:
            if self._spill_file is None:
                fd, path = tempfile.mkstemp(prefix="sail_align_")
                self._spill_file = os.fdopen(fd, "w+b")
                try:
                    os.unlink(path)   # anonymous: vanishes with the fd
                except OSError:
                    pass
            self._spill_file.seek(self._spill_off)
            self._spill_file.write(entry.data)
            self._items.append(("spill", self._spill_off,
                                len(entry.data), entry.seq, entry.kind,
                                entry.marker))
            self._spill_off += len(entry.data)
            self.spill_count += 1
            _record_metric("execution.spill_count", 1, kind="align")
        else:
            self._mem_bytes += len(entry.data)
            self._items.append(entry)

    def drain(self) -> List[Entry]:
        out: List[Entry] = []
        for item in self._items:
            if isinstance(item, Entry):
                self._mem_bytes -= len(item.data)
                out.append(item)
            else:
                _tag, off, ln, seq, kind, marker = item
                self._spill_file.seek(off)
                out.append(Entry(seq, kind, marker,
                                 self._spill_file.read(ln)))
        self._items = []
        self.buffered_bytes = 0
        self._spill_off = 0
        return out

    def close(self) -> None:
        if self._spill_file is not None:
            try:
                self._spill_file.close()
            except OSError:
                pass
            self._spill_file = None


class AlignedInput:
    """Marker alignment across a task's input channels.

    Input keys are ``(src_stage, src_partition)``. ``state_keys`` mark
    BROADCAST inputs (a static build side): their batches surface
    immediately as ``("state", key, data)`` accumulation, their markers
    only participate in alignment. For stream inputs, ``next`` yields
    ``("batch", key, data)`` in per-channel sequence order until an
    input delivers marker N — from then on that input's entries drain
    into a bounded spill-backed buffer (its producer keeps its credit)
    until every sibling has delivered N, at which point ``("marker", N,
    stats)`` fires and the buffered entries replay in order."""

    def __init__(self, keys: List[Tuple[int, int]],
                 state_keys: Optional[set] = None,
                 attempt: int = 0,
                 credit_bytes: int = 1 << 20,
                 align_buffer_bytes: int = 1 << 20):
        self.cond = threading.Condition()
        self.keys = list(keys)
        self.state_keys = set(state_keys or ())
        self.inboxes: Dict[Tuple[int, int], CreditInbox] = {
            k: CreditInbox(attempt, credit_bytes, self.cond)
            for k in self.keys}
        self._blocked: Dict[Tuple[int, int], int] = {}
        self._buffers: Dict[Tuple[int, int], _AlignBuffer] = {
            k: _AlignBuffer(align_buffer_bytes) for k in self.keys}
        self._replay: Dict[Tuple[int, int], List[Entry]] = {
            k: [] for k in self.keys}
        self._block_started: Optional[float] = None
        # state (broadcast build) inputs must PRIME — deliver their
        # startup push, or an empty-build marker — before stream
        # batches flow: joining early against a half-arrived build
        # would silently drop rows. The held stream entries stay in
        # their credit-bounded inboxes, so the wait is backpressure,
        # not loss.
        self._unprimed: set = set(self.state_keys)
        self.closed = False

    def offer(self, key: Tuple[int, int], attempt: int, seq: int,
              kind: str, marker: int, data: bytes) -> str:
        inbox = self.inboxes.get(key)
        if inbox is None:
            return "unready"
        return inbox.offer(attempt, seq, kind, marker, data)

    def backlog_bytes(self) -> int:
        with self.cond:
            return sum(i.pending_bytes for i in self.inboxes.values()) \
                + sum(b.buffered_bytes for b in self._buffers.values())

    def _take_one(self, key: Tuple[int, int]) -> Optional[Entry]:  # guarded-by: cond
        """Under ``self.cond``: next entry of one input, replay buffer
        first."""
        if self._replay[key]:
            return self._replay[key].pop(0)
        return self.inboxes[key].pop()

    def next(self, timeout: float = 0.2):
        """One step of aligned consumption; None on timeout."""
        deadline = time.time() + timeout
        with self.cond:
            while True:
                if self.closed:
                    return ("closed", -1, None)
                # 1. drain blocked inputs into their align buffers so
                # their producers' credit frees (the whole point of
                # buffering past the marker)
                for key, marker in list(self._blocked.items()):
                    if key in self.state_keys:
                        continue
                    while True:
                        entry = self.inboxes[key].pop()
                        if entry is None:
                            break
                        self._buffers[key].push(entry)
                # 2. state inputs surface immediately (blocked or not):
                # a build side primes before record-at-a-time flow starts
                for key in self.keys:
                    if key not in self.state_keys:
                        continue
                    if key in self._blocked:
                        continue
                    entry = self._take_one(key)
                    if entry is None:
                        continue
                    self._unprimed.discard(key)
                    if entry.kind == "marker":
                        self._note_blocked(key, entry.marker)
                        continue
                    return ("state", key, entry)
                # 3. unblocked stream inputs, round-robin — held back
                # until every state input has primed (first push or
                # empty-build marker seen)
                if not self._unprimed:
                    for key in self.keys:
                        if key in self._blocked or \
                                key in self.state_keys:
                            continue
                        entry = self._take_one(key)
                        if entry is None:
                            continue
                        if entry.kind == "marker":
                            self._note_blocked(key, entry.marker)
                            continue
                        return ("batch", key, entry)
                # 4. alignment: every input blocked on the same marker
                if self._blocked and len(self._blocked) == len(self.keys):
                    markers = set(self._blocked.values())
                    marker = min(markers)
                    stats = {
                        "wait_ms": round(
                            (time.time() - self._block_started) * 1000.0,
                            3) if self._block_started else 0.0,
                        "buffered_bytes": sum(
                            b.buffered_bytes
                            for b in self._buffers.values()),
                        "spills": sum(b.spill_count
                                      for b in self._buffers.values()),
                    }
                    # unblock inputs at this marker; replay buffers
                    for key in self.keys:
                        if self._blocked.get(key) == marker:
                            del self._blocked[key]
                            self._replay[key] = \
                                self._buffers[key].drain() \
                                + self._replay[key]
                    self._block_started = time.time() \
                        if self._blocked else None
                    return ("marker", marker, stats)
                remaining = deadline - time.time()
                if remaining <= 0:
                    return None
                self.cond.wait(remaining)

    def _note_blocked(self, key, marker: int) -> None:  # guarded-by: cond
        self._blocked[key] = marker
        if self._block_started is None:
            self._block_started = time.time()

    def close(self) -> None:
        with self.cond:
            self.closed = True
            for b in self._buffers.values():
                b.close()
            self.cond.notify_all()


# ---------------------------------------------------------------------------
# Push sender (credit stall-and-retry, zombie self-termination)
# ---------------------------------------------------------------------------

class Fenced(Exception):
    """The receiver refused this sender's generation: a newer pipeline
    relaunch owns the channels, so this task is a zombie and must stop
    pushing (silently — the relaunch's outputs are authoritative)."""


def offer_response(code: str) -> pb.PushRecordsResponse:
    """The single aligner-code → PushRecords wire-response mapping
    (worker inboxes, the driver root collector, and the unregistered-
    job fallback all share it)."""
    if code in ("ok", "dup"):
        return pb.PushRecordsResponse(accepted=True)
    if code == "fenced":
        return pb.PushRecordsResponse(accepted=False, reason="fenced")
    return pb.PushRecordsResponse(
        accepted=False, reason=code,
        retry_after_ms=2 if code == "credit" else 20)


def push_entry(addr: str, service: str, req: pb.PushRecordsRequest,
               collector=None, query_id: str = "",
               stop_check=None, on_stall=None) -> None:
    """Deliver one sequenced entry, stalling on exhausted credit and
    retrying transient failures (the receiver's seq dedupe makes
    at-least-once delivery exactly-once). Raises :class:`Fenced` for a
    stale generation. ``on_stall`` runs once per refused attempt — the
    DRIVER's source pushes drain their root inbox there, so a full
    root channel can never deadlock the push cycle (driver waits on
    leaf credit, leaf waits on root credit, root waits on the
    driver)."""
    from .cluster import _peer_channel

    site_key = f"s{req.dst_stage}p{req.dst_partition}"
    stall_s = 0.0
    stalled = False
    failures = 0
    while True:
        if stop_check is not None and stop_check():
            raise Fenced("stopped")
        faults.inject("shuffle.credit", key=site_key)
        try:
            channel = _peer_channel(addr)
            rpc = channel.unary_unary(
                f"/{service}/PushRecords",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=pb.PushRecordsResponse.FromString)
            resp = rpc(req, timeout=30)
        except Exception as e:  # noqa: BLE001 — grpc.RpcError and friends
            if isinstance(e, faults.WorkerCrash):
                raise
            failures += 1
            if failures > 40:
                raise
            time.sleep(min(0.25, 0.01 * failures))
            continue
        if resp.accepted:
            break
        if resp.reason == "fenced":
            raise Fenced(f"push to {addr} fenced")
        # "credit" (bounded in-flight bytes exhausted) and "unready"
        # (receiver task not registered yet) both stall-and-retry; the
        # stall IS the upstream propagation of backpressure
        wait = max(1, resp.retry_after_ms) / 1000.0
        if resp.reason == "credit":
            stalled = True
            stall_s += wait
        if on_stall is not None:
            on_stall()
        time.sleep(wait)
    if stalled:
        _record_metric("streaming.continuous.credit_stall_time",
                       stall_s, stage=str(req.dst_stage))
        stall_ms = round(stall_s * 1000.0, 3)
        if collector is not None:
            collector.emit(EventType.BACKPRESSURE, job_id=req.job_id,
                           stage=req.dst_stage,
                           partition=req.dst_partition,
                           channel=req.channel, stall_ms=stall_ms)
        else:
            events.emit(EventType.BACKPRESSURE, query_id=query_id,
                        job_id=req.job_id, stage=req.dst_stage,
                        partition=req.dst_partition,
                        channel=req.channel, stall_ms=stall_ms)


# ---------------------------------------------------------------------------
# Fragment analysis: which stages can process record batches one at a
# time (outputs concatenated over the interval == the interval output)
# ---------------------------------------------------------------------------

def _contains_stream_ref(p: pn.PlanNode, stream_sids: set) -> bool:
    if isinstance(p, jg.StageInputExec):
        return p.stage_id in stream_sids
    if isinstance(p, pn.ScanExec):
        return p.format == STREAM_FORMAT
    return any(_contains_stream_ref(c, stream_sids) for c in p.children)


def streamable_fragment(plan: pn.PlanNode, stream_sids: set,
                        is_producer: bool) -> bool:
    """True when applying the fragment per record batch and
    concatenating the outputs equals applying it to the interval
    concatenation: Filter/Project chains, joins whose streamed side is
    the probe (left) of an inner/left/semi/anti join against a
    stream-free build, and — for shuffle producers only — a TOP-LEVEL
    partial aggregate (its consumer merges the whole interval, so
    per-batch partials fold to the same totals)."""

    def ok(p: pn.PlanNode, top: bool) -> bool:
        if isinstance(p, (jg.StageInputExec, pn.ScanExec)):
            return True
        if isinstance(p, (pn.FilterExec, pn.ProjectExec)):
            return ok(p.input, False)
        if isinstance(p, pn.AggregateExec):
            if not (top and is_producer):
                return False
            return ok(p.input, False)
        if isinstance(p, pn.JoinExec):
            lhs = _contains_stream_ref(p.left, stream_sids)
            rhs = _contains_stream_ref(p.right, stream_sids)
            if rhs or not lhs:
                return False
            if p.join_type not in ("inner", "left", "semi", "anti"):
                return False
            return ok(p.left, False)
        return not _contains_stream_ref(p, stream_sids)

    return _contains_stream_ref(plan, stream_sids) and ok(plan, True)


def mark_stream_scans(node: pn.PlanNode, placeholder) -> Tuple[
        pn.PlanNode, int]:
    """Replace memory scans of the placeholder source table with
    ``__stream__`` leaves (the resident task substitutes pushed record
    batches); returns (plan, count found)."""
    found = [0]

    def repl(p):
        if isinstance(p, pn.ScanExec) and p.source is placeholder:
            found[0] += 1
            # the projection is KEPT: pushed record batches carry the
            # full source schema, and the resident task applies the
            # pruning the optimizer decided before substituting
            return dataclasses.replace(p, source=None,
                                       format=STREAM_FORMAT)
        if isinstance(p, pn.JoinExec):
            return dataclasses.replace(p, left=repl(p.left),
                                       right=repl(p.right))
        if isinstance(p, pn.UnionExec):
            return dataclasses.replace(
                p, inputs=tuple(repl(c) for c in p.inputs))
        if hasattr(p, "input") and p.input is not None:
            return dataclasses.replace(p, input=repl(p.input))
        return p

    out = repl(node)
    return out, found[0]


def _find_stream_scan(p: pn.PlanNode) -> Optional[pn.ScanExec]:
    if isinstance(p, pn.ScanExec) and p.format == STREAM_FORMAT:
        return p
    for c in p.children:
        got = _find_stream_scan(c)
        if got is not None:
            return got
    return None


# ---------------------------------------------------------------------------
# Worker side: resident stage tasks
# ---------------------------------------------------------------------------

class ResidentTask:
    """A long-lived stage task: decode the fragment once, then stream
    aligned record batches through it for the pipeline's lifetime."""

    def __init__(self, worker, task: pb.TaskDefinition, spec: dict,
                 cancel_ev: threading.Event):
        self.worker = worker
        self.task = task
        self.spec = spec
        self.cancel = cancel_ev
        self.generation = int(spec.get("generation", 0))
        self.recorder = events.TaskEventCollector()
        self.rows_out = 0
        keys: List[Tuple[int, int]] = []
        state_keys = set()
        for inp in spec.get("inputs", ()):  # ordered: deterministic concat
            sid = int(inp["stage"])
            for p in inp["parts"]:
                keys.append((sid, int(p)))
            if inp["mode"] == "broadcast":
                state_keys.update((sid, int(p)) for p in inp["parts"])
        self.aligner = AlignedInput(
            keys, state_keys=state_keys, attempt=self.generation,
            credit_bytes=int(spec.get("credit_bytes", 1 << 20)),
            align_buffer_bytes=int(spec.get("align_buffer_bytes",
                                            1 << 20)))
        # per destination (dst_stage, dst_partition): next sequence
        self._seqs: Dict[Tuple[int, int], int] = {}
        self._state: Dict[Tuple[int, int], List[object]] = {}
        self._acc: Dict[Tuple[int, int], List[object]] = {}
        self._frag: Optional[pn.PlanNode] = None
        self._stream_scan: Optional[pn.ScanExec] = None
        self._streamable = False
        self._flushes = 0

    # -- setup -----------------------------------------------------------
    def _prepare(self) -> None:
        from .cluster import _resolve_driver_scans
        task = self.task
        plan = jg.decode_fragment(task.plan, task.partition,
                                  max(task.num_partitions, 1))
        plan = _resolve_driver_scans(plan, task)
        if task.runtime_filters_json:
            plan = jg.apply_task_runtime_filters(
                plan, task.runtime_filters_json)
        self._frag = plan
        self._stream_scan = _find_stream_scan(plan)
        stream_sids = {int(inp["stage"])
                       for inp in self.spec.get("inputs", ())
                       if inp["mode"] not in ("broadcast", "source")}
        is_producer = any(o["mode"] == "shuffle"
                          for o in self.spec.get("outputs", ()))
        self._streamable = streamable_fragment(plan, stream_sids,
                                               is_producer)

    # -- execution -------------------------------------------------------
    def _attach(self, tables: Dict[int, object],
                batch=None) -> pn.PlanNode:
        import pyarrow as pa
        plan = self._frag
        if self._stream_scan is not None:
            scan = self._stream_scan
            table = batch if batch is not None else _empty_of(scan)
            if scan.projection is not None:
                table = table.select(list(scan.projection))
            plan = jg._replace_subtree(
                plan, scan,
                dataclasses.replace(scan, out_schema=scan.schema,
                                    source=table, projection=None,
                                    format="memory"))
        # every declared stage input needs a table: absent ones (an
        # interval with no batches) attach empty
        full: Dict[int, object] = {}
        for inp in self.spec.get("inputs", ()):
            sid = int(inp["stage"])
            if sid == SOURCE_STAGE:
                continue
            got = tables.get(sid)
            if got is None:
                schema = _stage_input_schema(self._frag, sid)
                got = schema.empty_table() if schema is not None else \
                    pa.table({})
            full[sid] = got
        return jg.attach_stage_inputs(plan, full) if full else plan

    def _execute(self, plan: pn.PlanNode):
        from .local import LocalExecutor
        with events.collecting(self.recorder):
            return LocalExecutor().execute(plan)

    def _state_table(self, sid: int):
        import pyarrow as pa
        parts = [t for (s, _p), ts in sorted(self._state.items())
                 if s == sid for t in ts]
        if not parts:
            return None
        return pa.concat_tables(parts, promote_options="permissive") \
            if len(parts) > 1 else parts[0]

    def _interval_tables(self) -> Tuple[Dict[int, object], object]:
        """(stage-input tables, source-batch concatenation) for one
        marker interval, in deterministic (producer, seq) order."""
        import pyarrow as pa
        out: Dict[int, object] = {}
        by_sid: Dict[int, List[object]] = {}
        for (sid, _p) in sorted(self._acc):
            by_sid.setdefault(sid, []).extend(self._acc[(sid, _p)])
        source = None
        for sid, parts in by_sid.items():
            merged = pa.concat_tables(parts,
                                      promote_options="permissive") \
                if len(parts) > 1 else parts[0]
            if sid == SOURCE_STAGE:
                source = merged
            else:
                out[sid] = merged
        for inp in self.spec.get("inputs", ()):
            if inp["mode"] == "broadcast":
                sid = int(inp["stage"])
                st = self._state_table(sid)
                if st is not None:
                    out[sid] = st
        return out, source

    # -- output ----------------------------------------------------------
    def _push_table(self, table) -> None:
        task = self.task
        for out in self.spec.get("outputs", ()):
            addrs = out["addrs"]
            service = _service_of(out)
            dst_stage = int(out["stage"])
            if out["mode"] == "shuffle" and \
                    task.HasField("shuffle_write") and \
                    task.shuffle_write.num_channels > 1:
                sw = task.shuffle_write
                parts = jg.hash_partition_table(
                    table, list(sw.key_columns), sw.num_channels)
                for c, part in enumerate(parts):
                    if part.num_rows == 0:
                        continue
                    self._send(addrs[c % len(addrs)], service, dst_stage,
                               c % len(addrs), c, "batch", 0,
                               sh.encode_table(part))
            elif out["mode"] == "forward":
                p = task.partition % len(addrs)
                if table.num_rows:
                    self._send(addrs[p], service, dst_stage, p, -1,
                               "batch", 0, sh.encode_table(table))
            else:  # merge | broadcast: the whole output to every consumer
                if table.num_rows or out["mode"] == "broadcast":
                    blob = sh.encode_table(table)
                    for p, addr in enumerate(addrs):
                        self._send(addr, service, dst_stage, p, -1,
                                   "batch", 0, blob)
        self.rows_out += int(table.num_rows)

    def _push_marker(self, marker: int) -> None:
        for out in self.spec.get("outputs", ()):
            service = _service_of(out)
            addrs = out["addrs"]
            if out["mode"] == "forward":
                # a FORWARD consumer partition expects ONLY its
                # matching producer — a marker to a sibling would
                # address a channel that consumer never registered
                p = self.task.partition % len(addrs)
                targets = [(p, addrs[p])]
            else:
                targets = list(enumerate(addrs))
            for p, addr in targets:
                self._send(addr, service, int(out["stage"]), p, -1,
                           "marker", marker, b"")

    def _send(self, addr: str, service: str, dst_stage: int,
              dst_partition: int, channel: int, kind: str, marker: int,
              data: bytes) -> None:
        task = self.task
        key = (dst_stage, dst_partition)
        seq = self._seqs.get(key, 0)
        req = pb.PushRecordsRequest(
            job_id=task.job_id, src_stage=task.stage,
            src_partition=task.partition, dst_stage=dst_stage,
            dst_partition=dst_partition, channel=channel, seq=seq,
            attempt=self.generation, kind=kind, marker=marker,
            data=data)
        push_entry(addr, service, req, collector=self.recorder,
                   stop_check=lambda: self.cancel.is_set()
                   or self.worker._crashed)
        self._seqs[key] = seq + 1

    # -- main loop -------------------------------------------------------
    def run(self) -> None:
        worker = self.worker
        task = self.task
        error = ""
        fenced = False
        try:
            faults.inject("worker.task_exec",
                          key=f"{worker.worker_id}:s{task.stage}"
                              f"p{task.partition}")
            self._prepare()
            worker._report(task, "running")
            self.recorder.emit(
                EventType.TASK_START, job_id=task.job_id,
                stage=task.stage, partition=task.partition,
                attempt=task.attempt, worker=worker.worker_id,
                tenant=task.tenant)
            static_leaf = self._stream_scan is None and not any(
                inp["mode"] not in ("source",)
                for inp in self.spec.get("inputs", ()))
            if static_leaf:
                # a static leaf (broadcast build side): its content
                # never changes within the pipeline's lifetime — push
                # once at startup, then forward markers for alignment
                self._push_table(self._execute(self._attach({})))
            while not self.cancel.is_set() and not worker._crashed:
                item = self.aligner.next(timeout=0.2)
                if item is None:
                    continue
                kind, key, payload = item
                if kind == "closed":
                    return
                if kind == "state":
                    self._state.setdefault(key, []).append(
                        sh.decode_stream(payload.data))
                    continue
                if kind == "batch":
                    table = sh.decode_stream(payload.data)
                    if self._streamable:
                        tables = {key[0]: table} if key[0] != \
                            SOURCE_STAGE else {}
                        for inp in self.spec.get("inputs", ()):
                            if inp["mode"] == "broadcast":
                                st = self._state_table(int(inp["stage"]))
                                if st is not None:
                                    tables[int(inp["stage"])] = st
                        out = self._execute(self._attach(
                            tables, batch=table
                            if key[0] == SOURCE_STAGE else None))
                        self._push_table(out)
                    else:
                        self._acc.setdefault(key, []).append(table)
                    continue
                # marker alignment reached mid-flight
                marker, stats = key, payload
                faults.inject("streaming.marker",
                              key=f"s{task.stage}p{task.partition}"
                                  f":m{marker}")
                self.recorder.emit(
                    EventType.MARKER_ALIGN, job_id=task.job_id,
                    stage=task.stage, partition=task.partition,
                    marker=marker, wait_ms=stats["wait_ms"],
                    buffered_bytes=stats["buffered_bytes"])
                if not self._streamable and not static_leaf:
                    tables, source = self._interval_tables()
                    out = self._execute(self._attach(tables,
                                                     batch=source))
                    self._acc.clear()
                    self._push_table(out)
                # ship the buffered flight-recorder events at marker
                # cadence (numbered flush, deduped driver-side): a
                # long-lived task must not hoard its marker_align /
                # backpressure events until death — or overflow the
                # bounded collector and drop them entirely. The flush
                # goes out BEFORE the marker so the root cannot align
                # interval N until every task's interval-N events
                # (retraces, stalls) are already enqueued at the
                # driver — run_interval's sync barrier then makes them
                # visible to the trigger's profile deterministically
                self._flushes += 1
                worker._report(task, "running",
                               recorder=self.recorder,
                               report_seq=self._flushes)
                self._push_marker(marker)
                _record_metric("streaming.continuous.backlog_bytes",
                               self.aligner.backlog_bytes())
        except Fenced:
            fenced = True  # zombie: a relaunch owns the channels
        except faults.WorkerCrash:
            worker._die()
            fenced = True  # a "dead" process reports nothing
        except Exception as e:  # noqa: BLE001 — full cause to the driver
            error = f"{type(e).__name__}: {e}"
        finally:
            self.aligner.close()
            if not fenced and not worker._crashed:
                worker._report(task, "failed" if error else "succeeded",
                               error=error, rows=self.rows_out,
                               recorder=self.recorder)
            worker.continuous.unregister(self)


def _service_of(out: dict) -> str:
    from .cluster import _DRIVER_SERVICE, _WORKER_SERVICE
    return _DRIVER_SERVICE if out.get("driver") else _WORKER_SERVICE


def _empty_of(scan: pn.ScanExec):
    import pyarrow as pa
    from ..columnar.arrow_interop import spec_type_to_arrow
    return pa.Table.from_arrays(
        [pa.array([], type=spec_type_to_arrow(f.dtype))
         for f in scan.schema],
        names=[f.name for f in scan.schema])


def _stage_input_schema(plan: pn.PlanNode, sid: int):
    import pyarrow as pa
    from ..columnar.arrow_interop import spec_type_to_arrow
    for node in pn.walk_plan(plan):
        if isinstance(node, jg.StageInputExec) and node.stage_id == sid:
            return pa.schema([(f.name, spec_type_to_arrow(f.dtype))
                              for f in node.out_schema])
    return None


class ContinuousWorker:
    """Per-worker registry of resident tasks and their input channels;
    the PushRecords handler routes into it."""

    def __init__(self, worker):
        self.worker = worker
        self._lock = threading.Lock()
        self._tasks: Dict[Tuple[str, int, int], ResidentTask] = {}

    def start_task(self, task: pb.TaskDefinition, spec: dict,
                   cancel_ev: threading.Event) -> None:
        rt = ResidentTask(self.worker, task, spec, cancel_ev)
        key = (task.job_id, task.stage, task.partition)
        with self._lock:
            old = self._tasks.get(key)
            self._tasks[key] = rt
        if old is not None:
            old.cancel.set()
            old.aligner.close()
        threading.Thread(
            target=rt.run, daemon=True,
            name=f"resident-{task.stage}p{task.partition}").start()

    def unregister(self, rt: "ResidentTask") -> None:
        key = (rt.task.job_id, rt.task.stage, rt.task.partition)
        with self._lock:
            if self._tasks.get(key) is rt:
                del self._tasks[key]
        self.worker._unregister_running(key, rt.cancel)

    def offer(self, req: pb.PushRecordsRequest) -> pb.PushRecordsResponse:
        with self._lock:
            rt = self._tasks.get((req.job_id, req.dst_stage,
                                  req.dst_partition))
        if rt is None:
            return offer_response("unready")
        return offer_response(rt.aligner.offer(
            (req.src_stage, req.src_partition), req.attempt, req.seq,
            req.kind, req.marker, req.data))

    def clean_job(self, job_id: str) -> None:
        with self._lock:
            doomed = [rt for (j, _s, _p), rt in self._tasks.items()
                      if j == job_id]
        for rt in doomed:
            rt.cancel.set()
            rt.aligner.close()

    def stop_all(self) -> None:
        with self._lock:
            tasks = list(self._tasks.values())
        for rt in tasks:
            rt.cancel.set()
            rt.aligner.close()


# ---------------------------------------------------------------------------
# Driver side: the continuous job runner
# ---------------------------------------------------------------------------

_GEN_LOCK = threading.Lock()
_GENERATIONS: Dict[str, int] = {}


def next_generation(job_id: str) -> int:
    """Monotonic pipeline generation per job id: relaunched resident
    tasks carry a higher generation than any zombie of a previous
    incarnation, so the fencing in :class:`CreditInbox` refuses the
    zombie's late pushes."""
    with _GEN_LOCK:
        _GENERATIONS[job_id] = _GENERATIONS.get(job_id, 0) + 1
        return _GENERATIONS[job_id]


class _DriverContinuousJob:
    """The driver actor's registration record for one continuous job."""

    def __init__(self, runner: "ContinuousJobRunner"):
        self.runner = runner
        self.job_id = runner.job_id
        self.graph = runner.graph
        self.generation = runner.generation
        self.tenant = runner.tenant
        self.query_id = ""
        self.task_workers: Dict[Tuple[int, int], str] = {}
        self.running: set = set()
        self.ready = threading.Event()
        self.seen_reports: set = set()


class ContinuousJobRunner:
    """Owns one continuous pipeline: splits the resolved plan, has the
    driver dispatch resident stage tasks, feeds source record batches,
    injects markers, and collects the per-interval root output."""

    def __init__(self, cluster, node: pn.PlanNode,
                 num_partitions: int, job_id: str,
                 tenant: str = "default"):
        self.cluster = cluster
        self.job_id = job_id
        self.tenant = tenant or "default"
        self.conf = conf()
        self.generation = 0
        # events attribute to the CURRENT trigger's query profile:
        # captured at start, restamped at every run_interval — so a
        # slow trigger's verdict (analysis/anomaly.py) finds the
        # resident-task retraces/stalls that delayed IT, not the
        # query that started the pipeline. job_id still threads the
        # intervals into one pipeline timeline.
        self.query_id = ""
        self._cj: Optional["_DriverContinuousJob"] = None
        self.failed: Optional[str] = None
        self._fail_ev = threading.Event()
        self.graph = jg.split_job(node, num_partitions)
        self.root_aligner: Optional[AlignedInput] = None
        self._root_parts: Dict[int, List[object]] = {}
        self._aligned_markers: List[int] = []
        self._started = False
        self._stopped = False
        self.leaf_targets: List[Tuple[int, int, bool]] = []  # sid, nparts, is_stream
        self._leaf_addrs: Dict[Tuple[int, int], str] = {}
        self._src_seqs: Dict[Tuple[int, int], int] = {}
        self._rr = 0
        if self.graph is not None and not self._eligible():
            self.graph = None

    def _eligible(self) -> bool:
        g = self.graph
        if g is None or not g.root.on_driver:
            return False
        if _find_stream_scan(g.root.plan) is not None:
            return False  # the stream scan must live in a worker stage
        has_stream_leaf = False
        for stage in g.stages:
            if stage.on_driver:
                continue
            is_stream = _find_stream_scan(stage.plan) is not None
            if not stage.inputs:
                self.leaf_targets.append(
                    (stage.stage_id, stage.num_partitions, is_stream))
                has_stream_leaf = has_stream_leaf or is_stream
            elif is_stream:
                return False  # a non-leaf stream scan is unreachable
        return has_stream_leaf

    # -- lifecycle -------------------------------------------------------
    def start(self) -> bool:
        if self.graph is None:
            return False
        self.generation = next_generation(self.job_id)
        top = self.graph.root.inputs[0].stage_id
        top_parts = self.graph.stages[top].num_partitions
        self.root_aligner = AlignedInput(
            [(top, p) for p in range(top_parts)],
            attempt=self.generation,
            credit_bytes=self.conf["credit_bytes"],
            align_buffer_bytes=self.conf["align_buffer_bytes"])
        cj = _DriverContinuousJob(self)
        self._cj = cj
        from .. import profiler
        prof = profiler.current_profile()
        if prof is not None:
            self.query_id = cj.query_id = prof.query_id
        got = self.cluster.driver.handle.ask(
            lambda reply: ("continuous_start", (cj, reply)),
            timeout=30.0)
        if not got or self.failed:
            return False
        self._leaf_addrs = dict(got)
        if not cj.ready.wait(self.conf["start_timeout_s"]):
            self.fail("resident tasks did not start in time")
            return False
        self._started = True
        return True

    def fail(self, reason: str) -> None:
        if self.failed is None:
            self.failed = reason
        self._fail_ev.set()
        if self.root_aligner is not None:
            self.root_aligner.close()

    def stop(self) -> None:
        if self._stopped:
            return
        self._stopped = True
        try:
            self.cluster.driver.handle.send(
                ("continuous_stop", self.job_id))
        except Exception:  # noqa: BLE001 — driver may already be down
            pass
        if self.root_aligner is not None:
            self.root_aligner.close()

    # -- data plane ------------------------------------------------------
    def root_offer(self, req: pb.PushRecordsRequest
                   ) -> pb.PushRecordsResponse:
        if self.root_aligner is None:
            return offer_response("unready")
        return offer_response(self.root_aligner.offer(
            (req.src_stage, req.src_partition), req.attempt, req.seq,
            req.kind, req.marker, req.data))

    def _push_source(self, leaf: Tuple[int, int], kind: str,
                     marker: int, data: bytes) -> None:
        from .cluster import _WORKER_SERVICE
        addr = self._leaf_addrs.get(leaf)
        if addr is None:
            raise RuntimeError(f"no worker for leaf task {leaf}")
        seq = self._src_seqs.get(leaf, 0)
        req = pb.PushRecordsRequest(
            job_id=self.job_id, src_stage=SOURCE_STAGE,
            src_partition=0, dst_stage=leaf[0], dst_partition=leaf[1],
            channel=-1, seq=seq, attempt=self.generation, kind=kind,
            marker=marker, data=data)
        push_entry(addr, _WORKER_SERVICE, req,
                   query_id=self.query_id,
                   stop_check=lambda: self._fail_ev.is_set(),
                   on_stall=lambda: self._drain_root(0.0))
        self._src_seqs[leaf] = seq + 1

    def _drain_root(self, timeout: float) -> Optional[int]:
        """Pop whatever the root aligner has ready; returns an aligned
        marker id when one fires, else None. Runs both from the
        interval wait loop and from source-push credit stalls — the
        driver keeps consuming its inbox even while ITS pushes are the
        ones backpressured."""
        item = self.root_aligner.next(timeout=timeout)
        if item is None:
            return None
        kind, key, payload = item
        if kind == "closed":
            raise RuntimeError(
                f"continuous pipeline failed: "
                f"{self.failed or 'root channel closed'}")
        if kind in ("batch", "state"):
            self._root_parts.setdefault(key[1], []).append(
                sh.decode_stream(payload.data))
            return None
        marker, stats = key, payload
        events.emit(EventType.MARKER_ALIGN, query_id=self.query_id,
                    job_id=self.job_id,
                    stage=self.graph.root.stage_id, partition=0,
                    marker=marker, wait_ms=stats["wait_ms"],
                    buffered_bytes=stats["buffered_bytes"])
        self._aligned_markers.append(marker)
        return marker

    def push_batch(self, table) -> None:
        """Slice a source table into bounded record batches and spread
        them round-robin over the stream-leaf partitions."""
        rows = self.conf["max_batch_rows"]
        stream_leaves = [(sid, p) for sid, nparts, is_stream
                         in self.leaf_targets if is_stream
                         for p in range(nparts)]
        off = 0
        while off < table.num_rows:
            chunk = table.slice(off, rows)
            off += chunk.num_rows
            leaf = stream_leaves[self._rr % len(stream_leaves)]
            self._rr += 1
            self._push_source(leaf, "batch", 0, sh.encode_table(chunk))

    def run_interval(self, marker: int, table) -> object:
        """Push one source slice, inject marker N at every source, and
        block until the marker aligns at the root — returning the
        interval's output table (the running pipeline's snapshot for
        epoch N's commit)."""
        import pyarrow as pa
        from .local import LocalExecutor
        if self.failed:
            raise RuntimeError(f"continuous pipeline failed: "
                               f"{self.failed}")
        # restamp: this interval's events (driver marker emits AND the
        # resident-task flushes ingested below) attribute to the
        # trigger profile that is paying for the interval
        from .. import profiler
        prof = profiler.current_profile()
        if prof is not None:
            self.query_id = prof.query_id
            if self._cj is not None:
                self._cj.query_id = prof.query_id
        t0 = time.perf_counter()
        if table is not None and table.num_rows:
            self.push_batch(table)
        faults.inject("streaming.marker", key=f"inject:m{marker}")
        events.emit(EventType.MARKER_INJECT, query_id=self.query_id,
                    job_id=self.job_id, marker=marker)
        for sid, nparts, _is_stream in self.leaf_targets:
            for p in range(nparts):
                self._push_source((sid, p), "marker", marker, b"")
        deadline = time.time() + self.conf["marker_timeout_s"]
        while marker not in self._aligned_markers:
            if self.failed:
                raise RuntimeError(f"continuous pipeline failed: "
                                   f"{self.failed}")
            if self._drain_root(0.2) is None and \
                    time.time() > deadline:
                self.fail(f"marker {marker} did not align at the "
                          f"root in time")
                raise RuntimeError(self.failed)
        self._aligned_markers = [m for m in self._aligned_markers
                                 if m > marker]
        # interval output: (partition, seq)-ordered concatenation, so
        # the committed bytes are deterministic under any arrival order
        top = self.graph.root.inputs[0].stage_id
        parts = [t for p in sorted(self._root_parts)
                 for t in self._root_parts[p]]
        self._root_parts = {}
        schema = _stage_input_schema(self.graph.root.plan, top)
        if parts:
            merged = pa.concat_tables(parts,
                                      promote_options="permissive") \
                if len(parts) > 1 else parts[0]
        else:
            merged = schema.empty_table() if schema is not None \
                else pa.table({})
        from .cluster import _reattach_local_scans
        root_plan = jg.attach_stage_inputs(self.graph.root.plan,
                                           {top: merged})
        root_plan = _reattach_local_scans(root_plan,
                                          self.graph.scan_tables)
        result = LocalExecutor().execute(root_plan)
        # FIFO barrier on the driver actor: every resident task
        # flushed its interval-N events BEFORE pushing marker N, and
        # the root only aligned after every marker arrived — so by now
        # all flush reports sit in the actor inbox. Draining it makes
        # the interval's worker-side evidence visible to the trigger's
        # profile (anomaly classification at finalize) without racing.
        self.sync_reports()
        _record_metric("streaming.continuous.latency",
                       time.perf_counter() - t0)
        return result

    def sync_reports(self, timeout: float = 5.0) -> None:
        """Block until the driver actor has processed every message
        enqueued before this call (its inbox is FIFO) — i.e. every
        already-sent resident-task report and its piggybacked event
        flush has been ingested into the cluster event log."""
        try:
            self.cluster.driver.handle.ask(
                lambda reply: ("continuous_sync", reply),
                timeout=timeout)
        except Exception:  # noqa: BLE001 — telemetry-only barrier
            pass
