"""Pinned grow-only capacity buckets: warm paths stop crossing bucket
boundaries.

The PR 17 retrace ledger (exec/retrace.py) names ``capacity-bucket``
churn as the cause behind every continuous-join p99 outlier: a warmed
program re-traces because ``round_capacity`` re-derived a different
padded capacity for a slightly different row count. Following Tailwind's
SLO contract (arXiv:2604.28079) that warm paths must be structurally
incapable of recompiling, this registry replaces the per-call rounding
with per-program-fingerprint pins:

- the FIRST observation for a fingerprint pins its bucket at the plain
  ``round_capacity`` value (counted ``execution.capacity.pinned_count``);
- every later observation at or under the pin reuses it verbatim — a
  smaller batch never re-buckets downward, so oscillating input sizes
  around a bucket boundary stay on ONE compiled program;
- an observation OVER the pin must still run at a correct (larger)
  capacity — it gets the plain rounded bucket for that call — but the
  pin itself only grows after ``execution.capacity.grow_streak``
  CONSECUTIVE over-pin observations (sustained occupancy, not a single
  spike; counted ``execution.capacity.grow_count``). Transient spikes
  round to the same raw buckets every time, so their programs warm once
  and stay cached.

Keys use the same vocabulary as the retrace ledger
(:func:`exec.retrace.program_fingerprint` over a structural cache key),
so the PR 17 taxonomy verifies the fix: with pinning on, the
``capacity-bucket`` cause count stays flat after warmup.

Callers never import this module directly — the single policy choke
point is :func:`columnar.batch.bucket_capacity` (the capacity-policy
lint fails any direct ``round_capacity`` call outside it).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, Optional

__all__ = ["bucket_for", "snapshot", "clear", "reload", "enabled"]


class _Bucket:
    __slots__ = ("cap", "streak", "grows", "hits")

    def __init__(self, cap: int):
        self.cap = cap
        self.streak = 0   # consecutive over-pin observations
        self.grows = 0
        self.hits = 0


class _Conf:
    __slots__ = ("enabled", "grow_streak", "max_entries")

    def __init__(self):
        from ..config import get as config_get, truthy
        self.enabled = truthy("execution.capacity.pinning", "true")
        try:
            self.grow_streak = max(1, int(config_get(
                "execution.capacity.grow_streak", 3)))
        except (TypeError, ValueError):
            self.grow_streak = 3
        try:
            self.max_entries = max(16, int(config_get(
                "execution.capacity.max_entries", 4096)))
        except (TypeError, ValueError):
            self.max_entries = 4096


class BucketRegistry:
    """Process-global, bounded (LRU), thread-safe pin table."""

    def __init__(self):
        self._lock = threading.Lock()
        self._buckets: "OrderedDict[str, _Bucket]" = OrderedDict()
        self._conf: Optional[_Conf] = None
        self._pinned = 0
        self._grown = 0

    def _cfg(self) -> _Conf:
        c = self._conf
        if c is None:
            c = self._conf = _Conf()
        return c

    # -- the one decision point -----------------------------------------
    def bucket_for(self, key, n: int,
                   minimum: Optional[int] = None) -> int:
        """Padded capacity for ``n`` rows of the program identified by
        ``key`` (any hashable structural cache key). Grow-only with
        hysteresis; falls back to plain rounding when pinning is off."""
        from ..columnar.batch import round_capacity
        raw = round_capacity(n, minimum)
        cfg = self._cfg()
        if not cfg.enabled or key is None:
            return raw
        from . import retrace
        fp = retrace.program_fingerprint(key)
        with self._lock:
            b = self._buckets.get(fp)
            if b is None:
                while len(self._buckets) >= cfg.max_entries:
                    self._buckets.popitem(last=False)
                self._buckets[fp] = _Bucket(raw)
                self._pinned += 1
                self._note_metric("execution.capacity.pinned_count")
                return raw
            self._buckets.move_to_end(fp)
            b.hits += 1
            if raw <= b.cap:
                b.streak = 0
                return b.cap
            b.streak += 1
            if b.streak >= cfg.grow_streak:
                b.cap = raw
                b.streak = 0
                b.grows += 1
                self._grown += 1
                self._note_metric("execution.capacity.grow_count")
            return raw

    @staticmethod
    def _note_metric(name: str) -> None:
        try:
            from ..metrics import record as _record_metric
            _record_metric(name, 1)
        except Exception:  # noqa: BLE001 — observability never breaks exec
            pass

    # -- observability ---------------------------------------------------
    def snapshot(self) -> Dict[str, object]:
        cfg = self._cfg()
        with self._lock:
            return {
                "enabled": cfg.enabled,
                "grow_streak": cfg.grow_streak,
                "entries": len(self._buckets),
                "pinned_count": self._pinned,
                "grow_count": self._grown,
                "buckets": [
                    {"fp": fp, "cap": b.cap, "hits": b.hits,
                     "grows": b.grows, "streak": b.streak}
                    for fp, b in list(self._buckets.items())[-32:]],
            }

    def clear(self) -> None:
        with self._lock:
            self._buckets.clear()
            self._pinned = 0
            self._grown = 0

    def reload(self) -> None:
        """Drop pins AND re-read config (tests / bench A-B knobs flip
        ``SAIL_EXECUTION__CAPACITY__PINNING`` between runs)."""
        with self._lock:
            self._conf = None
            self._buckets.clear()
            self._pinned = 0
            self._grown = 0


REGISTRY = BucketRegistry()


def bucket_for(key, n: int, minimum: Optional[int] = None) -> int:
    return REGISTRY.bucket_for(key, n, minimum)


def snapshot() -> Dict[str, object]:
    return REGISTRY.snapshot()


def clear() -> None:
    REGISTRY.clear()


def reload() -> None:
    REGISTRY.reload()


def enabled() -> bool:
    return REGISTRY._cfg().enabled
