"""Per-stage backend router: native C++ vs single-device XLA vs mesh.

Reference role: the Presto-on-GPUs result (arXiv:2606.24647) that
per-operator routing by cost beats a single execution substrate,
grafted onto the stage vocabulary PR 6 built: every fused pipeline
(``plan/stages.py FusedStage``) gets an explicit backend decision at
stage-split time instead of the implicit try-native-then-XLA ladder.

Backends:

- ``native``  the fused C++ host kernel (``sail_tpu/native/``) — wins
  when a stage's wall time is compile/dispatch rather than compute
  (per-process XLA trace+compile, per-op dispatch overhead at small
  batch sizes);
- ``xla``     the single-device jitted program (the default substrate);
- ``mesh``    the 8-device SPMD program (``parallel/mesh_exec.py``) —
  a PLAN-level decision (stage ``-1``): the whole job graph compiles
  into one shard_map program, worth its dispatch cost only above a
  row-volume floor (``execution.backend.mesh_min_rows``).

Decisions are pure functions of (stage fingerprint, configuration,
the bounded observation table this module keeps) — deterministic per
fingerprint — and every decision is recorded in the flight recorder
(``backend_route`` events) and on the query profile, rendered by
EXPLAIN / EXPLAIN ANALYZE / FORMAT JSON. ``execution.backend.force``
(session mirror ``spark.sail.execution.backend.force``) overrides
everything: ``native`` | ``xla`` | ``mesh`` | "" (route by cost).

The observation table is fed by the executor (PR 10's critical-path
categories at stage granularity): per stage fingerprint it holds the
compile and execute wall time of prior runs, so a stage whose observed
time is compile-dominated routes to the native path with the
``compile-bound`` reason instead of the static ``cost-model`` guess.
"""

from __future__ import annotations

import hashlib
import threading
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, NamedTuple, Optional

BACKENDS = ("native", "xla", "mesh")

#: reason vocabulary (mirrored in the ``backend_route`` event comment):
#: forced | cost-model | compile-bound | dispatch-bound | unsupported |
#: default | unavailable | slo-feedback

_LOCK = threading.Lock()
#: stage fingerprint digest -> [compile_s, exec_s, runs, recent exec_s
#: samples (bounded deque — the p99 the SLO feedback loop reads)]
_OBS: Dict[str, List] = {}
_OBS_MAX = 512
_OBS_SAMPLES = 64


class Decision(NamedTuple):
    stage: int          # FusedStage sid; -1 = the plan-level mesh gate
    kind: str           # stage kind (aggregate/sort/...) or "plan"
    backend: str        # native | xla | mesh
    reason: str

    def to_dict(self) -> dict:
        return {"stage": self.stage, "kind": self.kind,
                "backend": self.backend, "reason": self.reason}


# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------

def forced_backend(session_conf=None) -> str:
    """``spark.sail.execution.backend.force`` (session) over
    ``execution.backend.force`` (app config); "" = route by cost."""
    from ..config import get as config_get
    value = None
    if session_conf is not None:
        get = getattr(session_conf, "get", None)
        value = get("spark.sail.execution.backend.force") \
            if get is not None else None
    if value is None or value == "":
        value = config_get("execution.backend.force", "")
    value = str(value or "").strip().lower()
    return value if value in BACKENDS else ""


def slo_feedback_enabled(session_conf=None) -> bool:
    """``spark.sail.execution.backend.slo_feedback`` (session) over
    ``execution.backend.slo_feedback``: the router-as-feedback-
    controller gate. On by default, but inert until the SLO monitor
    has evaluated a burn rate for the session's tenant."""
    from ..config import truthy, truthy_value
    if session_conf is not None:
        get = getattr(session_conf, "get", None)
        value = get("spark.sail.execution.backend.slo_feedback") \
            if get is not None else None
        if value is not None:
            return truthy_value(value)
    return truthy("execution.backend.slo_feedback", "true")


def slo_context(session_conf=None) -> Optional[dict]:
    """The SLO feedback loop's decision inputs for ONE session, read
    once per decision batch: the tenant's latency target and its LAST
    EVALUATED burn rate (``analysis/anomaly.py SLO_MONITOR``). The
    router never triggers an evaluation — it consumes recorded state,
    so decisions stay pure functions of (fingerprint, observation
    table, this context) and replay identically. None = feedback off
    (gate disabled, SLO disabled, or no burn evaluated yet)."""
    if not slo_feedback_enabled(session_conf):
        return None
    try:
        from ..analysis import anomaly
        conf = anomaly._slo_conf()
        if not conf["enabled"]:
            return None
        tenant = None
        if session_conf is not None:
            get = getattr(session_conf, "get", None)
            tenant = get("spark.sail.tenant") if get is not None else None
        if not tenant:
            from ..config import get as config_get
            tenant = str(config_get("admission.tenant", "default")
                         or "default")
        burn = anomaly.SLO_MONITOR.burn_for(str(tenant))
        if burn is None:
            return None
        target_ms, objective = anomaly.SLO_MONITOR.objective_for(
            str(tenant), conf)
        return {"tenant": str(tenant), "target_ms": float(target_ms),
                "objective": float(objective), "burn": float(burn),
                "min_runs": 8}
    except Exception:  # noqa: BLE001 — feedback is advisory, never fatal
        return None


def _slo_violation(obs: Optional[dict],
                   slo_ctx: Optional[dict]) -> bool:
    """True when a stage's OBSERVED p99 breaks its tenant's target
    while the tenant's error budget is burning faster than sustainable
    (burn ≥ 1) — the re-route trigger."""
    if not slo_ctx or obs is None:
        return False
    p99 = obs.get("p99_ms")
    return (p99 is not None
            and obs.get("runs", 0) >= int(slo_ctx.get("min_runs", 8))
            and p99 > float(slo_ctx["target_ms"])
            and float(slo_ctx.get("burn", 0.0)) >= 1.0)


def mesh_min_rows() -> int:
    from ..config import get as config_get
    try:
        return max(0, int(config_get("execution.backend.mesh_min_rows",
                                     65536)))
    except (TypeError, ValueError):
        return 65536


# ---------------------------------------------------------------------------
# observations (critical-path categories at stage granularity)
# ---------------------------------------------------------------------------

def obs_key(fingerprint) -> str:
    """Stable digest of a stage fingerprint (structural; never data)."""
    return hashlib.sha256(repr(fingerprint).encode()).hexdigest()[:16]


def stage_obs_key(stage) -> str:
    """THE observation key for one fused stage: the compute operators'
    fingerprints only (no source leaves) — exactly what the executor
    records under (``[p] + chain``), so decisions and observations can
    never key apart."""
    from ..plan import stages as pst
    return obs_key(tuple(pst.node_fingerprint(n) for n in stage.nodes
                         if not pst.is_leaf(n)))


def note_stage(key: str, compile_s: float = 0.0,
               exec_s: float = 0.0) -> None:
    """Record one observed execution of a stage: ``exec_s`` is the
    stage's wall, ``compile_s`` the portion the profiler attributed to
    JIT compilation inside it."""
    with _LOCK:
        obs = _OBS.get(key)
        if obs is None:
            obs = _OBS[key] = [0.0, 0.0, 0.0,
                               deque(maxlen=_OBS_SAMPLES)]
            while len(_OBS) > _OBS_MAX:
                _OBS.pop(next(iter(_OBS)))
        obs[0] += max(0.0, float(compile_s))
        obs[1] += max(0.0, float(exec_s))
        obs[2] += 1.0
        obs[3].append(max(0.0, float(exec_s)))


@contextmanager
def observing(key: str):
    """Measure one stage execution into the observation table: wall
    time plus the portion the active profile attributed to JIT
    compilation inside the block (PR 10's compile category at stage
    granularity)."""
    import time as _time

    from .. import profiler
    prof = profiler.current_profile()
    c0 = prof.compile_ms if prof is not None else 0.0
    t0 = _time.perf_counter()
    try:
        yield
    finally:
        exec_s = _time.perf_counter() - t0
        compile_s = ((prof.compile_ms - c0) / 1000.0) \
            if prof is not None else 0.0
        note_stage(key, compile_s=compile_s, exec_s=exec_s)


def observed(key: str) -> Optional[dict]:
    with _LOCK:
        obs = _OBS.get(key)
        if obs is None or obs[2] <= 0:
            return None
        samples = sorted(obs[3])
        out = {"compile_s": obs[0], "exec_s": obs[1],
               "runs": int(obs[2])}
    if samples:
        out["p50_ms"] = samples[len(samples) // 2] * 1000.0
        out["p99_ms"] = samples[
            min(len(samples) - 1, int(len(samples) * 0.99))] * 1000.0
    return out


def clear_observations() -> None:
    with _LOCK:
        _OBS.clear()


# ---------------------------------------------------------------------------
# decisions
# ---------------------------------------------------------------------------

def _native_ok() -> bool:
    try:
        from .. import native as _native
        return _native.native_active()
    except Exception:  # noqa: BLE001 — no toolchain = no native path
        return False


def decide_stage(stage, force: str = "",
                 native_ok: Optional[bool] = None,
                 slo_ctx: Optional[dict] = None) -> Decision:
    """Route ONE fused stage (``plan/stages.py FusedStage``). Only
    aggregate stages have a native substrate today; everything else is
    the XLA program the stage compiler emits.

    With an ``slo_ctx`` (see :func:`slo_context`), the router acts as a
    feedback controller: a stage whose observed p99 violates its
    tenant's target while the error budget burns re-routes to the
    alternative substrate (``slo-feedback``) — unless the observation
    says compilation dominates, in which case native IS the fix and the
    cost-model route stands."""
    from ..plan import stages as pst

    kind = stage.kind
    native_eligible = (
        kind == "aggregate"
        and pst.agg_absorbs_chain(stage.root)
        and (native_ok if native_ok is not None else _native_ok()))
    if force:
        if force == "native" and not native_eligible:
            return Decision(stage.sid, kind, "xla", "unavailable")
        if force == "mesh":
            # mesh is a plan-level substrate; per-stage it means "do
            # not take the native detour"
            return Decision(stage.sid, kind, "xla", "forced")
        return Decision(stage.sid, kind, force, "forced")
    if native_eligible:
        obs = observed(stage_obs_key(stage))
        if obs is not None and obs["compile_s"] > 0.5 * obs["exec_s"]:
            # the stage's observed wall is dominated by compilation,
            # exactly the cost XLA re-pays per process/shape and the
            # native row loop does not
            return Decision(stage.sid, kind, "native", "compile-bound")
        if _slo_violation(obs, slo_ctx):
            # the native route is not holding the tenant's p99 and the
            # cost is not compile: give the stage back to the XLA
            # substrate until the rolling window clears the target
            return Decision(stage.sid, kind, "xla", "slo-feedback")
        return Decision(stage.sid, kind, "native", "cost-model")
    if kind == "aggregate":
        # not native-eligible: host/DISTINCT aggregates or no toolchain
        return Decision(stage.sid, kind, "xla", "unsupported")
    return Decision(stage.sid, kind, "xla", "default")


def decide_split(split, force: str = "",
                 slo_ctx: Optional[dict] = None) -> List[Decision]:
    """Route every stage of one ``StageSplit`` (deterministic per plan
    structure + configuration + observation table + SLO context)."""
    native_ok = _native_ok()
    return [decide_stage(s, force=force, native_ok=native_ok,
                         slo_ctx=slo_ctx)
            for s in split.stages]


def decide_plan(plan, nparts: int, force: str = "",
                mode: str = "auto",
                slo_ctx: Optional[dict] = None,
                floor: Optional[int] = None) -> Decision:
    """The plan-level mesh-vs-local gate (stage ``-1``): the SPMD
    program's fixed dispatch/compile cost is only worth paying above a
    row-volume floor. ``mode`` is the ``execution.mesh`` knob — "force"
    bypasses the cost gate (tests pin the mesh path with it).

    With an ``slo_ctx``, a plan the floor would keep local PRE-SPLITS
    to the mesh (``slo-feedback``) when its per-fingerprint latency
    baseline (``analysis/anomaly.py BASELINES`` — the PR 12
    ``query.latency`` histograms) shows a p99 over the tenant's target
    while the error budget burns: sharding the input across devices is
    the pre-split lever the local substrate does not have.

    ``floor`` is the row-volume gate as an injected signal
    (``execution.backend.mesh_min_rows``): replay passes the recorded
    value, the live path defaults from config — the decision itself
    never re-reads configuration."""
    if force == "mesh":
        return Decision(-1, "plan", "mesh", "forced")
    if force in ("xla", "native"):
        return Decision(-1, "plan", force, "forced")
    if nparts < 2 and mode != "force":
        return Decision(-1, "plan", "xla", "unavailable")
    if mode == "force":
        return Decision(-1, "plan", "mesh", "forced")
    floor = mesh_min_rows() if floor is None else floor
    if floor:
        est = _plan_input_rows(plan)
        if est is not None and est < floor:
            if _slo_violation(_plan_latency_obs(plan), slo_ctx):
                return Decision(-1, "plan", "mesh", "slo-feedback")
            # estimated INPUT volume too small for the SPMD program's
            # fixed dispatch + compile cost: stay on the local
            # substrate. Input, not root output — the cost being gated
            # scales with the rows the program moves, and a selective
            # filter or aggregate shrinks only the output.
            return Decision(-1, "plan", "xla", "dispatch-bound")
    return Decision(-1, "plan", "mesh", "cost-model")


def _plan_latency_obs(plan) -> Optional[dict]:
    """The plan's observed latency in :func:`_slo_violation`'s
    vocabulary, read from the per-fingerprint baseline store (never
    mutated here)."""
    try:
        from ..analysis import anomaly
        from ..plan import stages as pst
        fp = pst.plan_fingerprint_hash(plan)
        if not fp:
            return None
        base = anomaly.BASELINES.p99_for(fp)
        if base is None:
            return None
        count, p99_ms = base
        return {"runs": count, "p99_ms": p99_ms}
    except Exception:  # noqa: BLE001 — no baseline: no feedback
        return None


def _plan_input_rows(plan) -> Optional[float]:
    """Largest estimated source cardinality feeding the plan (the
    volume the SPMD program would actually move). None = no grounded
    estimate anywhere — attempt the mesh, matching the pre-router
    behavior for unknown sizes."""
    try:
        from ..plan import join_reorder as jr
        from ..plan import nodes as pn
        best: Optional[float] = None
        for node in pn.walk_plan(plan):
            if isinstance(node, pn.ScanExec):
                rows = jr._scan_rows(node)
                # the model's default for size-less scans is not
                # evidence of smallness; only a grounded estimate may
                # keep a plan off the mesh
                if rows is not None and rows != jr._DEFAULT_ROWS:
                    best = rows if best is None else max(best, rows)
        return best
    except Exception:  # noqa: BLE001 — no estimate: attempt the mesh
        return None


# ---------------------------------------------------------------------------
# recording
# ---------------------------------------------------------------------------

def record_decisions(decisions) -> None:
    """Flight recorder + metrics + query profile, for replayability:
    the routing a query ran under must be reconstructible from the
    event log alone."""
    from .. import profiler
    decisions = list(decisions)
    if not decisions:
        return
    try:
        from .. import events as _events
        for d in decisions:
            _events.emit(_events.EventType.BACKEND_ROUTE, stage=d.stage,
                         kind=d.kind, backend=d.backend, reason=d.reason)
    except Exception:  # noqa: BLE001 — telemetry must never break queries
        pass
    try:
        from ..metrics import record as _record_metric
        for d in decisions:
            _record_metric("execution.backend.route_count", 1,
                           backend=d.backend, reason=d.reason)
    except Exception:  # noqa: BLE001
        pass
    profiler.note_backend_routes([d.to_dict() for d in decisions])
