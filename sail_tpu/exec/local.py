"""Local (single-process) executor.

Reference role: LocalJobRunner + DataFusion's operator execution
(crates/sail-execution/src/job_runner.rs:47-66) — here the operators are
interpreted on the host while all bulk compute runs as jnp/XLA ops over
DeviceBatches. Batches use positional column names (c0, c1, …) internally;
plan-schema names are applied only at the Arrow boundary (duplicate output
names are legal in SQL).

Host↔device sync points (kept deliberately few):
- aggregate output shrink (live group count → smaller padded capacity)
- join build-duplicate check + expand-capacity computation
- scalar subquery evaluation
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from ..columnar import arrow_interop as ai
from ..columnar.batch import (Column, DeviceBatch, HostBatch, empty_batch,
                              physical_jnp_dtype, round_capacity)
from ..ops import aggregate as aggk
from ..ops import join as joink
from ..ops import sort as sortk
from ..plan import nodes as pn
from ..plan import rex as rx
from ..plan.compiler import Compiled, ExprCompiler, HostFallback
from ..spec import data_type as dt
from ..spec.literal import Literal as LV


class ExecutionError(RuntimeError):
    pass


def _col_name(i: int) -> str:
    return f"c{i}"


class LocalExecutor:
    def __init__(self, config: Optional[dict] = None):
        self.config = config or {}
        self._subquery_cache: Dict[int, LV] = {}

    # ------------------------------------------------------------------
    def execute(self, plan: pn.PlanNode) -> pa.Table:
        """Run a plan to an Arrow table with the plan's output names."""
        self._pre_eval_subqueries(plan)
        batch = self.run(plan)
        table = ai.to_arrow(batch)
        names = [f.name for f in plan.schema]
        return table.rename_columns(names)

    def run(self, plan: pn.PlanNode) -> HostBatch:
        method = getattr(self, "_exec_" + type(plan).__name__, None)
        if method is None:
            raise ExecutionError(f"no executor for {type(plan).__name__}")
        return method(plan)

    # ------------------------------------------------------------------
    # scalar subqueries
    # ------------------------------------------------------------------
    def _pre_eval_subqueries(self, plan: pn.PlanNode):
        for node in pn.walk_plan(plan):
            for r in _node_rex(node):
                for sub in rx.walk(r):
                    if isinstance(sub, rx.RScalarSubquery) and \
                            id(sub) not in self._subquery_cache:
                        self._subquery_cache[id(sub)] = self._eval_scalar(sub)

    def _eval_scalar(self, sub: rx.RScalarSubquery) -> LV:
        inner = LocalExecutor(self.config)
        inner._subquery_cache = self._subquery_cache
        table = inner.execute(sub.plan)
        if table.num_rows == 0:
            return LV(sub.dtype, None)
        if table.num_rows > 1:
            raise ExecutionError("scalar subquery returned more than one row")
        v = table.column(0)[0].as_py()
        return LV(sub.dtype, v)

    # ------------------------------------------------------------------
    # expression plumbing
    # ------------------------------------------------------------------
    def _compiler(self, batch: HostBatch, schema: pn.Schema) -> ExprCompiler:
        types = [f.dtype for f in schema]
        dicts = {}
        for i in range(len(schema)):
            name = _col_name(i)
            if name in batch.dicts:
                dicts[i] = batch.dicts[name]
        return ExprCompiler(types, dicts, self._subquery_cache)

    @staticmethod
    def _cols(batch: HostBatch) -> List:
        dev = batch.device
        return [(dev.columns[_col_name(i)].data, dev.columns[_col_name(i)].validity)
                for i in range(len(dev.columns))]

    def _eval(self, compiled: Compiled, batch: HostBatch):
        return compiled.fn(self._cols(batch))

    # ------------------------------------------------------------------
    # leaves
    # ------------------------------------------------------------------
    def _exec_ScanExec(self, p: pn.ScanExec) -> HostBatch:
        from ..io.formats import read_table
        if p.source is not None:
            table = p.source
            if p.projection is not None:
                table = table.select(list(p.projection))
        else:
            table = read_table(p.format, p.paths, dict(p.options),
                               columns=p.projection)
            table = self._apply_declared_schema(table, p.schema)
        hb = ai.from_arrow(table)
        return _positional(hb)

    @staticmethod
    def _apply_declared_schema(table: pa.Table, schema: pn.Schema) -> pa.Table:
        """Reorder/cast file data to the plan's declared schema (a user-set
        read schema may differ from the file's natural order and types)."""
        arrays = []
        names = []
        for f in schema:
            at = ai.spec_type_to_arrow(f.dtype)
            if f.name in table.column_names:
                col = table.column(f.name)
                if col.type != at:
                    col = col.cast(at, safe=False)
            else:
                col = pa.nulls(table.num_rows, type=at)
            arrays.append(col)
            names.append(f.name)
        return pa.table(dict(zip(names, arrays)))

    def _exec_OneRowExec(self, p: pn.OneRowExec) -> HostBatch:
        sel = np.zeros(8, dtype=bool)
        sel[0] = True
        return HostBatch(DeviceBatch({}, jnp.asarray(sel)), {})

    def _exec_ValuesExec(self, p: pn.ValuesExec) -> HostBatch:
        arrays = []
        for j, f in enumerate(p.out_schema):
            vals = [row[j] for row in p.rows]
            at = ai.spec_type_to_arrow(f.dtype)
            arrays.append(pa.array([v.value for v in vals], type=at))
        table = pa.table(dict(zip([_col_name(j) for j in range(len(arrays))], arrays)))
        return ai.from_arrow(table)

    def _exec_RangeExec(self, p: pn.RangeExec) -> HostBatch:
        n = max(0, -(-(p.end - p.start) // p.step)) if p.step else 0
        vals = np.arange(p.start, p.end, p.step, dtype=np.int64)
        table = pa.table({"c0": pa.array(vals, type=pa.int64())})
        return ai.from_arrow(table)

    # ------------------------------------------------------------------
    # unary operators
    # ------------------------------------------------------------------
    def _exec_ProjectExec(self, p: pn.ProjectExec) -> HostBatch:
        child = self.run(p.input)
        comp = self._compiler(child, p.input.schema)
        dev = child.device
        out_cols: Dict[str, Column] = {}
        out_dicts: Dict[str, pa.Array] = {}
        for i, (name, e) in enumerate(p.exprs):
            c = comp.compile(e)
            data, validity = self._eval(c, child)
            key = _col_name(i)
            odt = rx.rex_type(e)
            jdt = physical_jnp_dtype(odt)
            if data.dtype != jnp.dtype(jdt):
                data = data.astype(jdt)
            out_cols[key] = Column(data, validity, odt)
            if c.dictionary is not None:
                out_dicts[key] = c.dictionary
        if not out_cols:  # SELECT of zero columns
            return HostBatch(DeviceBatch({}, dev.sel), {})
        return HostBatch(DeviceBatch(out_cols, dev.sel), out_dicts)

    def _exec_FilterExec(self, p: pn.FilterExec) -> HostBatch:
        child = self.run(p.input)
        comp = self._compiler(child, p.input.schema)
        c = comp.compile(p.condition)
        data, validity = self._eval(c, child)
        keep = data.astype(jnp.bool_)
        if validity is not None:
            keep = keep & validity
        dev = child.device
        return HostBatch(dev.with_sel(dev.sel & keep), child.dicts)

    def _exec_LimitExec(self, p: pn.LimitExec) -> HostBatch:
        child = self.run(p.input)
        dev = child.device
        if p.offset == -1:  # tail
            n = int(dev.num_rows())
            off = max(0, n - (p.limit or 0))
            out = sortk.limit(dev, p.limit or 0, off)
        else:
            out = sortk.limit(dev, p.limit if p.limit is not None else dev.capacity,
                              p.offset)
        return HostBatch(out, child.dicts)

    def _exec_SortExec(self, p: pn.SortExec) -> HostBatch:
        child = self.run(p.input)
        comp = self._compiler(child, p.input.schema)
        keys = []
        for k in p.keys:
            c = comp.compile(k.expr)
            data, validity = self._eval(c, child)
            kdt = rx.rex_type(k.expr)
            if c.dictionary is not None:
                ranks = ai.dictionary_ranks(c.dictionary)
                data = jnp.asarray(ranks)[data]
                kdt = dt.IntegerType()
            keys.append((data, validity, kdt, k.ascending, k.nulls_first))
        perm = sortk.lexsort_perm(keys, child.device.sel)
        out = sortk.take_batch(child.device, perm)
        if p.limit is not None:
            out = sortk.limit(out, p.limit)
            out = _shrink(out, p.limit)
        return HostBatch(out, child.dicts)

    def _exec_AggregateExec(self, p: pn.AggregateExec) -> HostBatch:
        child = self.run(p.input)
        dev = child.device
        key_cols = [dev.columns[_col_name(i)] for i in p.group_indices]
        if p.group_indices:
            max_groups = p.max_groups_hint or dev.capacity
        else:
            max_groups = 1
        ctx, sorted_keys = aggk.group_rows(key_cols, dev.sel, max_groups)
        if p.max_groups_hint and bool(aggk.group_overflow(ctx)):
            ctx, sorted_keys = aggk.group_rows(key_cols, dev.sel, dev.capacity)
        out_cols: Dict[str, Column] = {}
        out_dicts: Dict[str, pa.Array] = {}
        gsel = aggk.group_sel(ctx)
        gkeys = aggk.group_key_output(ctx, sorted_keys)
        for j, gi in enumerate(p.group_indices):
            key = _col_name(j)
            out_cols[key] = gkeys[j]
            src = _col_name(gi)
            if src in child.dicts:
                out_dicts[key] = child.dicts[src]
        ng = len(p.group_indices)
        for j, a in enumerate(p.aggs):
            key = _col_name(ng + j)
            arg = None if a.arg is None else dev.columns[_col_name(a.arg)]
            col = self._run_agg(ctx, a, arg)
            out_cols[key] = col
            if a.arg is not None and a.fn in ("min", "max", "first", "last"):
                src = _col_name(a.arg)
                if src in child.dicts:
                    out_dicts[key] = child.dicts[src]
        out = DeviceBatch(out_cols, gsel) if out_cols else \
            DeviceBatch({}, gsel)
        # shrink to the live group count (host sync)
        n_groups = int(ctx.num_groups)
        out = _shrink(out, n_groups)
        return HostBatch(out, out_dicts)

    def _run_agg(self, ctx, a: pn.AggSpec, arg: Optional[Column]) -> Column:
        if a.fn == "count":
            return aggk.agg_count(ctx, arg)
        if a.fn == "sum":
            return aggk.agg_sum(ctx, arg, a.out_dtype)
        if a.fn == "min":
            return aggk.agg_min_max(ctx, arg, is_min=True)
        if a.fn == "max":
            return aggk.agg_min_max(ctx, arg, is_min=False)
        if a.fn == "first":
            return aggk.agg_first_last(ctx, arg, is_first=True,
                                       ignore_nulls=a.ignore_nulls)
        if a.fn == "last":
            return aggk.agg_first_last(ctx, arg, is_first=False,
                                       ignore_nulls=a.ignore_nulls)
        if a.fn == "bool_and":
            return aggk.agg_bool(ctx, arg, is_any=False)
        if a.fn == "bool_or":
            return aggk.agg_bool(ctx, arg, is_any=True)
        raise ExecutionError(f"aggregate {a.fn!r} not implemented")

    # ------------------------------------------------------------------
    # joins
    # ------------------------------------------------------------------
    def _exec_JoinExec(self, p: pn.JoinExec) -> HostBatch:
        left = self.run(p.left)
        right = self.run(p.right)
        jt = p.join_type
        if jt == "cross" and not p.left_keys:
            out = self._cross_join(p, left, right)
            if p.residual is not None:
                comb_schema = tuple(p.left.schema) + tuple(p.right.schema)
                comp = ExprCompiler(
                    [f.dtype for f in comb_schema],
                    {i: out.dicts[_col_name(i)] for i in range(len(comb_schema))
                     if _col_name(i) in out.dicts},
                    self._subquery_cache)
                c = comp.compile(p.residual)
                data, validity = self._eval(c, out)
                keep = data.astype(jnp.bool_)
                if validity is not None:
                    keep = keep & validity
                out = HostBatch(out.device.with_sel(out.device.sel & keep),
                                out.dicts)
            return out
        if jt == "right":
            flipped = pn.JoinExec(p.right, p.left, "left", p.right_keys,
                                  p.left_keys,
                                  _flip_residual(p.residual, len(p.left.schema),
                                                 len(p.right.schema)))
            out = self._join(flipped, right, left)
            return _reorder_right(out, len(p.right.schema), len(p.left.schema))
        return self._join(p, left, right)

    def _join(self, p: pn.JoinExec, left: HostBatch, right: HostBatch) -> HostBatch:
        jt = p.join_type
        lcomp = self._compiler(left, p.left.schema)
        rcomp = self._compiler(right, p.right.schema)
        lkeys, rkeys, lkey_dicts = [], [], []
        for lk, rk in zip(p.left_keys, p.right_keys):
            lc = lcomp.compile(lk)
            rc = rcomp.compile(rk)
            ld, lv = self._eval(lc, left)
            rd, rv = self._eval(rc, right)
            ktype = rx.rex_type(lk)
            if lc.dictionary is not None or rc.dictionary is not None:
                merged, ra, rb = ai.unify_dictionaries(lc.dictionary, rc.dictionary)
                ld = jnp.asarray(ra)[ld]
                rd = jnp.asarray(rb)[rd]
                ktype = dt.IntegerType()
            lkeys.append(Column(ld, lv, ktype))
            rkeys.append(Column(rd, rv, ktype))
        # build on the right side
        for seed in range(4):
            bt = joink.build_side(rkeys, right.device.sel, seed)
            if bt.exact or not bool(joink.hash_ambiguous(bt, rkeys)):
                break
        else:
            raise ExecutionError("could not build unambiguous hash join")
        ranges = joink.probe_ranges(bt, lkeys, left.device.sel,
                                    build_key_cols=rkeys if not bt.exact else None)
        merged_dicts = dict(left.dicts)
        right_names = {}
        n_left = len(p.left.schema)
        # rename right columns to combined positions
        r_dev_cols = {}
        for i in range(len(p.right.schema)):
            r_dev_cols[_col_name(n_left + i)] = right.device.columns[_col_name(i)]
            if _col_name(i) in right.dicts:
                merged_dicts[_col_name(n_left + i)] = right.dicts[_col_name(i)]
        build_payload = DeviceBatch(r_dev_cols, right.device.sel)
        build_names = list(r_dev_cols.keys()) if jt not in ("semi", "anti") else []

        has_dup = bool(joink.has_duplicate_build_keys(bt))
        if not has_dup and p.residual is None:
            out_dev = joink.join_unique(bt, ranges, left.device, build_payload,
                                        jt, build_names)
            out_dicts = merged_dicts if jt not in ("semi", "anti") else left.dicts
            return HostBatch(out_dev, out_dicts)
        return self._join_expand(p, left, right, bt, ranges, build_payload,
                                 build_names, merged_dicts)

    def _join_expand(self, p: pn.JoinExec, left: HostBatch, right: HostBatch,
                     bt, ranges, build_payload, build_names, merged_dicts) -> HostBatch:
        jt = p.join_type
        n_left = len(p.left.schema)
        total = int(joink.join_output_count(ranges, left.device.sel, "inner"))
        cap = round_capacity(max(total, 1))
        res = joink.join_expand(bt, ranges, left.device, build_payload,
                                "inner", list(build_payload.columns.keys()),
                                cap)
        exp_batch, pi, is_match = res.batch, res.probe_index, res.is_match
        ok = exp_batch.sel
        if p.residual is not None:
            comb_schema = tuple(p.left.schema) + tuple(p.right.schema)
            comp = ExprCompiler([f.dtype for f in comb_schema],
                                {i: merged_dicts[_col_name(i)]
                                 for i in range(len(comb_schema))
                                 if _col_name(i) in merged_dicts},
                                self._subquery_cache)
            c = comp.compile(p.residual)
            cols = [(exp_batch.columns[_col_name(i)].data,
                     exp_batch.columns[_col_name(i)].validity)
                    for i in range(len(comb_schema))]
            rdat, rval = c.fn(cols)
            res_ok = rdat.astype(jnp.bool_)
            if rval is not None:
                res_ok = res_ok & rval
            ok = ok & res_ok
        if jt == "inner":
            return HostBatch(exp_batch.with_sel(ok), merged_dicts)
        # probe rows with >= 1 surviving match
        probe_cap = left.device.capacity
        matched_probe = jnp.zeros(probe_cap, dtype=jnp.bool_).at[pi].max(
            ok, mode="drop")
        if jt == "semi":
            return HostBatch(left.device.with_sel(left.device.sel & matched_probe),
                             left.dicts)
        if jt == "anti":
            return HostBatch(left.device.with_sel(left.device.sel & ~matched_probe),
                             left.dicts)
        if jt in ("left", "full"):
            # surviving inner rows + unmatched probe rows with null build cols
            unmatched = left.device.sel & ~matched_probe
            out_cap = cap + probe_cap
            cols = {}
            for i in range(n_left):
                key = _col_name(i)
                ec = exp_batch.columns[key]
                lc = left.device.columns[key]
                data = jnp.concatenate([ec.data, lc.data])
                validity = None
                if ec.validity is not None or lc.validity is not None:
                    ev = ec.validity if ec.validity is not None else \
                        jnp.ones(cap, dtype=jnp.bool_)
                    lv = lc.validity if lc.validity is not None else \
                        jnp.ones(probe_cap, dtype=jnp.bool_)
                    validity = jnp.concatenate([ev, lv])
                cols[key] = Column(data, validity, ec.dtype)
            for key in build_payload.columns:
                ec = exp_batch.columns[key]
                pad_v = jnp.zeros(probe_cap, dtype=jnp.bool_)
                ev = ec.validity if ec.validity is not None else \
                    jnp.ones(cap, dtype=jnp.bool_)
                cols[key] = Column(
                    jnp.concatenate([ec.data, jnp.zeros(probe_cap, dtype=ec.data.dtype)]),
                    jnp.concatenate([ev, pad_v]), ec.dtype)
            sel = jnp.concatenate([ok, unmatched])
            out = DeviceBatch(cols, sel)
            if jt == "full":
                out = self._append_unmatched_build(out, p, bt, ranges, left,
                                                   build_payload, ok, pi)
            return HostBatch(out, merged_dicts)
        raise ExecutionError(f"join type {jt!r} not implemented")

    def _append_unmatched_build(self, out: DeviceBatch, p, bt, ranges, left,
                                build_payload, ok, pi) -> DeviceBatch:
        # NOTE: residual-filtered matches are conservatively treated as
        # matches for the build side in v0 full outer joins.
        matched_build = joink.build_matched_mask(bt, ranges, left.device.sel)
        unmatched = build_payload.sel & ~matched_build
        n_left = len(p.left.schema)
        bcap = matched_build.shape[0]
        cols = {}
        for i in range(n_left):
            key = _col_name(i)
            c = out.columns[key]
            cols[key] = Column(
                jnp.concatenate([c.data, jnp.zeros(bcap, dtype=c.data.dtype)]),
                jnp.concatenate([c.validity if c.validity is not None
                                 else jnp.ones(c.data.shape[0], dtype=jnp.bool_),
                                 jnp.zeros(bcap, dtype=jnp.bool_)]), c.dtype)
        for key, c in build_payload.columns.items():
            oc = out.columns[key]
            v = c.validity if c.validity is not None else jnp.ones(bcap, dtype=jnp.bool_)
            cols[key] = Column(
                jnp.concatenate([oc.data, c.data]),
                jnp.concatenate([oc.validity if oc.validity is not None
                                 else jnp.ones(oc.data.shape[0], dtype=jnp.bool_), v]),
                c.dtype)
        sel = jnp.concatenate([out.sel, unmatched])
        return DeviceBatch(cols, sel)

    def _cross_join(self, p: pn.JoinExec, left: HostBatch, right: HostBatch) -> HostBatch:
        n_left_rows = int(left.device.num_rows())
        n_right_rows = int(right.device.num_rows())
        total = n_left_rows * n_right_rows
        cap = round_capacity(max(total, 1))
        lcomp = sortk.compact(left.device)
        rcomp_d = sortk.compact(right.device)
        idx = jnp.arange(cap, dtype=jnp.int32)
        li = jnp.clip(idx // max(n_right_rows, 1), 0, left.device.capacity - 1)
        ri = jnp.clip(idx % max(n_right_rows, 1), 0, right.device.capacity - 1)
        sel = idx < total
        cols = {}
        n_left = len(p.left.schema)
        for i in range(n_left):
            c = lcomp.columns[_col_name(i)]
            cols[_col_name(i)] = Column(c.data[li],
                                        None if c.validity is None else c.validity[li],
                                        c.dtype)
        dicts = dict(left.dicts)
        for i in range(len(p.right.schema)):
            c = rcomp_d.columns[_col_name(i)]
            cols[_col_name(n_left + i)] = Column(
                c.data[ri], None if c.validity is None else c.validity[ri], c.dtype)
            if _col_name(i) in right.dicts:
                dicts[_col_name(n_left + i)] = right.dicts[_col_name(i)]
        return HostBatch(DeviceBatch(cols, sel), dicts)

    # ------------------------------------------------------------------
    def _exec_UnionExec(self, p: pn.UnionExec) -> HostBatch:
        parts = [self.run(c) for c in p.inputs]
        ncols = len(p.schema)
        total_cap = sum(b.device.capacity for b in parts)
        cols = {}
        dicts = {}
        for i in range(ncols):
            key = _col_name(i)
            f = p.schema[i]
            str_col = any(key in b.dicts for b in parts)
            if str_col:
                from ..plan.compiler import _merge_dicts
                merged, remaps = _merge_dicts([b.dicts[key] for b in parts])
                datas = [jnp.asarray(rm)[b.device.columns[key].data]
                         for rm, b in zip(remaps, parts)]
                dicts[key] = merged
            else:
                jdt = physical_jnp_dtype(f.dtype)
                datas = [b.device.columns[key].data.astype(jdt) for b in parts]
            data = jnp.concatenate(datas)
            validities = []
            has_v = any(b.device.columns[key].validity is not None for b in parts)
            if has_v:
                for b in parts:
                    v = b.device.columns[key].validity
                    validities.append(v if v is not None else
                                      jnp.ones(b.device.capacity, dtype=jnp.bool_))
                validity = jnp.concatenate(validities)
            else:
                validity = None
            cols[key] = Column(data, validity, f.dtype)
        sel = jnp.concatenate([b.device.sel for b in parts])
        return HostBatch(DeviceBatch(cols, sel), dicts)


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _positional(hb: HostBatch) -> HostBatch:
    """Rename columns to positional keys c0..cn."""
    dev = hb.device
    cols = {}
    dicts = {}
    for i, (name, col) in enumerate(dev.columns.items()):
        cols[_col_name(i)] = col
        if name in hb.dicts:
            dicts[_col_name(i)] = hb.dicts[name]
    return HostBatch(DeviceBatch(cols, dev.sel), dicts)


def _shrink(dev: DeviceBatch, n_live: int) -> DeviceBatch:
    """Slice a front-compacted batch down to a smaller padded capacity."""
    cap = round_capacity(max(n_live, 1))
    if cap >= dev.capacity:
        return dev
    cols = {n: Column(c.data[:cap],
                      None if c.validity is None else c.validity[:cap], c.dtype)
            for n, c in dev.columns.items()}
    return DeviceBatch(cols, dev.sel[:cap])


def _flip_residual(r: Optional[rx.Rex], n_left: int, n_right: int) -> Optional[rx.Rex]:
    if r is None:
        return None

    def flip(x: rx.Rex) -> rx.Rex:
        if isinstance(x, rx.BoundRef):
            if x.index < n_left:
                return dataclasses.replace(x, index=x.index + n_right)
            return dataclasses.replace(x, index=x.index - n_left)
        if isinstance(x, rx.RCall):
            return dataclasses.replace(x, args=tuple(flip(a) for a in x.args))
        if isinstance(x, rx.RCast):
            return dataclasses.replace(x, child=flip(x.child))
        if isinstance(x, rx.RCase):
            return dataclasses.replace(
                x, branches=tuple((flip(c), flip(v)) for c, v in x.branches),
                else_value=None if x.else_value is None else flip(x.else_value))
        return x

    return flip(r)


def _reorder_right(hb: HostBatch, n_right: int, n_left: int) -> HostBatch:
    """After executing a flipped right join (as left join with sides swapped),
    restore the original column order: right-output cols [0..n_right) move
    after the left cols."""
    dev = hb.device
    cols = {}
    dicts = {}
    for i in range(n_left):
        src = _col_name(n_right + i)
        cols[_col_name(i)] = dev.columns[src]
        if src in hb.dicts:
            dicts[_col_name(i)] = hb.dicts[src]
    for i in range(n_right):
        src = _col_name(i)
        cols[_col_name(n_left + i)] = dev.columns[src]
        if src in hb.dicts:
            dicts[_col_name(n_left + i)] = hb.dicts[src]
    return HostBatch(DeviceBatch(cols, dev.sel), dicts)


def _node_rex(p: pn.PlanNode):
    if isinstance(p, pn.FilterExec):
        yield p.condition
    elif isinstance(p, pn.ProjectExec):
        for _, e in p.exprs:
            yield e
    elif isinstance(p, pn.JoinExec):
        yield from p.left_keys
        yield from p.right_keys
        if p.residual is not None:
            yield p.residual
    elif isinstance(p, pn.SortExec):
        for k in p.keys:
            yield k.expr
