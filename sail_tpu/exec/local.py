"""Local (single-process) executor.

Reference role: LocalJobRunner + DataFusion's operator execution
(crates/sail-execution/src/job_runner.rs:47-66) — here the operators are
interpreted on the host while all bulk compute runs as jnp/XLA ops over
DeviceBatches. Batches use positional column names (c0, c1, …) internally;
plan-schema names are applied only at the Arrow boundary (duplicate output
names are legal in SQL).

Host↔device sync points (kept deliberately few):
- aggregate output shrink (live group count → smaller padded capacity)
- join build-duplicate check + expand-capacity computation
- scalar subquery evaluation
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from ..columnar import arrow_interop as ai
from ..metrics import record as _record_metric
from ..columnar.batch import (Column, DeviceBatch, HostBatch,
                              bucket_capacity, empty_batch,
                              physical_jnp_dtype)
from ..ops import aggregate as aggk
from ..ops import join as joink
from ..ops import sort as sortk
from ..plan import nodes as pn
from ..plan import rex as rx
from ..plan import stages as pst
from ..plan.compiler import Compiled, ExprCompiler, HostFallback
from ..spec import data_type as dt
from ..spec.literal import Literal as LV


class _NativeMiss(Exception):
    """Native fast-path declined; discards its telemetry span."""


class ExecutionError(RuntimeError):
    pass


def _generate_rows(kind: str, args: List, col_names: List[str]
                   ) -> List[tuple]:
    n_cols = len(col_names)
    if kind == "explode":
        c = args[0]
        if c is None:
            return []
        if isinstance(c, dict):
            return [(k, v) for k, v in c.items()]
        return [(x,) for x in c]
    if kind == "posexplode":
        c = args[0]
        if c is None:
            return []
        if isinstance(c, dict):
            return [(i, k, v) for i, (k, v) in enumerate(c.items())]
        return [(i, x) for i, x in enumerate(c)]
    if kind == "inline":
        c = args[0]
        if c is None:
            return []
        out = []
        for st in c:
            if st is None:
                out.append(tuple([None] * n_cols))
            elif all(n in st for n in col_names):
                # match struct fields by NAME (dict insertion order may
                # differ between elements)
                out.append(tuple(st[n] for n in col_names))
            else:
                vals = list(st.values())
                out.append(tuple(vals[:n_cols] +
                                 [None] * (n_cols - len(vals))))
        return out
    if kind == "json_tuple":
        import json as _json
        s = args[0]
        try:
            v = _json.loads(s) if s is not None else None
        except ValueError:
            v = None
        if not isinstance(v, dict):
            return [tuple([None] * n_cols)]
        row = []
        for key in args[1:]:
            x = v.get(key)
            if x is None:
                row.append(None)
            elif isinstance(x, (dict, list)):
                row.append(_json.dumps(x, separators=(",", ":")))
            elif isinstance(x, bool):
                row.append("true" if x else "false")
            else:
                row.append(str(x))
        return [tuple(row)]
    if kind == "stack":
        n_rows = int(args[0])
        vals = args[1:]
        per = -(-len(vals) // n_rows) if n_rows else 0
        out = []
        for r in range(n_rows):
            row = vals[r * per:(r + 1) * per]
            out.append(tuple(list(row) + [None] * (per - len(row))))
        return out
    raise ExecutionError(f"unknown generator {kind!r}")


def _replace_node(plan: pn.PlanNode, target: pn.PlanNode,
                  replacement: pn.PlanNode) -> pn.PlanNode:
    if plan is target:
        return replacement
    if isinstance(plan, pn.JoinExec):
        return dataclasses.replace(
            plan, left=_replace_node(plan.left, target, replacement),
            right=_replace_node(plan.right, target, replacement))
    if isinstance(plan, pn.UnionExec):
        return dataclasses.replace(plan, inputs=tuple(
            _replace_node(c, target, replacement) for c in plan.inputs))
    if hasattr(plan, "input") and plan.input is not None:
        return dataclasses.replace(
            plan, input=_replace_node(plan.input, target, replacement))
    return plan


def _empty_arrow(schema) -> "pa.Table":
    return pa.Table.from_arrays(
        [pa.array([], type=ai.spec_type_to_arrow(f.dtype)) for f in schema],
        names=[f.name for f in schema])


def _hashable(v):
    if isinstance(v, list):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple((k, _hashable(x)) for k, x in v.items())
    return v


def _sort_key(v):
    """Total-order sort key for nested values: nulls first at every
    nesting level (Spark ordering), arrays/structs lexicographic."""
    if v is None:
        return (0,)
    if isinstance(v, (list, tuple)):
        return (1, tuple(_sort_key(x) for x in v))
    if isinstance(v, dict):
        return (1, tuple((k, _sort_key(x)) for k, x in v.items()))
    return (1, v)


def _dict_order_ranks(dictionary: pa.Array) -> np.ndarray:
    """Order-preserving rank per dictionary code. Arrow sort covers
    string/binary dictionaries; array/struct dictionaries (which Arrow
    cannot sort) fall back to a host lexicographic sort."""
    try:
        return ai.dictionary_ranks(dictionary)
    except (pa.ArrowNotImplementedError, pa.ArrowInvalid):
        vals = dictionary.to_pylist()
        order = sorted(range(len(vals)), key=lambda i: _sort_key(vals[i]))
        ranks = np.empty(len(vals), dtype=np.int32)
        ranks[order] = np.arange(len(vals), dtype=np.int32)
        return ranks


def _norm_intervals(vals):
    """Host aggregates see intervals as plain numbers: YM → int months,
    DT → int microseconds (recursing into struct-packed arg rows)."""
    import datetime as _dtm

    def norm(v):
        if v is None:
            return None
        if type(v).__name__ == "MonthDayNano":
            return int(v[0])
        if isinstance(v, _dtm.timedelta):
            return round(v.total_seconds() * 1e6)
        if isinstance(v, dict):
            return {k: norm(x) for k, x in v.items()}
        if isinstance(v, list):
            return [norm(x) for x in v]
        return v

    return [norm(v) for v in vals]


def _intervalize(v, d):
    """Numbers back to interval values per the declared output type."""
    import datetime as _dtm

    if v is None:
        return None
    if isinstance(d, dt.YearMonthIntervalType):
        return (int(round(float(v))), 0, 0)
    if isinstance(d, dt.DayTimeIntervalType):
        if isinstance(v, _dtm.timedelta):
            return v
        return _dtm.timedelta(microseconds=round(float(v)))
    if isinstance(d, dt.ArrayType) and isinstance(v, list):
        return [_intervalize(x, d.element_type) for x in v]
    return v


def _host_agg_one(spec, cols, rows_idx, host_aggs):
    """One aggregate over one group's row indices (host path)."""
    fn = spec.fn
    vals = None if spec.arg is None else [cols[spec.arg][i]
                                          for i in rows_idx]
    if fn.startswith("__host__"):
        name = fn[len("__host__"):]
        ha = host_aggs[name]
        assert vals is not None
        if name.startswith("__udaf_"):
            # wire UDAFs see the FULL group including nulls (PySpark hands
            # the grouped-agg pandas UDF the whole Series, NaN for NULL)
            if vals and isinstance(vals[0], dict):
                rows = [tuple(v.values()) if v is not None else None
                        for v in vals]
            else:
                rows = list(vals)
            return ha.impl(rows)
        if vals and isinstance(vals[0], dict):
            tuples = [tuple(v.values()) if v is not None else None
                      for v in vals]
            # per-function null eligibility: max_by/min_by drop rows with a
            # null ORDERING key (the value may be null); value-first
            # aggregates drop rows with a null value; statistical pairs
            # drop rows with any null
            if name in ("max_by", "min_by"):
                rows = [t for t in tuples
                        if t is not None and t[1] is not None]
            elif name in ("listagg", "string_agg", "percentile",
                          "percentile_approx", "approx_percentile",
                          "percentile_cont", "percentile_disc",
                          "histogram_numeric", "__listagg_ordered",
                          "__mode_ordered", "mode", "approx_top_k",
                          "kll_sketch_agg_bigint", "kll_sketch_agg_double",
                          "kll_sketch_agg_float", "hll_sketch_agg",
                          "theta_sketch_agg", "count_min_sketch"):
                rows = [t for t in tuples
                        if t is not None and t[0] is not None]
            else:
                rows = [t for t in tuples
                        if t is not None and all(x is not None for x in t)]
        else:
            rows = [v for v in vals if v is not None]
        if spec.distinct:
            seen = []
            rows = [r for r in rows
                    if not (r in seen or seen.append(r))]
        return ha.impl(rows)
    nn = None if vals is None else [v for v in vals if v is not None]
    if spec.distinct and nn:
        # dedup on the hashable key but keep the ORIGINAL values, so
        # min/max/first over array/struct columns return lists/dicts
        seen: dict = {}
        for v in nn:
            seen.setdefault(_hashable(v), v)
        nn = list(seen.values())
    if fn == "count":
        return len(rows_idx) if vals is None else len(nn)
    if fn == "sum":
        return sum(nn) if nn else None
    if fn == "min":
        # compare via the sort key so array/struct values (incl. nested
        # nulls) order per Spark but the ORIGINAL value returns
        return min(nn, key=_sort_key) if nn else None
    if fn == "max":
        return max(nn, key=_sort_key) if nn else None
    if fn == "first":
        pool = nn if spec.ignore_nulls else vals
        return pool[0] if pool else None
    if fn == "last":
        pool = nn if spec.ignore_nulls else vals
        return pool[-1] if pool else None
    if fn == "bool_and":
        return all(nn) if nn else None
    if fn == "bool_or":
        return any(nn) if nn else None
    raise ExecutionError(f"aggregate {fn!r} has no host path")


def _fit_capacity(data, validity, cap: int):
    """Broadcast constant (scalar / 1-element) expression results to the
    batch capacity, so literal projections over OneRow line up with the
    selection mask (UNIONs of FROM-less SELECTs concatenate per-column)."""
    if data.ndim == 0:
        data = jnp.broadcast_to(data[None], (cap,))
    elif data.shape[0] != cap and data.shape[0] == 1:
        data = jnp.broadcast_to(data, (cap,))
    if validity is not None:
        if validity.ndim == 0:
            validity = jnp.broadcast_to(validity[None], (cap,))
        elif validity.shape[0] != cap and validity.shape[0] == 1:
            validity = jnp.broadcast_to(validity, (cap,))
    return data, validity


def _col_name(i: int) -> str:
    return f"c{i}"


class _OpCache:
    """Compiled-operator cache.

    Keyed by (plan-node structural key, input-dictionary identity). The
    bind-time closures bake host lookup tables derived from dictionaries, so
    a cached entry is valid exactly while the same dictionary objects flow
    in — the entry holds strong references and verifies identity on hit.
    Combined with the scan cache (stable dictionaries per table), repeated
    queries of the same shape skip both tracing and XLA compilation.
    """

    def __init__(self, max_entries: Optional[int] = None):
        from collections import OrderedDict
        self.entries = OrderedDict()
        self._max_entries = max_entries

    @property
    def max_entries(self) -> int:
        # resolved lazily so the config layer is ready by first use
        if self._max_entries is None:
            self._max_entries = _runtime_cache_size(
                "runtime.op_cache_size", 512)
        return self._max_entries

    def get(self, key, dict_objs: Tuple, builder):
        ident = tuple(id(d) for d in dict_objs)
        hit = self.entries.get((key, ident))
        if hit is not None:
            stored, value = hit
            if all(s is d for s, d in zip(stored, dict_objs)):
                self.entries.move_to_end((key, ident))
                return value
        value = builder()
        while len(self.entries) >= self.max_entries:
            evicted_key, _ = self.entries.popitem(last=False)  # LRU
            try:
                # the dropped program's next compile is an eviction
                # retrace — the ledger keeps its signature history
                from . import retrace
                retrace.LEDGER.note_eviction(evicted_key[0])
            except Exception:  # noqa: BLE001 — forensics never break exec
                pass
        self.entries[(key, ident)] = (tuple(dict_objs), value)
        return value


def _compile_timed(fn, key, fused=False):
    """Wrap a jitted fn so every call that actually traces and XLA-
    compiles (jax.jit itself is lazy) is timed, charged to the active
    query, and attributed to a typed retrace cause (exec/retrace.py).

    Detection: jax's jitted callables expose ``_cache_size()`` — the
    number of compiled signatures resident in the jit cache. A call
    after which it GREW compiled; anything else ran a bound executable.
    That sees every beyond-first-call retrace (new aval signature,
    capacity-bucket churn) the old first-call-only timing was blind to.
    When the introspection hook is absent, only the first call is timed
    (the pre-forensics behavior). ``fused`` marks whole-stage programs:
    their compile time additionally rides
    ``execution.fusion.compile_time``."""
    import time as _time

    from .. import profiler
    from . import retrace

    cache_size = getattr(fn, "_cache_size", None)
    pending = [True]

    def _charge(elapsed_s: float, args) -> None:
        key_repr = repr(key[0]) if isinstance(key, tuple) and key \
            else repr(key)
        if fused:
            try:
                from ..metrics import record as _record_metric
                _record_metric("execution.fusion.compile_time",
                               elapsed_s)
            except Exception:  # noqa: BLE001 — timing must never raise
                pass
        profiler.note_compile_time(elapsed_s, key=key_repr)
        from . import pcache
        retrace.attribute(key, pcache.signature(args), elapsed_s,
                          site="memory")

    def wrapper(*args, **kwargs):
        first = bool(pending)
        if cache_size is None:
            if not first:
                return fn(*args, **kwargs)
            del pending[:]
            t0 = _time.perf_counter()
            out = fn(*args, **kwargs)
            _charge(_time.perf_counter() - t0, args)
            return out
        n0 = cache_size()
        t0 = _time.perf_counter()
        out = fn(*args, **kwargs)
        if cache_size() > n0:
            if first:
                del pending[:]
            _charge(_time.perf_counter() - t0, args)
        elif first:
            del pending[:]
        return out

    return wrapper


def _runtime_cache_size(key: str, default: int) -> int:
    """Process-wide cache bound from config, read once per key (these
    sit on hot paths; app-config flattening must not ride every hit)."""
    size = _RUNTIME_CACHE_SIZES.get(key)
    if size is None:
        try:
            from ..config import get as config_get
            size = max(1, int(config_get(key, default)))
        except (TypeError, ValueError, ImportError):
            size = default
        _RUNTIME_CACHE_SIZES[key] = size
    return size


_RUNTIME_CACHE_SIZES: Dict[str, int] = {}
_OP_CACHE = _OpCache()
# runtime join filters: join-structure key → last observed prune ratio
# (scan + probe pruning over probed rows); joins whose filters proved
# useless skip the build on later executions (adaptive)
_RTF_HISTORY: Dict = {}


class _RtfConf(NamedTuple):
    """spark.sail.join.runtimeFilter.* resolved for one executor."""

    enabled: bool
    min_build_rows: int
    max_bits: int
    in_list_max: int
    ndv_ratio: float
    min_selectivity: float


class _Rtf(NamedTuple):
    """A built runtime filter, ready to mask the filtered side."""

    bits: object           # device bool[num_bits] bloom bit array
    kmin: object           # device uint64 packed/hashed key bounds
    kmax: object
    ordinals: Tuple[int, ...]  # join-key ordinals folded into the bloom
    num_bits: int
    fids: Tuple[int, ...]      # annotated filter ids (scan stat lookup)
    history_key: object        # adaptive-skip key (None if unhashable)
    pushed: int                # scan targets that received conjuncts
    # False: built from the build (right) side, masks the probe side.
    # True: built from the probe (left) side, masks the build side —
    # the direction that wins when join reordering made the FACT table
    # the build side of the topmost joins.
    reverse: bool = False


def clear_caches():
    from . import capacity, result_cache, retrace
    _OP_CACHE.entries.clear()
    _RTF_HISTORY.clear()
    _RUNTIME_CACHE_SIZES.clear()
    result_cache.clear_all()
    retrace.clear()
    capacity.reload()


class LocalExecutor:
    def __init__(self, config: Optional[dict] = None):
        self.config = config or {}
        self._subquery_cache: Dict[int, LV] = {}
        # runtime join filters: per-fid (rows_before, rows_after) scan
        # pruning observed while executing this plan (adaptive feedback)
        self._rtf_scan_stats: Dict[int, Tuple[int, int]] = {}
        # whole-stage fusion gate, resolved once per executor
        self._fusion: Optional[bool] = None
        # concurrent-scan sharing (enabled, wait_timeout_s), resolved
        # once per executor (io/prefetch.scan_share_conf)
        self._scan_share_conf: Optional[Tuple[bool, float]] = None
        # persistent compiled-program cache gate (exec/pcache.py)
        self._pcache: Optional[bool] = None
        # per-stage backend routing decisions of the current plan
        # (exec/router.py): stage sid -> Decision, plus the node->sid
        # map the decisions were made under
        self._backend_routes: Dict = {}
        self._route_stage_of: Dict = {}

    def _fusion_on(self) -> bool:
        """``spark.sail.execution.fusion.enabled`` (session conf) over
        ``execution.fusion.enabled`` (app config), default on. Off
        restores pre-fusion per-operator execution for A/B and
        bisection."""
        if self._fusion is None:
            from ..plan.stages import fusion_enabled
            self._fusion = fusion_enabled(
                self.config.get("spark.sail.execution.fusion.enabled"))
        return self._fusion

    def _note_stage_split(self, plan: pn.PlanNode) -> None:
        """Stage-split accounting + the fused-stage invariant walk (the
        splitter's output drives this query's fusion decisions, so a bad
        split must surface here, not as a wrong answer)."""
        from .. import profiler
        from ..analysis.invariants import (VALIDATE_OFF,
                                           validate_stage_split,
                                           validation_mode)
        from ..plan import stages as pst

        split = pst.split_stages(plan)
        _record_metric("execution.fusion.stage_count", len(split.stages))
        fused_ops = split.fused_op_count
        if fused_ops:
            _record_metric("execution.fusion.fused_op_count", fused_ops)
        profiler.note_fusion(stages=len(split.stages),
                             fused_ops=fused_ops)
        # per-stage backend routing, decided HERE — at stage-split time
        # — so execution consults a recorded decision instead of making
        # an implicit one per operator (exec/router.py)
        from . import router
        decisions = router.decide_split(
            split, force=router.forced_backend(self.config),
            slo_ctx=router.slo_context(self.config))
        self._backend_routes = {d.stage: d for d in decisions}
        self._route_stage_of = split.stage_of
        router.record_decisions(decisions)
        mode = validation_mode(
            self.config.get("spark.sail.analysis.validatePlans"))
        if mode != VALIDATE_OFF:
            validate_stage_split(plan, split)
            profiler.note_plan_validated()

    def _note_fusion_fallback(self, site: str) -> None:
        """One pipeline declined whole-stage fusion at execution time
        (host-only expressions etc.) and ran per-op instead."""
        from .. import profiler
        _record_metric("execution.fusion.fallback_count", 1, site=site)
        profiler.note_fusion(fallbacks=1)

    # ------------------------------------------------------------------
    def execute(self, plan: pn.PlanNode) -> pa.Table:
        """Run a plan to an Arrow table with the plan's output names."""
        import contextlib

        from .. import profiler
        # a nested executor (scalar subquery, command sub-plan) runs
        # entirely inside the outer "execute" timer — recording its
        # fetch separately would overlap the phases
        prof = profiler.current_profile()
        nested = prof is not None and prof.is_open("execute")
        with profiler.maybe_phase("execute"):
            self._pre_eval_subqueries(plan)
            if self._fusion_on():
                self._note_stage_split(plan)
            batch = self.run(plan)
        with contextlib.nullcontext() if nested \
                else profiler.maybe_phase("fetch"):
            table = ai.to_arrow(batch)
            names = [f.name for f in plan.schema]
            return table.rename_columns(names)

    def run(self, plan: pn.PlanNode) -> HostBatch:
        method = getattr(self, "_exec_" + type(plan).__name__, None)
        if method is None:
            raise ExecutionError(f"no executor for {type(plan).__name__}")
        from .. import telemetry as tel
        if tel.current_collector() is None:
            return method(plan)
        detail = ""
        if isinstance(plan, pn.ScanExec):
            detail = plan.table_name or ",".join(plan.paths)
        with tel.operator_span(type(plan).__name__, detail) as m:
            out = method(plan)
            # rows/capacity force a device sync — only under EXPLAIN ANALYZE
            m.output_rows = int(out.device.num_rows())
            m.capacity = out.capacity
            return out

    # ------------------------------------------------------------------
    # scalar subqueries
    # ------------------------------------------------------------------
    def _pre_eval_subqueries(self, plan: pn.PlanNode):
        for node in pn.walk_plan(plan):
            for r in _node_rex(node):
                for sub in rx.walk(r):
                    if isinstance(sub, rx.RScalarSubquery) and \
                            id(sub) not in self._subquery_cache:
                        self._subquery_cache[id(sub)] = self._eval_scalar(sub)

    def _eval_scalar(self, sub: rx.RScalarSubquery) -> LV:
        inner = LocalExecutor(self.config)
        inner._subquery_cache = self._subquery_cache
        table = inner.execute(sub.plan)
        if table.num_rows == 0:
            return LV(sub.dtype, None)
        if table.num_rows > 1:
            raise ExecutionError("scalar subquery returned more than one row")
        v = table.column(0)[0].as_py()
        return LV(sub.dtype, v)

    # ------------------------------------------------------------------
    # expression plumbing
    # ------------------------------------------------------------------
    def _compiler(self, batch: HostBatch, schema: pn.Schema) -> ExprCompiler:
        types = [f.dtype for f in schema]
        dicts = {}
        for i in range(len(schema)):
            name = _col_name(i)
            if name in batch.dicts:
                dicts[i] = batch.dicts[name]
        return ExprCompiler(types, dicts, self._subquery_cache)

    @staticmethod
    def _cols(batch: HostBatch) -> List:
        dev = batch.device
        return [(dev.columns[_col_name(i)].data, dev.columns[_col_name(i)].validity)
                for i in range(len(dev.columns))]

    def _eval(self, compiled: Compiled, batch: HostBatch):
        return compiled.fn(self._cols(batch))

    def _dict_objs(self, batch: HostBatch) -> Tuple:
        return tuple(batch.dicts[k] for k in sorted(batch.dicts))

    def _op_key(self, *parts):
        """Structural cache key, or None when unhashable (e.g. embedded
        scalar-subquery plans holding memory tables).

        Scalar-subquery values are baked into compiled closures, so the key
        appends each referenced subquery's value in rex-walk order (stable
        across executions of structurally-equal plans)."""
        sub_vals = []
        for part in parts:
            for r in _walk_part_rex(part):
                for node in rx.walk(r):
                    if isinstance(node, rx.RScalarSubquery):
                        v = self._subquery_cache.get(id(node))
                        sub_vals.append(repr(None if v is None else v.value))
        key = parts + (tuple(sub_vals),)
        try:
            hash(key)
            return key
        except TypeError:
            return None

    def _pcache_on(self) -> bool:
        """Persistent compiled-program cache gate, resolved once per
        executor: ``spark.sail.compileCache.enabled`` (session conf)
        over the process-wide ``compile_cache.{enabled,dir}`` (a store
        only exists when a directory is configured)."""
        if self._pcache is None:
            from ..config import truthy_value
            from . import pcache
            session = self.config.get("spark.sail.compileCache.enabled")
            self._pcache = pcache.enabled() and \
                (session is None or truthy_value(session))
        return self._pcache

    def _jitted(self, key, dict_objs: Tuple, builder, fused=False):
        """Returns (fn, aux) where fn is jit-compiled and cached when the
        key is hashable, else built fresh and run eagerly.

        Compile accounting: every call is a compile-cache hit or miss
        (``execution.compile.{cache_hit_count,cache_miss_count}`` and the
        active query profile); a miss additionally times the jitted
        program's FIRST invocation — where jax traces and XLA compiles —
        as ``execution.compile.compile_time`` (and, for whole-stage
        fused programs, ``execution.fusion.compile_time``).

        With the persistent cache enabled (``compile_cache.*``), an
        in-memory miss consults the cross-process AOT store BEFORE
        tracing (``exec/pcache.py``): a persistent hit deserializes the
        stored executable (no trace, no XLA compile), a persistent miss
        AOT-compiles and stores. Builders routed here must bake only
        key-covered structure, dictionary-derived tables, and keyed
        subquery values into their closures — that is the persistence
        contract the entry digest verifies."""
        import jax

        from .. import profiler

        if key is None:
            # unhashable plan key: uncached eager build — still a miss
            profiler.note_compile_cache(hit=False)
            fn, aux = builder()
            return fn, aux

        def build():
            missed.append(True)
            fn, aux = builder()
            if self._pcache_on():
                from . import pcache
                site = key[0] if isinstance(key, tuple) and key \
                    and isinstance(key[0], str) else "op"
                wrapped = pcache.wrap(fn, key, dict_objs, fused=fused,
                                      site=site)
                if wrapped is not None:
                    return wrapped, aux
            return _compile_timed(jax.jit(fn), key, fused=fused), aux

        missed: list = []
        value = _OP_CACHE.get(key, dict_objs, build)
        profiler.note_compile_cache(hit=not missed)
        return value

    # ------------------------------------------------------------------
    # leaves
    # ------------------------------------------------------------------
    def _exec_ScanExec(self, p: pn.ScanExec) -> HostBatch:
        from ..io.formats import expand_paths
        import os
        if p.format == "python_ds":
            # user data source: read at EXECUTION, never cached — the
            # engine can't know the external source is stable
            from ..io.python_datasource import materialize
            from ..spec import data_type as dt_
            ds_cls, opts = p.source
            st = dt_.StructType(tuple(
                dt_.StructField(f.name, f.dtype, f.nullable)
                for f in p.out_schema))
            table, _ = materialize(ds_cls, dict(opts), st)
            if p.projection is not None:
                table = table.select(list(p.projection))
            return _positional(ai.from_arrow(table))
        from . import result_cache as rc
        from .. import profiler
        rtf_preds = p.runtime_predicates
        if p.source is not None:
            cache_key = ("mem", id(p.source), p.projection, rtf_preds)
            table_key = rc.memory_table_key(p.table_name) \
                if p.table_name else None
        elif p.format == "delta":
            from ..lakehouse.delta import DeltaLog
            files = p.paths
            mtimes = (DeltaLog(p.paths[0]).latest_version(),
                      tuple(sorted(dict(p.options).items())))
            cache_key = ("delta", files, mtimes, p.projection,
                         tuple((f.name, f.dtype) for f in p.schema))
            table_key = p.paths[0] if p.paths else None
        else:
            try:
                files = tuple(expand_paths(p.paths))
                mtimes = tuple(int(os.path.getmtime(f) * 1e6) for f in files)
            except OSError:
                files, mtimes = p.paths, ()
            cache_key = ("file", files, mtimes, p.projection, p.predicates,
                         rtf_preds,
                         tuple(sorted(dict(p.options).items())),
                         tuple((f.name, f.dtype) for f in p.schema))
            table_key = p.paths[0] if p.paths else None
        hit = rc.FRAGMENT_CACHE.get(cache_key, p.source)
        if hit is not None:
            self._note_rtf_scan(p, hit.rtf_stats)
            profiler.note_result_cache(fragment=hit.fragment_id,
                                       nbytes=hit.nbytes)
            return hit.batch
        # concurrent-scan sharing: a fragment miss races other queries
        # admitted in the same window — one leader decodes, followers
        # attach to the in-flight load instead of running N identical
        # scans (followers fall back to a local decode on timeout)
        leader, flight = False, None
        share_enabled, share_timeout = self._scan_share()
        if share_enabled:
            from ..io.prefetch import SCAN_LOADS
            leader, flight = SCAN_LOADS.begin(cache_key)
            if not leader:
                _record_metric("execution.scan_share.attached_count", 1)
                try:
                    ok, entry = flight.wait(share_timeout)
                finally:
                    SCAN_LOADS.detach(flight)
                if ok and entry is not None and \
                        (p.source is None or entry.source is p.source):
                    _record_metric(
                        "execution.scan_share.decode_passes_saved", 1)
                    self._note_rtf_scan(p, entry.rtf_stats)
                    profiler.note_result_cache(
                        status="shared-scan", fragment=entry.fragment_id,
                        nbytes=entry.nbytes, attached=1, saved=1)
                    return entry.batch
                flight = None
        try:
            hb = self._decode_scan(p, cache_key, table_key, files
                                   if p.source is None else None,
                                   flight if leader else None)
            return hb
        finally:
            if leader and flight is not None:
                from ..io.prefetch import SCAN_LOADS
                SCAN_LOADS.finish(cache_key, flight)

    def _decode_scan(self, p: pn.ScanExec, cache_key, table_key,
                     files, flight) -> HostBatch:
        """The actual decode/upload pass (fragment-cache fill). When a
        ScanFlight is handed in, publishes the stored fragment to
        attached followers — or the failure, which propagates."""
        from . import result_cache as rc
        import time as _time
        t0 = _time.perf_counter()
        try:
            hb, table, rtf_stats = self._decode_scan_table(p, files)
        except BaseException as exc:
            if flight is not None:
                flight.fail(exc)
            raise
        self._note_rtf_scan(p, rtf_stats)
        try:
            nbytes = int(table.nbytes)
        except Exception:  # noqa: BLE001 — size is advisory
            nbytes = 0
        entry = rc.FRAGMENT_CACHE.put(
            cache_key, p.source, hb, rtf_stats, table_key=table_key,
            nbytes=nbytes, rows=table.num_rows,
            decode_ms=(_time.perf_counter() - t0) * 1000.0)
        # observed-exact cardinality: the cached fragment is a grounded
        # input for AQE/join ordering on every later substitution
        from ..plan import join_reorder
        join_reorder.note_observed_rows(p, table.num_rows)
        if flight is not None:
            flight.publish(entry)
        return hb

    def _scan_share(self) -> Tuple[bool, float]:
        if self._scan_share_conf is None:
            from ..io.prefetch import scan_share_conf
            self._scan_share_conf = scan_share_conf(self.config)
        return self._scan_share_conf

    def _decode_scan_table(self, p: pn.ScanExec, files):
        from ..io.formats import read_table
        rtf_preds = p.runtime_predicates
        rtf_stats = None
        if p.source is not None:
            table = p.source
            if p.projection is not None:
                table = table.select(list(p.projection))
            if rtf_preds:
                # runtime join-filter conjuncts: prune probe rows HOST-side
                # before upload, so every downstream kernel runs at the
                # pruned (bucketed) capacity
                table, rtf_stats = _apply_runtime_predicates(
                    table, rtf_preds, p.schema)
        else:
            filter_expr = None
            preds = p.predicates
            if p.format == "parquet" and (preds or rtf_preds):
                from ..io.formats import rex_predicates_to_arrow, \
                    row_group_pruning_enabled
                if not row_group_pruning_enabled():
                    preds = rtf_preds = ()
                if rtf_preds:
                    # runtime filter conjuncts join the static predicates
                    # for parquet row-group/page skipping; fall back to
                    # static-only if the combination fails to convert
                    filter_expr = rex_predicates_to_arrow(
                        preds + rtf_preds, p.schema)
                if filter_expr is None and preds:
                    filter_expr = rex_predicates_to_arrow(preds, p.schema)
            table = read_table(p.format, p.paths, dict(p.options),
                               columns=p.projection,
                               filter_expr=filter_expr)
            table = self._apply_declared_schema(table, p.schema)
            if rtf_preds and filter_expr is not None and not p.predicates:
                # adaptive evidence for parquet pruning: with no static
                # predicates in the filter, footer row counts give the
                # exact pre-filter cardinality for free
                try:
                    from ..io.cache import METADATA_CACHE
                    before = sum(METADATA_CACHE.num_rows(f)
                                 for f in files)
                    rtf_stats = (int(before), table.num_rows)
                except Exception:  # noqa: BLE001 — stats are advisory
                    rtf_stats = None
        hb = _positional(ai.from_arrow(table, bucket_key=_scan_cap_key(p)))
        return hb, table, rtf_stats

    def _note_rtf_scan(self, p: pn.ScanExec, stats) -> None:
        """Record one scan's runtime-filter pruning (executor-local for
        the join's adaptive feedback, registry + profiler for
        observability). Cache hits replay the cached stats: the pruning
        is baked into the cached batch and still shapes this query."""
        if not p.runtime_filters or stats is None:
            return
        before, after = stats
        for t in p.runtime_filters:
            self._rtf_scan_stats[t.fid] = (before, after)
        pruned = before - after
        if pruned <= 0:
            return
        from .. import profiler
        from .. import telemetry as tel
        _record_metric("execution.runtime_filter.rows_pruned", pruned,
                       site="scan")
        profiler.note_runtime_filter(rows_pruned=pruned)
        if tel.current_collector() is not None:
            tel.note("RuntimeFilter",
                     f"scan {p.table_name or p.format}",
                     rows_pruned=pruned, rows_in=before)

    @staticmethod
    def _apply_declared_schema(table: pa.Table, schema: pn.Schema) -> pa.Table:
        """Reorder/cast file data to the plan's declared schema (a user-set
        read schema may differ from the file's natural order and types)."""
        arrays = []
        names = []
        for f in schema:
            at = ai.spec_type_to_arrow(f.dtype)
            if f.name in table.column_names:
                col = table.column(f.name)
                if col.type != at:
                    col = col.cast(at, safe=False)
            else:
                col = pa.nulls(table.num_rows, type=at)
            arrays.append(col)
            names.append(f.name)
        return pa.table(dict(zip(names, arrays)))

    def _exec_OneRowExec(self, p: pn.OneRowExec) -> HostBatch:
        sel = np.zeros(8, dtype=bool)
        sel[0] = True
        return HostBatch(DeviceBatch({}, jnp.asarray(sel)), {})

    def _exec_ValuesExec(self, p: pn.ValuesExec) -> HostBatch:
        arrays = []
        for j, f in enumerate(p.out_schema):
            vals = [row[j] for row in p.rows]
            at = ai.spec_type_to_arrow(f.dtype)
            if isinstance(f.dtype, dt.YearMonthIntervalType):
                arrays.append(pa.array(
                    [None if v.value is None else (int(v.value), 0, 0)
                     for v in vals], type=at))
                continue
            arrays.append(pa.array([v.value for v in vals], type=at))
        table = pa.table(dict(zip([_col_name(j) for j in range(len(arrays))], arrays)))
        return ai.from_arrow(table)

    def _exec_RangeExec(self, p: pn.RangeExec) -> HostBatch:
        n = max(0, -(-(p.end - p.start) // p.step)) if p.step else 0
        vals = np.arange(p.start, p.end, p.step, dtype=np.int64)
        table = pa.table({"c0": pa.array(vals, type=pa.int64())})
        return ai.from_arrow(table)

    # ------------------------------------------------------------------
    # unary operators
    # ------------------------------------------------------------------
    def _exec_ProjectExec(self, p: pn.ProjectExec) -> HostBatch:
        if self._fusion_on():
            out = self._try_fused_chain(p)
            if out is not None:
                return out
        return self._project_over(p, self.run(p.input))

    def _project_over(self, p: pn.ProjectExec, child: HostBatch
                      ) -> HostBatch:
        dev = child.device
        if not p.exprs:  # SELECT of zero columns
            return HostBatch(DeviceBatch({}, dev.sel), {})

        def builder():
            comp = self._compiler(child, p.input.schema)
            compiled = [comp.compile(e) for _, e in p.exprs]
            types = [rx.rex_type(e) for _, e in p.exprs]
            jdts = [physical_jnp_dtype(t) for t in types]

            def fn(cols):
                out = []
                for c, jdt in zip(compiled, jdts):
                    data, validity = c.fn(cols)
                    if data.dtype != jnp.dtype(jdt):
                        data = data.astype(jdt)
                    out.append((data, validity))
                return tuple(out)

            dicts = {_col_name(i): c.dictionary
                     for i, c in enumerate(compiled) if c.dictionary is not None}
            return fn, dicts

        key = self._op_key("project", p.exprs,
                           tuple((f.name, f.dtype) for f in p.input.schema))
        try:
            fn, out_dicts = self._jitted(key, self._dict_objs(child), builder)
        except HostFallback:
            return self._project_host_path(p, child)
        results = fn(self._cols(child))
        cap = dev.sel.shape[0]
        out_cols = {}
        for i, ((d, v), (_, e)) in enumerate(zip(results, p.exprs)):
            d, v = _fit_capacity(d, v, cap)
            out_cols[_col_name(i)] = Column(d, v, rx.rex_type(e))
        return HostBatch(DeviceBatch(out_cols, dev.sel), out_dicts)

    def _project_host_path(self, p: pn.ProjectExec, child: HostBatch) -> HostBatch:
        """Per-expression evaluation with host fallback for expressions the
        device compiler can't lower (string-returning Python UDFs, …)."""
        comp = self._compiler(child, p.input.schema)
        dev = child.device
        out_cols: Dict[str, Column] = {}
        out_dicts: Dict[str, pa.Array] = {}
        for i, (name, e) in enumerate(p.exprs):
            keyn = _col_name(i)
            try:
                c = comp.compile(e)
                data, validity = self._eval(c, child)
                data, validity = _fit_capacity(data, validity,
                                               dev.sel.shape[0])
                if c.dictionary is not None:
                    out_dicts[keyn] = c.dictionary
            except HostFallback:
                data, validity, dictionary = self._host_eval(e, comp, child)
                if dictionary is not None:
                    out_dicts[keyn] = dictionary
            odt = rx.rex_type(e)
            if not isinstance(odt, (dt.ArrayType, dt.MapType,
                                    dt.StructType, dt.NullType)):
                jdt = physical_jnp_dtype(odt)
                if data.dtype != jnp.dtype(jdt):
                    data = data.astype(jdt)
            out_cols[keyn] = Column(data, validity, odt)
        return HostBatch(DeviceBatch(out_cols, dev.sel), out_dicts)

    def _host_eval(self, e: rx.Rex, comp: ExprCompiler, child: HostBatch):
        """Host evaluation of a __pyudf call (incl. string returns): args
        evaluate on device, rows run through the Python function, string
        results dictionary-encode."""
        if isinstance(e, rx.RCast) and isinstance(e.dtype, dt.StringType) \
                and not isinstance(rx.rex_type(e.child),
                                   (dt.ArrayType, dt.MapType, dt.StructType)):
            try:
                return self._host_cast_to_string(e, comp, child)
            except HostFallback:
                pass
        if not (isinstance(e, rx.RCall) and e.fn == "__pyudf"):
            # general host interpreter (arrays/maps/structs/json/lambdas/…)
            from .host_interp import HostInterpreter, encode_host_column
            interp = HostInterpreter(self, comp, child)
            values = interp.values(e)
            return encode_host_column(values, rx.rex_type(e),
                                      child.device.capacity)
        from ..plan.compiler import (udf_arg_decoder, udf_decode_column,
                                     udf_encode_numeric, udf_invoke)
        u = dict(e.options)["udf"]
        n = child.capacity
        cols_py = []
        for a in e.args:
            ac = comp.compile(a)
            data, validity = self._eval(ac, child)
            dec = udf_arg_decoder(rx.rex_type(a), ac.dictionary)
            cols_py.append(udf_decode_column(
                dec, np.asarray(data),
                None if validity is None else np.asarray(validity)))
        res = udf_invoke(u, cols_py, n)
        out_t = u.return_type
        if isinstance(out_t, (dt.StringType, dt.BinaryType)):
            def _null_like(v):
                if v is None:
                    return True
                try:
                    return bool(v != v)  # NaN
                except (TypeError, ValueError):
                    return True  # pd.NA: truth value is ambiguous → NULL
            arr = pa.array([None if _null_like(v) else str(v)
                            for v in res], type=pa.string())
            enc = arr.dictionary_encode()
            codes = np.asarray(enc.indices.fill_null(0)).astype(np.int32)
            import pyarrow.compute as _pc
            validity = jnp.asarray(np.asarray(_pc.is_valid(arr)))
            return jnp.asarray(codes), validity, enc.dictionary
        jdt = physical_jnp_dtype(out_t)
        out, mask = udf_encode_numeric(res, n, np.dtype(jdt))
        return jnp.asarray(out), jnp.asarray(mask), None

    def _host_cast_to_string(self, e: rx.RCast, comp: ExprCompiler,
                             child: HostBatch):
        """CAST(x AS STRING) for non-dictionary columns: evaluate the child
        on device, format values on host with Spark's text forms, and
        dictionary-encode the result."""
        import datetime as _dtm
        import decimal as _dec

        ac = comp.compile(e.child)
        data, validity = self._eval(ac, child)
        src_t = rx.rex_type(e.child)
        arr = ai.column_values_to_arrow(np.asarray(data),
                                        None if validity is None
                                        else np.asarray(validity),
                                        src_t, ac.dictionary)

        def fmt(v):
            if v is None:
                return None
            if isinstance(v, bool):
                return "true" if v else "false"
            if isinstance(v, float):
                from ..utils.format import format_double
                return format_double(v)
            if isinstance(v, _dtm.datetime):
                if v.tzinfo is not None:
                    from ..utils.tz import session_zone
                    v = v.astimezone(session_zone())
                s = v.strftime("%Y-%m-%d %H:%M:%S")
                if v.microsecond:
                    s += f".{v.microsecond:06d}".rstrip("0")
                return s
            if isinstance(v, _dtm.date):
                return v.isoformat()
            if isinstance(v, _dec.Decimal):
                return format(v, "f")
            return str(v)

        sarr = pa.array([fmt(v) for v in arr.to_pylist()], type=pa.string())
        enc = sarr.dictionary_encode()
        codes = np.asarray(enc.indices.fill_null(0)).astype(np.int32)
        import pyarrow.compute as _pc
        out_validity = jnp.asarray(np.asarray(_pc.is_valid(sarr)))
        return jnp.asarray(codes), out_validity, enc.dictionary

    def _exec_GenerateExec(self, p: pn.GenerateExec) -> HostBatch:
        """Host row expansion for explode/posexplode/inline/stack."""
        from .host_interp import HostInterpreter

        child = self.run(p.input)
        comp = self._compiler(child, p.input.schema)
        interp = HostInterpreter(self, comp, child)
        sel = np.asarray(child.device.sel)
        live = np.nonzero(sel)[0]
        def live_vals(r):
            vals = interp.values(r)
            return [vals[i] for i in live]

        pt_vals = [(n, live_vals(r)) for n, r in p.passthrough]
        arg_vals = [live_vals(a) for a in p.args]
        out_rows: List[tuple] = []
        for row_i in range(len(live)):
            pt = tuple(vals[row_i] for _, vals in pt_vals)
            gen_rows = _generate_rows(
                p.generator, [col[row_i] for col in arg_vals],
                [f.name for f in p.gen_schema])
            if not gen_rows and p.outer:
                gen_rows = [tuple([None] * len(p.gen_schema))]
            for g in gen_rows:
                out_rows.append(pt + g)
        names = [n for n, _ in p.passthrough] + \
            [f.name for f in p.gen_schema]
        types = [rx.rex_type(r) for _, r in p.passthrough] + \
            [f.dtype for f in p.gen_schema]
        arrays = []
        for ci, (n, t) in enumerate(zip(names, types)):
            at = ai.spec_type_to_arrow(t)
            vals = [r[ci] for r in out_rows]
            from .host_interp import _pyarrowable
            arrays.append(pa.array([_pyarrowable(v, t) for v in vals],
                                   type=at))
        table = pa.Table.from_arrays(arrays, names=[f"c{i}" for i in
                                                    range(len(names))])
        return ai.from_arrow(table)

    # -- PySpark UDF relations (host-evaluated; reference:
    # sail-python-udf group/cogroup map + map-iter kinds) ---------------
    def _named_arrow(self, p_input) -> "pa.Table":
        child = self.run(p_input)
        table = ai.to_arrow(child)
        return table.rename_columns([f.name for f in p_input.schema])

    def _udf_result_to_batch(self, frames, out_schema) -> HostBatch:
        """pandas frames / arrow batches from a UDF → HostBatch matching
        the DECLARED output schema (cast, reorder, missing → error)."""
        import pandas as pd

        tables = []
        for f in frames:
            if isinstance(f, pa.Table):
                tables.append(f)
            elif isinstance(f, pa.RecordBatch):
                tables.append(pa.Table.from_batches([f]))
            elif isinstance(f, pd.DataFrame):
                tables.append(pa.Table.from_pandas(f, preserve_index=False))
            else:
                raise TypeError(
                    f"UDF returned {type(f).__name__}; expected DataFrame "
                    f"or arrow batch")
        names = [f.name for f in out_schema]
        types = [ai.spec_type_to_arrow(f.dtype) for f in out_schema]
        if not tables:
            table = pa.Table.from_arrays(
                [pa.array([], type=t) for t in types], names=names)
        else:
            table = pa.concat_tables(tables, promote_options="permissive")
            missing = [n for n in names if n not in table.column_names]
            if missing:
                raise ValueError(
                    f"UDF output is missing declared columns {missing}")
            cols = [table.column(n).cast(t, safe=False)
                    for n, t in zip(names, types)]
            table = pa.Table.from_arrays(cols, names=names)
        return _positional(ai.from_arrow(table))

    @staticmethod
    def _udf_arity(func, default: int) -> int:
        import inspect
        try:
            return len(inspect.signature(func).parameters)
        except (TypeError, ValueError):
            return default

    @staticmethod
    def _norm_key(key) -> tuple:
        """Group keys as comparable tuples: pandas represents null keys
        as NaN, and NaN != NaN would split one logical group across the
        two cogroup sides — normalize to None."""
        kt = key if isinstance(key, tuple) else (key,)
        return tuple(None if (isinstance(x, float) and x != x) else x
                     for x in kt)

    def _exec_UdtfExec(self, p: pn.UdtfExec) -> HostBatch:
        """Python UDTF: handler.eval(*args) yields rows (tuples or
        scalars); terminate() may yield trailing rows."""
        inst = p.handler() if isinstance(p.handler, type) else p.handler
        rows = []

        def extend(gen):
            if gen is None:
                return
            for row in gen:
                if not isinstance(row, (tuple, list)):
                    row = (row,)
                rows.append(tuple(row))

        extend(inst.eval(*p.args))
        if hasattr(inst, "terminate"):
            extend(inst.terminate())
        names = [f.name for f in p.out_schema]
        types = [ai.spec_type_to_arrow(f.dtype) for f in p.out_schema]
        arrays = []
        for ci, t in enumerate(types):
            arrays.append(pa.array(
                [r[ci] if ci < len(r) else None for r in rows], type=t))
        table = pa.Table.from_arrays(arrays, names=names)
        return _positional(ai.from_arrow(table))

    def _exec_GroupMapExec(self, p: pn.GroupMapExec) -> HostBatch:
        table = self._named_arrow(p.input)
        pdf = table.to_pandas()
        key_cols = [table.column_names[i] for i in p.key_indices]
        func = p.udf.func
        wants_key = self._udf_arity(func, 1) >= 2
        outs = []
        if len(pdf) and key_cols:
            for key, g in pdf.groupby(key_cols, dropna=False, sort=True):
                g = g.reset_index(drop=True)
                if wants_key:
                    k = key if isinstance(key, tuple) else (key,)
                    outs.append(func(k, g))
                else:
                    outs.append(func(g))
        elif len(pdf):
            outs.append(func(pdf))
        return self._udf_result_to_batch(outs, p.out_schema)

    def _exec_CoGroupMapExec(self, p: pn.CoGroupMapExec) -> HostBatch:
        import pandas as pd

        lt = self._named_arrow(p.left)
        rt = self._named_arrow(p.right)
        lpdf, rpdf = lt.to_pandas(), rt.to_pandas()
        lk = [lt.column_names[i] for i in p.left_keys]
        rk = [rt.column_names[i] for i in p.right_keys]
        lgroups = {self._norm_key(k): g
                   for k, g in lpdf.groupby(lk, dropna=False, sort=True)} \
            if len(lpdf) else {}
        rgroups = {self._norm_key(k): g
                   for k, g in rpdf.groupby(rk, dropna=False, sort=True)} \
            if len(rpdf) else {}
        func = p.udf.func
        nparams = self._udf_arity(func, 2)
        outs = []
        for key in sorted(set(lgroups) | set(rgroups),
                          key=lambda k: tuple(str(x) for x in k)):
            lg = lgroups.get(key)
            rg = rgroups.get(key)
            lg = (lg.reset_index(drop=True) if lg is not None
                  else lpdf.iloc[0:0].copy())
            rg = (rg.reset_index(drop=True) if rg is not None
                  else rpdf.iloc[0:0].copy())
            if nparams >= 3:
                outs.append(func(key, lg, rg))
            else:
                outs.append(func(lg, rg))
        return self._udf_result_to_batch(outs, p.out_schema)

    def _exec_MapPartitionsExec(self, p: pn.MapPartitionsExec) -> HostBatch:
        table = self._named_arrow(p.input)
        func = p.udf.func
        if p.udf.eval_type == "map_arrow":
            it = func(iter(table.to_batches()))
            outs = list(it)
        else:  # map_pandas
            it = func(iter([table.to_pandas()]))
            outs = list(it)
        return self._udf_result_to_batch(outs, p.out_schema)

    def _exec_FilterExec(self, p: pn.FilterExec) -> HostBatch:
        if self._fusion_on():
            out = self._try_fused_chain(p)
            if out is not None:
                return out
        return self._filter_over(p, self.run(p.input))

    def _filter_over(self, p: pn.FilterExec, child: HostBatch
                     ) -> HostBatch:
        dev = child.device

        def builder():
            comp = self._compiler(child, p.input.schema)
            c = comp.compile(p.condition)

            def fn(cols, sel):
                data, validity = c.fn(cols)
                keep = data.astype(jnp.bool_)
                if validity is not None:
                    keep = keep & validity
                return sel & keep

            return fn, None

        key = self._op_key("filter", p.condition,
                           tuple((f.name, f.dtype) for f in p.input.schema))
        try:
            fn, _ = self._jitted(key, self._dict_objs(child), builder)
        except HostFallback:
            # host-only predicate (arrays/json/…): interpret row-wise
            from .host_interp import HostInterpreter
            comp = self._compiler(child, p.input.schema)
            vals = HostInterpreter(self, comp, child).values(p.condition)
            keep = jnp.asarray(np.array([v is True for v in vals]))
            return HostBatch(dev.with_sel(dev.sel & keep), child.dicts)
        return HostBatch(dev.with_sel(fn(self._cols(child), dev.sel)),
                         child.dicts)

    def _exec_LimitExec(self, p: pn.LimitExec) -> HostBatch:
        child = self.run(p.input)
        dev = child.device
        if p.offset == -1:  # tail
            n = int(dev.num_rows())
            off = max(0, n - (p.limit or 0))
            out = sortk.limit(dev, p.limit or 0, off)
        else:
            out = sortk.limit(dev, p.limit if p.limit is not None else dev.capacity,
                              p.offset)
        return HostBatch(out, child.dicts)

    def _exec_SortExec(self, p: pn.SortExec) -> HostBatch:
        chain: List[pn.PlanNode] = []
        node = p.input
        if self._fusion_on():
            while isinstance(node, (pn.FilterExec, pn.ProjectExec)):
                chain.append(node)
                node = node.input
        if not chain:
            child = self.run(p.input)
            spilled = self._try_external_sort(p, child)
            if spilled is not None:
                return spilled
            return self._sort_over(p, child)
        # pre-sort pipeline: chain + key eval + gather compile to ONE
        # program. Out-of-core candidates (bounded by the BOTTOM batch's
        # capacity, so no device sync decides this) materialize the
        # chain first and keep the spill path byte-identical.
        child = self.run(node)
        if self._sort_may_spill(p, child):
            mat = self._apply_chain(chain, child, node)
            spilled = self._try_external_sort(p, mat)
            if spilled is not None:
                return spilled
            return self._sort_over(p, mat)
        return self._fused_sort(p, chain, child, node)

    def _sort_may_spill(self, p: pn.SortExec, child: HostBatch) -> bool:
        """Upper-bound spill check: capacity >= live rows, so a False
        here is exact (the external sort could never engage) without
        forcing a device sync on the hot path."""
        from ..config import get as config_get
        try:
            threshold = int(config_get("execution.sort_spill_rows",
                                       8_000_000))
        except (TypeError, ValueError):
            threshold = 8_000_000
        if threshold <= 0 or not p.keys:
            return False
        if any(not isinstance(k.expr, rx.BoundRef) for k in p.keys):
            return False
        return child.device.capacity > threshold

    def _fused_sort(self, p: pn.SortExec, chain: List[pn.PlanNode],
                    child: HostBatch, bottom: pn.PlanNode) -> HostBatch:
        from ..plan import stages as pst

        key = self._op_key(
            "fused_sort", pst.stage_fingerprint([p] + chain,
                                                bottom.schema))

        def builder():
            chain_fn, top_dicts, top_schema = self._compile_chain(
                chain, child, bottom)
            top_schema = tuple(top_schema)
            comp = ExprCompiler(
                [f.dtype for f in top_schema],
                {i: top_dicts[_col_name(i)]
                 for i in range(len(top_schema))
                 if _col_name(i) in top_dicts},
                self._subquery_cache)
            compiled = [(comp.compile(k.expr), k) for k in p.keys]
            rank_luts = []
            for c, k in compiled:
                rank_luts.append(
                    jnp.asarray(ai.dictionary_ranks(c.dictionary))
                    if c.dictionary is not None
                    and len(c.dictionary) > 0 else None)

            def fn(cols, sel):
                pairs, sel2 = chain_fn(cols, sel)
                cap = sel2.shape[0]
                fitted = [_fit_capacity(d, v, cap) for d, v in pairs]
                keys = []
                for (c, k), lut in zip(compiled, rank_luts):
                    data, validity = c.fn(fitted)
                    kdt = rx.rex_type(k.expr)
                    if lut is not None:
                        data = lut[data]
                        kdt = dt.IntegerType()
                    keys.append((data, validity, kdt, k.ascending,
                                 k.nulls_first))
                perm = sortk.lexsort_perm(keys, sel2)
                out_d = [d[perm] for d, _ in fitted]
                out_v = [None if v is None else v[perm]
                         for _, v in fitted]
                out_sel = sel2[perm]
                if p.limit is not None:
                    idx = jnp.arange(out_sel.shape[0], dtype=jnp.int32)
                    out_sel = out_sel & (idx < p.limit)
                return out_d, out_v, out_sel

            return fn, (top_dicts, top_schema)

        from .. import telemetry as tel
        try:
            fn, aux = self._jitted(key, self._dict_objs(child), builder,
                                   fused=True)
        except HostFallback:
            # count the declined pipeline ONCE and apply the chain
            # per-op directly — re-attempting the fused chain program
            # here would recompile the same failing bind a second time
            self._note_fusion_fallback("sort")
            mat = child
            for op in reversed(chain):
                mat = self._apply_op(op, mat)
            return self._sort_over(p, mat)

        def finish():
            top_dicts, top_schema = aux
            out_d, out_v, out_sel = fn(self._cols(child),
                                       child.device.sel)
            cols = {_col_name(i): Column(d, v, f.dtype)
                    for i, (d, v, f) in enumerate(
                        zip(out_d, out_v, top_schema))}
            out = DeviceBatch(cols, out_sel)
            if p.limit is not None:
                out = _shrink(out, p.limit)
            return HostBatch(out, top_dicts)

        if tel.current_collector() is not None:
            ops = "+".join(type(n).__name__ for n in chain)
            with tel.operator_span("FusedSort", ops) as m:
                out = finish()
                m.output_rows = int(out.device.num_rows())
                m.capacity = out.capacity
                return out
        return finish()

    def _sort_over(self, p: pn.SortExec, child: HostBatch) -> HostBatch:
        def builder():
            comp = self._compiler(child, p.input.schema)
            compiled = [(comp.compile(k.expr), k) for k in p.keys]
            rank_luts = []
            for c, k in compiled:
                # an empty dictionary (0-row input) has no codes to remap —
                # and a 0-size LUT gather is a compile error
                rank_luts.append(jnp.asarray(ai.dictionary_ranks(c.dictionary))
                                 if c.dictionary is not None
                                 and len(c.dictionary) > 0 else None)

            def fn(cols, sel, datas, validities):
                keys = []
                for (c, k), lut in zip(compiled, rank_luts):
                    data, validity = c.fn(cols)
                    kdt = rx.rex_type(k.expr)
                    if lut is not None:
                        data = lut[data]
                        kdt = dt.IntegerType()
                    keys.append((data, validity, kdt, k.ascending, k.nulls_first))
                perm = sortk.lexsort_perm(keys, sel)
                out_d = [d[perm] for d in datas]
                out_v = [None if v is None else v[perm] for v in validities]
                out_sel = sel[perm]
                if p.limit is not None:
                    idx = jnp.arange(out_sel.shape[0], dtype=jnp.int32)
                    out_sel = out_sel & (idx < p.limit)
                return out_d, out_v, out_sel

            return fn, None

        key = self._op_key("sort", p.keys, p.limit,
                           tuple((f.name, f.dtype) for f in p.input.schema))
        try:
            fn, _ = self._jitted(key, self._dict_objs(child), builder)
        except HostFallback:
            # host-only sort keys (struct fields, host functions)
            return self._sort_host_fallback(p, child)
        dev = child.device
        names = [_col_name(i) for i in range(len(dev.columns))]
        datas = [dev.columns[n].data for n in names]
        validities = [dev.columns[n].validity for n in names]
        out_d, out_v, out_sel = fn(self._cols(child), dev.sel, datas, validities)
        cols = {n: Column(d, v, dev.columns[n].dtype)
                for n, d, v in zip(names, out_d, out_v)}
        out = DeviceBatch(cols, out_sel)
        if p.limit is not None:
            out = _shrink(out, p.limit)
        return HostBatch(out, child.dicts)

    def _sort_host_fallback(self, p: pn.SortExec,
                            child: HostBatch) -> HostBatch:
        """Sort keys the device compiler cannot express (struct fields,
        host-only functions): key VALUES come from the host interpreter,
        the permutation from a stable pandas sort, and the row gather
        stays on device."""
        import jax
        import pandas as pd

        from .host_interp import HostInterpreter

        comp = self._compiler(child, p.input.schema)
        interp = HostInterpreter(self, comp, child)
        sel = np.asarray(jax.device_get(child.device.sel))
        frame: Dict[str, object] = {"__dead": ~sel}
        by = ["__dead"]          # dead rows sort to the end
        asc = [True]
        for i, k in enumerate(p.keys):
            vals = interp.values(k.expr)
            nulls_first = k.nulls_first if k.nulls_first is not None \
                else k.ascending
            isna = np.array([v is None for v in vals], dtype=bool)
            frame[f"n{i}"] = ~isna if nulls_first else isna
            by.append(f"n{i}")
            asc.append(True)
            fill = next((v for v in vals if v is not None), None)
            frame[f"k{i}"] = [fill if v is None else v for v in vals]
            by.append(f"k{i}")
            asc.append(k.ascending)
        perm = jnp.asarray(pd.DataFrame(frame).sort_values(
            by, ascending=asc, kind="stable").index.to_numpy())
        dev = child.device
        cols = {nm: Column(c.data[perm],
                           None if c.validity is None else c.validity[perm],
                           c.dtype)
                for nm, c in dev.columns.items()}
        out_sel = dev.sel[perm]
        if p.limit is not None:
            idx = jnp.arange(out_sel.shape[0], dtype=jnp.int32)
            out_sel = out_sel & (idx < p.limit)
        out = DeviceBatch(cols, out_sel)
        if p.limit is not None:
            out = _shrink(out, p.limit)
        return HostBatch(out, child.dicts)

    def _pipeline_chain(self, p: pn.PlanNode):
        """Collect the Filter/Project chain under ``p`` (top-down) and the
        batch below it — the chain fuses into the consumer's jit so XLA
        sees one program (no intermediate HBM materialization)."""
        chain = []
        node = p
        while isinstance(node, (pn.FilterExec, pn.ProjectExec)):
            chain.append(node)
            node = node.input
        child = self.run(node)
        return chain, child, node

    # -- whole-stage fusion: standalone pipeline stages -----------------
    def _try_fused_chain(self, top: pn.PlanNode) -> Optional[HostBatch]:
        """Execute a maximal Filter/Project pipeline as ONE jitted
        program (the ``pipeline`` stage of ``plan/stages.py``): the
        chain's intermediates never materialize between operators.
        Returns None when the chain is trivial (single operator — the
        per-op path already compiles one program) or needs host
        evaluation (the caller falls back per-op, which re-enters
        fusion on the shorter sub-chains)."""
        from .. import telemetry as tel

        chain: List[pn.PlanNode] = []
        node = top
        while isinstance(node, (pn.FilterExec, pn.ProjectExec)):
            chain.append(node)
            node = node.input
        if len(chain) < 2:
            return None
        child = self.run(node)
        try:
            if tel.current_collector() is not None:
                # aborted spans (HostFallback) are discarded by
                # operator_span, so the fallback run reports cleanly
                ops = "+".join(type(n).__name__ for n in chain)
                with tel.operator_span("FusedPipeline", ops) as m:
                    out = self._run_chain(chain, child, node)
                    m.output_rows = int(out.device.num_rows())
                    m.capacity = out.capacity
                    return out
            return self._run_chain(chain, child, node)
        except HostFallback:
            # per-op over the ALREADY-materialized bottom batch: falling
            # all the way back through run() would re-execute the input
            # subtree once per chain suffix (and over-count fallbacks)
            self._note_fusion_fallback("pipeline")
            out = child
            for op in reversed(chain):
                out = self._apply_op(op, out)
            return out

    def _run_chain(self, chain: List[pn.PlanNode], child: HostBatch,
                   bottom: pn.PlanNode) -> HostBatch:
        """One compiled program for a Filter/Project pipeline over an
        already-materialized bottom batch. Raises HostFallback when any
        chain expression needs host evaluation."""
        from ..plan import stages as pst

        key = self._op_key(
            "fused_chain", pst.stage_fingerprint(chain, bottom.schema))

        def builder():
            chain_fn, out_dicts, out_schema = self._compile_chain(
                chain, child, bottom)
            return chain_fn, (out_dicts, tuple(out_schema))

        fn, aux = self._jitted(key, self._dict_objs(child), builder,
                               fused=True)
        out_dicts, out_schema = aux
        cols2, sel2 = fn(self._cols(child), child.device.sel)
        if not any(isinstance(n, pn.ProjectExec) for n in chain):
            # filter-only pipeline: the batch's columns are untouched
            return HostBatch(child.device.with_sel(sel2), child.dicts)
        cap = child.device.sel.shape[0]
        out_cols: Dict[str, Column] = {}
        for i, ((d, v), f) in enumerate(zip(cols2, out_schema)):
            d, v = _fit_capacity(d, v, cap)
            out_cols[_col_name(i)] = Column(d, v, f.dtype)
        return HostBatch(DeviceBatch(out_cols, sel2), out_dicts)

    def _apply_op(self, op: pn.PlanNode, batch: HostBatch) -> HostBatch:
        """One Filter/Project over a given batch, with an operator span
        under EXPLAIN ANALYZE (these don't pass through ``run``)."""
        from .. import telemetry as tel

        def go():
            if isinstance(op, pn.FilterExec):
                return self._filter_over(op, batch)
            return self._project_over(op, batch)

        if tel.current_collector() is not None:
            with tel.operator_span(type(op).__name__) as m:
                out = go()
                m.output_rows = int(out.device.num_rows())
                m.capacity = out.capacity
                return out
        return go()

    def _apply_chain(self, chain: List[pn.PlanNode], child: HostBatch,
                     bottom: pn.PlanNode) -> HostBatch:
        """Materialize a chain's output over ``child``: the fused
        program when it compiles, per-operator evaluation otherwise."""
        if not chain:
            return child
        try:
            return self._run_chain(chain, child, bottom)
        except HostFallback:
            self._note_fusion_fallback("pipeline")
            out = child
            for op in reversed(chain):
                out = self._apply_op(op, out)
            return out

    def _compile_chain(self, chain, bottom: HostBatch, bottom_node: pn.PlanNode):
        """Returns (chain_fn, out_dicts, out_schema): chain_fn maps the
        bottom batch's (cols, sel) to the top of the chain's (cols, sel).
        Must be called at bind time (host): dictionaries propagate level by
        level."""
        levels = list(reversed(chain))  # bottom-up
        cur_batch = bottom
        cur_schema = bottom_node.schema
        steps = []
        for node in levels:
            comp = self._compiler(cur_batch, cur_schema)
            if isinstance(node, pn.FilterExec):
                c = comp.compile(node.condition)
                steps.append(("filter", c))
                # dicts/schema unchanged
            else:
                compiled = [comp.compile(e) for _, e in node.exprs]
                steps.append(("project", compiled,
                              [rx.rex_type(e) for _, e in node.exprs]))
                new_dicts = {_col_name(i): c.dictionary
                             for i, c in enumerate(compiled)
                             if c.dictionary is not None}
                # fabricate a dict-only HostBatch view for the next level's
                # compiler (only .dicts is consulted at bind time)
                cur_batch = HostBatch(cur_batch.device, new_dicts)
                cur_schema = node.schema
        out_dicts = dict(cur_batch.dicts)

        def chain_fn(cols, sel):
            for step in steps:
                if step[0] == "filter":
                    d, v = step[1].fn(cols)
                    keep = d.astype(jnp.bool_)
                    if v is not None:
                        keep = keep & v
                    sel = sel & keep
                else:
                    _, compiled, types = step
                    new_cols = []
                    for c, t in zip(compiled, types):
                        d, v = c.fn(cols)
                        jdt = physical_jnp_dtype(t)
                        if d.dtype != jnp.dtype(jdt):
                            d = d.astype(jdt)
                        new_cols.append((d, v))
                    cols = new_cols
            return cols, sel

        return chain_fn, out_dicts, cur_schema

    def _exec_AggregateExec(self, p: pn.AggregateExec) -> HostBatch:
        # Fuse the Filter/Project chain under the aggregate into ONE jitted
        # program: no intermediate batch materializes in HBM (the TPC-H Q1
        # hot path — filter, derived-expression projection, aggregation —
        # compiles to a single XLA executable). Under EXPLAIN ANALYZE run
        # unfused so every operator reports its own rows/time.
        from .. import telemetry as tel
        if any(a.fn.startswith("__host__") for a in p.aggs) or \
                any(a.distinct for a in p.aggs):
            return self._host_aggregate(p, self.run(p.input))
        chunked = self._try_chunked_aggregate(p)
        if chunked is not None:
            return chunked
        # Under EXPLAIN ANALYZE keep the PRODUCTION (fused) program and
        # report the pipeline as one fused operator — profiling must
        # measure the program that actually runs, not an unfused variant.
        chain, child, bottom_node = self._pipeline_chain(p.input)
        # CPU fallback fast path: fused C++ row loop over host buffers
        # (one pass for all aggregates; see sail_tpu/native/) — taken
        # only when the backend router's stage decision says native
        # (stage-split-time routing; `execution.backend.force` can pin
        # either substrate for A/B and bisection)
        from .. import native as _native
        from . import router

        from ..plan import stages as pst

        route = self._aggregate_route(p)
        go_native = _native.native_active() and \
            (route is None or route.backend == "native")
        obs_key = router.obs_key(
            tuple(pst.node_fingerprint(n) for n in [p] + chain))
        with router.observing(obs_key):
            if tel.current_collector() is not None:
                if go_native:
                    try:
                        with tel.operator_span(
                                "NativeFusedAggregate",
                                "fused C++ host kernel") as m:
                            native = _native.try_native_agg(
                                self, p, chain, child, bottom_node)
                            if native is None:
                                raise _NativeMiss()  # discard the span
                            m.output_rows = int(native.device.num_rows())
                            m.capacity = native.capacity
                            return native
                    except _NativeMiss:
                        pass
            elif go_native:
                native = _native.try_native_agg(self, p, chain, child,
                                                bottom_node)
                if native is not None:
                    return native
            return self._agg_xla_path(p, chain, child, bottom_node)

    def _aggregate_route(self, p: pn.AggregateExec):
        """The stage-split-time routing decision for this aggregate's
        stage, when one was recorded; a forced backend applies even
        when no split ran (fusion off)."""
        from . import router
        sid = self._route_stage_of.get(id(p))
        if sid is not None:
            dec = self._backend_routes.get(sid)
            if dec is not None:
                return dec
        force = router.forced_backend(self.config)
        if force:
            return router.Decision(-1, "aggregate",
                                   force if force != "mesh" else "xla",
                                   "forced")
        return None

    def _agg_xla_path(self, p, chain, child, bottom_node):
        from .. import telemetry as tel
        if tel.current_collector() is not None and chain:
            ops = "+".join(type(c).__name__ for c in chain)
            try:
                with tel.operator_span("FusedAggregate", ops) as m:
                    out = self._agg_with_chain(p, chain, child, bottom_node)
                    m.output_rows = int(out.device.num_rows())
                    m.capacity = out.capacity
                    return out
            except HostFallback:
                # the fused attempt aborted (span discarded): run and
                # profile the actual unfused program instead
                self._note_fusion_fallback("aggregate")
                child = self.run(chain[0])
                with tel.operator_span("AggregateExec",
                                       "unfused (host fallback)") as m:
                    out = self._agg_with_chain(p, [], child, p.input)
                    m.output_rows = int(out.device.num_rows())
                    m.capacity = out.capacity
                    return out
        return self._agg_with_chain_or_unfused(p, chain, child, bottom_node)

    def _agg_with_chain_or_unfused(self, p, chain, child, bottom_node):
        try:
            return self._agg_with_chain(p, chain, child, bottom_node)
        except HostFallback:
            # chains needing host evaluation (string UDFs, host-only casts)
            # cannot fuse — run the chain operators unfused instead
            if chain:
                self._note_fusion_fallback("aggregate")
                child = self.run(chain[0])
            return self._agg_with_chain(p, [], child, p.input)

    def _agg_with_chain(self, p: pn.AggregateExec, chain, child: HostBatch,
                        bottom_node: pn.PlanNode) -> HostBatch:
        dev = child.device
        in_schema = p.input.schema
        if p.group_indices:
            max_groups = p.max_groups_hint or dev.capacity
        else:
            max_groups = 1

        from ..plan import stages as pst
        stage_key = pst.stage_fingerprint([p] + chain, bottom_node.schema)

        def make_builder(mg):
            def builder():
                chain_fn, top_dicts, _ = self._compile_chain(chain, child,
                                                             bottom_node)
                # direct binning when every group key has a known small
                # domain (dictionary codes / booleans) — no sort needed.
                # Decided at bind time; the cache key's dictionary identity
                # pins the decision's inputs.
                domains = []
                for gi in p.group_indices:
                    f = in_schema[gi]
                    name = _col_name(gi)
                    if name in top_dicts:
                        domains.append(len(top_dicts[name]))
                    elif isinstance(f.dtype, dt.BooleanType):
                        domains.append(2)
                    else:
                        domains.append(None)
                direct_total = 1
                for d in domains:
                    direct_total = direct_total * (d + 1) if d is not None else None
                    if direct_total is None:
                        break
                use_direct = (p.group_indices and direct_total is not None
                              and direct_total <= 4096)

                # min/max over a dictionary-encoded column must order by
                # VALUE, not code: remap codes through an order-preserving
                # rank LUT before the segment reduce and back after
                minmax_luts = {}
                for j, a in enumerate(p.aggs):
                    if a.fn in ("min", "max") and a.arg is not None:
                        name = _col_name(a.arg)
                        if name in top_dicts and len(top_dicts[name]) > 1:
                            ranks = _dict_order_ranks(top_dicts[name])
                            inv = np.empty_like(ranks)
                            inv[ranks] = np.arange(len(ranks),
                                                   dtype=ranks.dtype)
                            minmax_luts[j] = (jnp.asarray(ranks),
                                              jnp.asarray(inv))

                def fn(cols, sel):
                    cols, sel = chain_fn(cols, sel)
                    key_cols = [Column(cols[i][0], cols[i][1],
                                       in_schema[i].dtype)
                                for i in p.group_indices]
                    if use_direct:
                        ctx, sorted_keys = aggk.group_rows_direct(
                            key_cols, domains, sel)
                    else:
                        ctx, sorted_keys = aggk.group_rows(key_cols, sel, mg)
                    gkeys = aggk.group_key_output(ctx, sorted_keys)
                    outs = []
                    for j, a in enumerate(p.aggs):
                        arg = None if a.arg is None else \
                            Column(cols[a.arg][0], cols[a.arg][1],
                                   in_schema[a.arg].dtype)
                        lut = minmax_luts.get(j)
                        if lut is not None:
                            ranks_lut, inv_lut = lut
                            codes = jnp.clip(arg.data, 0,
                                             ranks_lut.shape[0] - 1)
                            arg = Column(ranks_lut[codes], arg.validity,
                                         arg.dtype)
                            col = self._run_agg(ctx, a, arg)
                            col = Column(
                                inv_lut[jnp.clip(col.data, 0,
                                                 inv_lut.shape[0] - 1)],
                                col.validity, col.dtype)
                        else:
                            col = self._run_agg(ctx, a, arg)
                        outs.append((col.data, col.validity))
                    return ([(g.data, g.validity) for g in gkeys], outs,
                            aggk.group_sel(ctx), ctx.num_groups,
                            aggk.group_overflow(ctx))
                return fn, top_dicts
            return builder

        import jax

        key = self._op_key("agg", stage_key, max_groups)
        fn, top_dicts = self._jitted(key, self._dict_objs(child),
                                     make_builder(max_groups),
                                     fused=bool(chain))
        gk, aggs_out, gsel, n_groups, overflow = fn(self._cols(child), dev.sel)
        # one batched fetch: each blocking scalar read is a full round trip
        # on a remote accelerator
        n_groups, overflow = jax.device_get((n_groups, overflow))
        if p.max_groups_hint and bool(overflow):
            key2 = self._op_key("agg2", stage_key, dev.capacity)
            fn2, top_dicts = self._jitted(key2, self._dict_objs(child),
                                          make_builder(dev.capacity),
                                          fused=bool(chain))
            gk, aggs_out, gsel, n_groups, overflow = fn2(self._cols(child), dev.sel)
            n_groups = jax.device_get(n_groups)
        out_cols: Dict[str, Column] = {}
        out_dicts: Dict[str, pa.Array] = {}
        for j, gi in enumerate(p.group_indices):
            k = _col_name(j)
            out_cols[k] = Column(gk[j][0], gk[j][1], in_schema[gi].dtype)
            src = _col_name(gi)
            if src in top_dicts:
                out_dicts[k] = top_dicts[src]
        ng = len(p.group_indices)
        for j, a in enumerate(p.aggs):
            k = _col_name(ng + j)
            out_cols[k] = Column(aggs_out[j][0], aggs_out[j][1], a.out_dtype)
            if a.arg is not None and a.fn in ("min", "max", "first", "last"):
                src = _col_name(a.arg)
                if src in top_dicts:
                    out_dicts[k] = top_dicts[src]
        out = DeviceBatch(out_cols, gsel)
        out = _shrink(out, int(n_groups),
                      bucket_key=("agg-shrink", pst.node_fingerprint(p)))
        return HostBatch(out, out_dicts)

    # out-of-core: aggregates over big parquet scans stream chunk-wise
    # through the fused partial-agg program, so a table never needs to fit
    # in HBM whole (reference role: DataFusion memory pools + morsel scan;
    # TPU shape: fixed-capacity chunks re-use ONE compiled XLA program).
    # The scan side is PIPELINED: a bounded background producer drives
    # parquet decode + declared-schema normalization while this thread
    # runs the jitted partial-aggregate on the previous chunk, and
    # partials fold incrementally so peak host memory stays bounded by
    # prefetch depth × chunk size rather than the number of chunks.
    _CHUNK_MERGE = {"sum": "sum", "count": "sum", "min": "min",
                    "max": "max", "first": "first", "last": "last",
                    "bool_and": "bool_and", "bool_or": "bool_or"}

    def _prefetch_depth(self) -> int:
        from ..io.prefetch import prefetch_depth
        return prefetch_depth(self.config)

    def _try_chunked_aggregate(self, p: pn.AggregateExec
                               ) -> Optional[HostBatch]:
        import pyarrow.dataset as pads
        from .. import telemetry as tel
        from ..io.formats import expand_paths, rex_predicates_to_arrow
        from ..io.prefetch import Prefetcher

        if any(a.distinct or a.fn not in self._CHUNK_MERGE or
               a.filter is not None for a in p.aggs):
            return None
        # find the chain bottom scan
        node = p.input
        while isinstance(node, (pn.FilterExec, pn.ProjectExec)):
            node = node.input
        if not (isinstance(node, pn.ScanExec) and node.paths
                and node.format == "parquet"):
            return None
        chunk_rows = int(self.config.get("spark.sail.scan.chunkRows", 0) or 0)
        try:
            files = expand_paths(node.paths)
            total_bytes = sum(os.path.getsize(f) for f in files)
        except OSError:
            return None
        if chunk_rows <= 0:
            if total_bytes < 1 << 30:
                return None  # small scans take the resident path
            chunk_rows = 8_000_000
        filter_expr = None
        if node.predicates:
            from ..io.formats import row_group_pruning_enabled
            if row_group_pruning_enabled():
                filter_expr = rex_predicates_to_arrow(node.predicates,
                                                      node.schema)
        ds = pads.dataset(files, format="parquet")
        scanner = ds.scanner(
            columns=list(node.projection) if node.projection else None,
            filter=filter_expr, batch_size=chunk_rows)
        nk = len(p.group_indices)
        part_schema = tuple(
            pn.Field(f"p{i}", f.dtype, True)
            for i, f in enumerate(p.schema))
        final_aggs = tuple(
            pn.AggSpec(self._CHUNK_MERGE[a.fn], nk + j, False, a.out_dtype,
                       None, a.ignore_nulls)
            for j, a in enumerate(p.aggs))

        def merge_plan(partials_table: pa.Table) -> pn.AggregateExec:
            return pn.AggregateExec(
                pn.ScanExec(part_schema, partials_table, (), "memory"),
                tuple(range(nk)), final_aggs, p.out_names,
                p.max_groups_hint)

        def chunks():
            # coalesce scanner batches up to chunk_rows: parquet hands
            # back row-group-sized batches no matter what batch_size
            # asks for, and every undersized chunk pays a full
            # plan-rewrite + executor dispatch — amortize it
            acc, rows = [], 0
            for b in scanner.to_batches():
                if b.num_rows == 0:
                    continue
                acc.append(b)
                rows += b.num_rows
                if rows >= chunk_rows:
                    yield acc
                    acc, rows = [], 0
            if acc:
                yield acc

        def decode(batches) -> pa.Table:
            # runs on the producer thread: Arrow materialization and
            # schema normalization overlap the consumer's jitted compute
            table = pa.Table.from_batches(batches)
            return self._apply_declared_schema(table, node.schema)

        depth = self._prefetch_depth()
        src = chunks()
        pending: List[pa.Table] = []
        pending_rows = 0
        folded_rows = 0
        with Prefetcher(src, transform=decode, depth=depth,
                        kind="scan") as pf:
            for table in pf:
                chunk_scan = pn.ScanExec(node.out_schema, table, (),
                                         "memory",
                                         projection=node.projection)
                chunk_plan = _replace_node(p, node, chunk_scan)
                pending.append(ai.to_arrow(self.run(chunk_plan)))
                pending_rows += pending[-1].num_rows
                # drop the scan cache entry so chunks don't pile up in HBM
                _drop_mem_scan_entry(table)
                if len(pending) > 1 and \
                        pending_rows > max(chunk_rows, 2 * folded_rows):
                    # streaming fold: compact accumulated partials through
                    # the merge aggregate instead of holding them all for
                    # one giant end-of-scan concat. The 2× guard keeps
                    # high-cardinality groupings amortized O(n): a fold
                    # that can't shrink below the distinct-group count
                    # must not re-run after every chunk
                    folded = pa.concat_tables(pending,
                                              promote_options="permissive")
                    compacted = ai.to_arrow(self.run(merge_plan(folded)))
                    _drop_mem_scan_entry(folded)
                    pending = [compacted]
                    pending_rows = compacted.num_rows
                    folded_rows = pending_rows
        tel.note("ScanPrefetch", "chunked scan→aggregate",
                 **pf.stats.as_extra())
        if not pending:
            empty_scan = pn.ScanExec(node.out_schema,
                                     _empty_arrow(node.schema), (),
                                     "memory", projection=node.projection)
            return self.run(_replace_node(p, node, empty_scan))
        merged = pa.concat_tables(pending, promote_options="permissive")
        out = self.run(merge_plan(merged))
        _drop_mem_scan_entry(merged)
        return out

    def _host_aggregate(self, p: pn.AggregateExec, child: HostBatch
                        ) -> HostBatch:
        """Python grouping path for the statistical/collection aggregate
        tail (reference role: sail-function aggregates). The group slices
        reaching here are already small; the hot sum/count/min/max path
        stays on the device segment kernels."""
        from ..functions.host_aggregates import HOST_AGGS

        table = ai.to_arrow(child)
        cols = {i: _norm_intervals(table.column(i).to_pylist())
                for i in range(table.num_columns)}
        n = table.num_rows
        if p.group_indices:
            groups: Dict[tuple, list] = {}
            for r in range(n):
                key = tuple(_hashable(cols[g][r]) for g in p.group_indices)
                groups.setdefault(key, []).append(r)
            items = list(groups.items())
        else:
            items = [((), list(range(n)))]
        key_out: List[list] = [[] for _ in p.group_indices]
        agg_out: List[list] = [[] for _ in p.aggs]
        for key, rows_idx in items:
            for ki, g in enumerate(p.group_indices):
                key_out[ki].append(cols[g][rows_idx[0]])
            for ai_, spec in enumerate(p.aggs):
                agg_out[ai_].append(
                    _host_agg_one(spec, cols, rows_idx, HOST_AGGS))
        import pyarrow as pa
        arrays = []
        names = []
        in_schema = p.input.schema
        for ki, g in enumerate(p.group_indices):
            at = ai.spec_type_to_arrow(in_schema[g].dtype)
            vals_k = [_intervalize(v, in_schema[g].dtype)
                      for v in key_out[ki]]
            arrays.append(pa.array(vals_k, type=at))
            names.append(p.out_names[ki])
        for ai_, spec in enumerate(p.aggs):
            at = ai.spec_type_to_arrow(spec.out_dtype)
            agg_out[ai_] = [_intervalize(v, spec.out_dtype)
                            for v in agg_out[ai_]]
            try:
                arrays.append(pa.array(agg_out[ai_], type=at))
            except (pa.ArrowInvalid, pa.ArrowTypeError):
                # coerce through the declared type rather than silently
                # changing the column type the plan schema promised
                from .host_interp import py_cast
                coerced = [None if v is None else
                           py_cast(v, dt.NullType(), spec.out_dtype)
                           for v in agg_out[ai_]]
                arrays.append(pa.array(coerced, type=at))
            names.append(p.out_names[len(p.group_indices) + ai_])
        out = pa.Table.from_arrays(arrays, names=names)
        return _positional(ai.from_arrow(out))

    def _run_agg(self, ctx, a: pn.AggSpec, arg: Optional[Column]) -> Column:
        if a.fn == "count":
            return aggk.agg_count(ctx, arg)
        if a.fn == "sum":
            return aggk.agg_sum(ctx, arg, a.out_dtype)
        if a.fn == "min":
            return aggk.agg_min_max(ctx, arg, is_min=True)
        if a.fn == "max":
            return aggk.agg_min_max(ctx, arg, is_min=False)
        if a.fn == "first":
            return aggk.agg_first_last(ctx, arg, is_first=True,
                                       ignore_nulls=a.ignore_nulls)
        if a.fn == "last":
            return aggk.agg_first_last(ctx, arg, is_first=False,
                                       ignore_nulls=a.ignore_nulls)
        if a.fn == "bool_and":
            return aggk.agg_bool(ctx, arg, is_any=False)
        if a.fn == "bool_or":
            return aggk.agg_bool(ctx, arg, is_any=True)
        raise ExecutionError(f"aggregate {a.fn!r} not implemented")

    # ------------------------------------------------------------------
    # joins
    # ------------------------------------------------------------------
    def _exec_JoinExec(self, p: pn.JoinExec) -> HostBatch:
        left, right, rtf = self._run_join_inputs(p)
        jt = p.join_type
        if jt == "anti" and p.null_aware:
            return self._null_aware_anti(p, left, right)
        if jt in ("cross", "inner") and not p.left_keys:
            out = self._cross_join(p, left, right)
            if p.residual is not None:
                comb_schema = tuple(p.left.schema) + tuple(p.right.schema)
                comp = ExprCompiler(
                    [f.dtype for f in comb_schema],
                    {i: out.dicts[_col_name(i)] for i in range(len(comb_schema))
                     if _col_name(i) in out.dicts},
                    self._subquery_cache)
                c = comp.compile(p.residual)
                data, validity = self._eval(c, out)
                keep = data.astype(jnp.bool_)
                if validity is not None:
                    keep = keep & validity
                out = HostBatch(out.device.with_sel(out.device.sel & keep),
                                out.dicts)
            return out
        if jt == "right":
            flipped = pn.JoinExec(p.right, p.left, "left", p.right_keys,
                                  p.left_keys,
                                  _flip_residual(p.residual, len(p.left.schema),
                                                 len(p.right.schema)))
            out = self._join(flipped, right, left)
            return _reorder_right(out, len(p.right.schema), len(p.left.schema))
        return self._join(p, left, right, rtf=rtf)

    def _null_aware_anti(self, p: pn.JoinExec, left: HostBatch,
                         right: HostBatch) -> HostBatch:
        """NOT IN (subquery) anti join (reference role:
        crates/sail-plan null-aware anti join selection).

        The IN key is the last key pair; earlier pairs are correlation
        keys. NOT IN over an empty set is TRUE; any NULL build key makes
        every membership test unknown (no rows); NULL probe keys are
        excluded while the build side is non-empty.
        """
        rcomp = self._compiler(right, p.right.schema)
        _, rval = self._eval(rcomp.compile(p.right_keys[-1]), right)
        rsel = right.device.sel
        if int(jnp.sum(rsel)) == 0:
            return left
        # Residual conjuncts are per-row correlation too: the membership set
        # differs per probe row, so the global NULL shortcuts don't apply.
        correlated = len(p.left_keys) > 1 or p.residual is not None
        if rval is not None and bool(jnp.any(rsel & ~rval)):
            if correlated:
                raise ExecutionError(
                    "correlated NOT IN with NULL subquery keys not supported")
            return HostBatch(
                left.device.with_sel(jnp.zeros_like(left.device.sel)),
                left.dicts)
        out = self._join(p, left, right)
        lcomp = self._compiler(left, p.left.schema)
        _, lval = self._eval(lcomp.compile(p.left_keys[-1]), left)
        if lval is not None and bool(jnp.any(left.device.sel & ~lval)):
            if correlated:
                raise ExecutionError(
                    "correlated NOT IN with NULL probe keys not supported")
            out = HostBatch(out.device.with_sel(out.device.sel & lval),
                            out.dicts)
        return out

    # -- runtime join filters (sideways information passing) -----------
    def _run_join_inputs(self, p: pn.JoinExec):
        """Run a join's children. For runtime-filter-annotated inner/semi
        joins the estimated-SMALLER side runs first; a filter derived
        from its keys is pushed into the other subtree's annotated scans
        before that side executes, and a device bloom mask is handed to
        ``_join`` for the filtered side's selection. Forward = build
        (right) filters probe; reverse = probe (left) filters build —
        the direction that matters when join reordering made the fact
        table the build side of the topmost joins."""
        conf = self._rtf_conf()
        use = (conf.enabled and p.runtime_filters and p.left_keys
               and p.join_type in ("inner", "semi") and not p.null_aware)
        if not use:
            return self.run(p.left), self.run(p.right), None
        try:
            est_l, est_r = _rtf_est_rows(p.left), _rtf_est_rows(p.right)
        except Exception:  # noqa: BLE001 — estimation is advisory
            est_l = est_r = None
        reverse = (est_l is not None and est_r is not None
                   and est_l < est_r
                   and any(t.side == "build" for t in p.runtime_filters))
        if reverse:
            left = self.run(p.left)
            rtf, build_plan = self._rtf_prepare(p, left, conf, True,
                                                est_l, est_r)
            right = self.run(build_plan)
        else:
            right = self.run(p.right)
            rtf, probe_plan = self._rtf_prepare(p, right, conf, False,
                                                est_r, est_l)
            left = self.run(probe_plan)
        return left, right, rtf

    def _rtf_conf(self) -> "_RtfConf":
        from ..config import get as config_get

        def setting(spark_key: str, app_key: str, default):
            v = self.config.get(spark_key)
            if v is None:
                v = config_get(app_key, default)
            return v

        def as_bool(v) -> bool:
            return str(v).strip().lower() not in ("0", "false", "off",
                                                  "no")

        def as_int(v, d: int) -> int:
            try:
                return int(v)
            except (TypeError, ValueError):
                return d

        def as_float(v, d: float) -> float:
            try:
                return float(v)
            except (TypeError, ValueError):
                return d

        pfx = "spark.sail.join.runtimeFilter."
        apfx = "join.runtime_filter."
        return _RtfConf(
            enabled=as_bool(setting(pfx + "enabled",
                                    apfx + "enabled", "true")),
            min_build_rows=as_int(setting(pfx + "minBuildRows",
                                          apfx + "min_build_rows", 0), 0),
            max_bits=max(1024, as_int(setting(pfx + "maxBits",
                                              apfx + "max_bits",
                                              1 << 20), 1 << 20)),
            in_list_max=as_int(setting(pfx + "inListMax",
                                       apfx + "in_list_max", 8192), 8192),
            ndv_ratio=as_float(setting(pfx + "ndvRatio",
                                       apfx + "ndv_ratio", 0.75), 0.75),
            min_selectivity=as_float(setting(pfx + "minSelectivity",
                                             apfx + "min_selectivity",
                                             0.02), 0.02))

    def _rtf_history_key(self, p: pn.JoinExec, reverse: bool):
        # the verdict must be specific to THIS query's join, not just its
        # key/schema shape: the same `fact JOIN dim` with a different
        # WHERE on dim has a completely different selectivity, so the
        # fingerprint folds in every filter condition and scan identity
        # reachable in both subtrees
        def fingerprint(node: pn.PlanNode):
            out = []
            for n in pn.walk_plan(node):
                if isinstance(n, pn.FilterExec):
                    out.append(n.condition)
                elif isinstance(n, pn.ScanExec):
                    out.append((n.table_name, n.paths,
                                id(n.source) if n.source is not None
                                else None))
            return tuple(out)

        key = ("rtf_hist", reverse, p.left_keys, p.right_keys,
               tuple((f.name, f.dtype) for f in p.left.schema),
               tuple((f.name, f.dtype) for f in p.right.schema),
               fingerprint(p.left), fingerprint(p.right))
        try:
            hash(key)
            return key
        except TypeError:
            return None

    def _rtf_prepare(self, p: pn.JoinExec, src: HostBatch,
                     conf: "_RtfConf", reverse: bool,
                     est_src, est_tgt):
        """Build the runtime filter from the materialized SOURCE side
        (build side forward, probe side reverse) and push value conjuncts
        into the other subtree's annotated scans. Returns
        (rtf-or-None, rewritten target subtree)."""
        import time as _time

        import jax

        from .. import profiler
        from ..ops import hash as hashk
        from ..ops import runtime_filter as rtfk
        from ..plan import runtime_filters as rtfp

        src_node = p.left if reverse else p.right
        src_keys = p.left_keys if reverse else p.right_keys
        target_plan = p.right if reverse else p.left
        wanted_side = "build" if reverse else "probe"
        targets = tuple(t for t in p.runtime_filters
                        if t.side == wanted_side)

        hkey = self._rtf_history_key(p, reverse)
        if hkey is not None:
            past = _RTF_HISTORY.get(hkey)
            if past is not None and past < conf.min_selectivity:
                return None, target_plan  # observed useless: skip
        # a filter only pays when its source side is smaller than the
        # side it prunes: deriving one FROM a fact-sized side to prune a
        # dimension-sized side costs more than the join saves
        if est_src is not None and est_tgt is not None \
                and est_src >= est_tgt:
            return None, target_plan
        t0 = _time.perf_counter()
        try:
            comp = self._compiler(src, src_node.schema)
            compiled = [comp.compile(k) for k in src_keys]
        except HostFallback:
            return None, target_plan
        # eligible key ordinals: device-hashable physical types whose key
        # bits agree across sides WITHOUT dictionary unification (string
        # keys use per-side code spaces, so they cannot ride the filter).
        # The filter packs with the LEFT key's type — exactly the join's
        # own convention (_compile_join_keys labels both sides with
        # rex_type(lk)) — so source and filtered-side key bits agree.
        ordinals = tuple(
            i for i, (c, lk) in enumerate(zip(compiled, p.left_keys))
            if c.dictionary is None
            and getattr(rx.rex_type(lk), "physical_dtype", None)
            in hashk._KEY_BITS)
        if not ordinals:
            return None, target_plan
        num_bits = conf.max_bits
        key = self._op_key("rtf_build", reverse, p.left_keys,
                           p.right_keys, ordinals, num_bits,
                           tuple((f.name, f.dtype)
                                 for f in src_node.schema))

        def builder():
            bcomp = self._compiler(src, src_node.schema)
            bcompiled = [bcomp.compile(src_keys[i]) for i in ordinals]
            # LEFT key types, matching the join's key-bit convention
            ktypes = [rx.rex_type(p.left_keys[i]) for i in ordinals]

            def fn(scols, ssel):
                kcols = []
                usable = ssel
                for c, kt in zip(bcompiled, ktypes):
                    d, v = c.fn(scols)
                    kcols.append(Column(d, v, kt))
                    if v is not None:
                        usable = usable & v
                res = rtfk.build(kcols, ssel, num_bits)
                bounds = tuple(rtfk.column_bounds(c.data, usable)
                               for c in kcols)
                datas = tuple(c.data for c in kcols)
                return res, bounds, datas, usable

            return fn, None

        try:
            fn, _ = self._jitted(key, self._dict_objs(src), builder)
            res, bounds, datas, usable = fn(self._cols(src),
                                            src.device.sel)
        except HostFallback:
            return None, target_plan
        # one batched fetch for every host decision value; raw source key
        # values ride along only when the source batch is small enough
        # that exact in-list membership is worth extracting
        fetch_values = src.device.capacity <= (1 << 17)
        bundle = [res.n_build, res.ndv, bounds]
        if fetch_values:
            bundle.append((datas, usable))
        fetched = jax.device_get(tuple(bundle))
        n_build, ndv = int(fetched[0]), int(fetched[1])
        host_bounds = fetched[2]
        if n_build < conf.min_build_rows:
            return None, target_plan
        if n_build > 0:
            # a filter cannot prune much when the source's distinct keys
            # rival the filtered side's row count (the PK→PK shape)
            if est_tgt is not None and ndv >= conf.ndv_ratio * est_tgt:
                return None, target_plan
        values_by_ord: Dict[int, object] = {}
        if fetch_values:
            datas_np, usable_np = fetched[3]
            u = np.asarray(usable_np)
            for oi, i in enumerate(ordinals):
                vals = np.unique(np.asarray(datas_np[oi])[u])
                if vals.size <= conf.in_list_max:
                    values_by_ord[i] = vals
        if n_build == 0:
            # empty build: the device bounds are dtype-extreme sentinels
            # (min > max) which can overflow date literals — an explicit
            # always-false [1, 0] range prunes everything just the same
            bounds_by_ord = {i: (1, 0) for i in ordinals}
        else:
            bounds_by_ord = {i: host_bounds[oi]
                             for oi, i in enumerate(ordinals)}
        pushed = 0
        for t in targets:
            if t.key not in bounds_by_ord:
                continue
            scan = rtfp.find_scan_by_fid(target_plan, t.fid)
            if scan is None:
                continue  # target scan lives outside this plan fragment
            if scan.source is None and scan.format != "parquet":
                continue
            field = scan.schema[t.column]
            if not rtfp.supports_bounds(field.dtype):
                continue
            lo, hi = bounds_by_ord[t.key]
            try:
                conjs = rtfp.bounds_conjuncts(
                    t.column, field, int(lo), int(hi),
                    values_by_ord.get(t.key))
            except (OverflowError, ValueError):
                continue  # out-of-range literal (exotic date values)
            new_scan = dataclasses.replace(
                scan,
                runtime_predicates=scan.runtime_predicates + conjs)
            target_plan = _replace_node(target_plan, scan, new_scan)
            pushed += 1
            _record_metric("execution.runtime_filter.pushed_count", 1,
                           site="scan")
        build_s = _time.perf_counter() - t0
        _record_metric("execution.runtime_filter.built_count", 1)
        _record_metric("execution.runtime_filter.build_time", build_s)
        profiler.note_runtime_filter(built=1, pushed=pushed,
                                     build_ms=build_s * 1000.0)
        rtf = _Rtf(bits=res.bits, kmin=res.kmin, kmax=res.kmax,
                   ordinals=ordinals, num_bits=num_bits,
                   fids=tuple(t.fid for t in targets),
                   history_key=hkey, pushed=pushed, reverse=reverse)
        return rtf, target_plan

    def _rtf_finish(self, rtf: "_Rtf", before: int, after: int) -> None:
        """Post-join accounting: probe-mask pruning + adaptive history
        (scan-site pruning for this join's fids folds in, so an effective
        scan push does not read as a useless probe mask)."""
        from .. import profiler
        from .. import telemetry as tel

        pruned = before - after
        if pruned > 0:
            _record_metric("execution.runtime_filter.rows_pruned", pruned,
                           site="probe")
            profiler.note_runtime_filter(rows_pruned=pruned)
            if tel.current_collector() is not None:
                tel.note("RuntimeFilter", "probe mask",
                         rows_pruned=pruned, rows_in=before)
        # adaptive verdict: only SCAN-site pruning pays — fewer rows
        # decode/upload and every downstream kernel runs at the pruned
        # capacity. The in-join selection mask prunes rows the join
        # would reject anyway inside the SAME static-shape program, so a
        # filter whose value conjuncts never landed at a scan is pure
        # build overhead and stops rebuilding. Pushed-but-unmeasured
        # scans (parquet behind static predicates) record NO verdict —
        # the filter keeps building rather than being falsely condemned.
        ratio = 0.0
        measured = False
        for fid in rtf.fids:
            st = self._rtf_scan_stats.get(fid)
            if st is not None and st[0] > 0:
                measured = True
                ratio = max(ratio, (st[0] - st[1]) / st[0])
        if rtf.history_key is not None and (measured or rtf.pushed == 0):
            while len(_RTF_HISTORY) > 256:
                _RTF_HISTORY.pop(next(iter(_RTF_HISTORY)))
            _RTF_HISTORY[rtf.history_key] = ratio

    def _compile_join_keys(self, p: pn.JoinExec, left: HostBatch, right: HostBatch,
                           seed: int, rtf_sig=None):
        """Builder for the jitted build+probe phase of an equi-join."""
        # import OUTSIDE the traced fn: a first import during an active
        # jit trace would execute the module body inside the trace and
        # turn its module-level jnp constants (_KEY_MAX) into leaked
        # tracers, poisoning every later join trace in the process
        from ..ops import runtime_filter as rtfk

        def builder():
            lcomp = self._compiler(left, p.left.schema)
            rcomp = self._compiler(right, p.right.schema)
            pairs = []
            for lk, rk in zip(p.left_keys, p.right_keys):
                lc = lcomp.compile(lk)
                rc = rcomp.compile(rk)
                ktype = rx.rex_type(lk)
                luts = None
                if lc.dictionary is not None or rc.dictionary is not None:
                    merged, ra, rb = ai.unify_dictionaries(lc.dictionary,
                                                           rc.dictionary)
                    luts = (jnp.asarray(ra), jnp.asarray(rb))
                    ktype = dt.IntegerType()
                pairs.append((lc, rc, ktype, luts))

            def fn(lcols, lsel, rcols, rsel, *rtf_args):
                lkeys, rkeys = [], []
                for lc, rc, ktype, luts in pairs:
                    ld, lv = lc.fn(lcols)
                    rd, rv = rc.fn(rcols)
                    if luts is not None:
                        ld = luts[0][ld]
                        rd = luts[1][rd]
                    lkeys.append(Column(ld, lv, ktype))
                    rkeys.append(Column(rd, rv, ktype))
                rtf_before = rtf_after = jnp.int64(0)
                if rtf_sig is not None:
                    # runtime join filter: mask the filtered side's
                    # selection with the source side's bloom before the
                    # build/probe (fused into this program — the counts
                    # ride the existing batched host fetch, no extra
                    # sync). Forward masks the probe; reverse masks the
                    # build (a masked build row's key has no probe
                    # partner, so it could never match).
                    bits, kmin, kmax = rtf_args
                    if rtf_sig[2]:  # reverse
                        sub = [rkeys[i] for i in rtf_sig[0]]
                        masked = rtfk.apply(bits, kmin, kmax, sub, rsel)
                        rtf_before = jnp.sum(rsel.astype(jnp.int64))
                        rtf_after = jnp.sum(masked.astype(jnp.int64))
                        rsel = masked
                    else:
                        sub = [lkeys[i] for i in rtf_sig[0]]
                        masked = rtfk.apply(bits, kmin, kmax, sub, lsel)
                        rtf_before = jnp.sum(lsel.astype(jnp.int64))
                        rtf_after = jnp.sum(masked.astype(jnp.int64))
                        lsel = masked
                bt = joink.build_side(rkeys, rsel, seed)
                ambiguous = joink.hash_ambiguous(bt, rkeys) if not bt.exact \
                    else jnp.asarray(False)
                ranges = joink.probe_ranges(
                    bt, lkeys, lsel, build_key_cols=rkeys if not bt.exact else None)
                has_dup = joink.has_duplicate_build_keys(bt)
                inner_total = joink.join_output_count(ranges, lsel, "inner")
                return (bt.perm, bt.sorted_keys, bt.num_valid,
                        ranges.lo, ranges.cnt, ranges.usable,
                        has_dup, ambiguous, inner_total, bt.exact,
                        rtf_before, rtf_after)

            return fn, None
        return builder

    def _join(self, p: pn.JoinExec, left: HostBatch, right: HostBatch,
              rtf=None) -> HostBatch:
        spilled = self._try_partitioned_join(p, left, right)
        if spilled is not None:
            if rtf is not None:
                # the spill path applies its own exact per-partition
                # masks; the bloom goes unused, but the SCAN-site
                # pruning already happened — record its verdict so a
                # useless filter still shuts off adaptively
                self._rtf_finish(rtf, 0, 0)
            return spilled
        jt = p.join_type
        schema_key = (tuple((f.name, f.dtype) for f in p.left.schema),
                      tuple((f.name, f.dtype) for f in p.right.schema))
        dict_objs = self._dict_objs(left) + self._dict_objs(right)
        lcols, lsel = self._cols(left), left.device.sel
        rcols, rsel = self._cols(right), right.device.sel
        import jax

        rtf_sig = None if rtf is None else (rtf.ordinals, rtf.num_bits,
                                            rtf.reverse)
        rtf_args = () if rtf is None else (rtf.bits, rtf.kmin, rtf.kmax)
        for seed in range(4):
            key = self._op_key("join_phase", p.left_keys, p.right_keys, seed,
                               schema_key, rtf_sig)
            fn, _ = self._jitted(key, dict_objs,
                                 self._compile_join_keys(p, left, right, seed,
                                                         rtf_sig))
            (perm, sorted_keys, num_valid, lo, cnt, usable,
             has_dup_a, ambiguous, inner_total, exact,
             rtf_before, rtf_after) = fn(lcols, lsel, rcols, rsel, *rtf_args)
            # one batched fetch for every host decision scalar (each
            # separate blocking read is a device round trip)
            (has_dup_a, ambiguous, inner_total, exact, rtf_before,
             rtf_after) = jax.device_get(
                (has_dup_a, ambiguous, inner_total, exact, rtf_before,
                 rtf_after))
            if exact or not bool(ambiguous):
                break
        else:
            raise ExecutionError("could not build unambiguous hash join")
        if rtf is not None:
            self._rtf_finish(rtf, int(rtf_before), int(rtf_after))
        bt = joink.BuildTable(perm, sorted_keys, bool(exact), num_valid, seed)
        ranges = joink.MatchRanges(lo, cnt, usable)
        merged_dicts = dict(left.dicts)
        right_names = {}
        n_left = len(p.left.schema)
        # rename right columns to combined positions
        r_dev_cols = {}
        for i in range(len(p.right.schema)):
            r_dev_cols[_col_name(n_left + i)] = right.device.columns[_col_name(i)]
            if _col_name(i) in right.dicts:
                merged_dicts[_col_name(n_left + i)] = right.dicts[_col_name(i)]
        build_payload = DeviceBatch(r_dev_cols, right.device.sel)
        build_names = list(r_dev_cols.keys()) if jt not in ("semi", "anti") else []

        has_dup = bool(has_dup_a)
        # full outer always takes the expanding path (it appends unmatched
        # build rows, which the unique fast path cannot express)
        if not has_dup and p.residual is None and jt != "full":
            # exact/seed are baked into ufn's closure (the rebuilt
            # BuildTable), so they MUST ride the key: a repeat execution
            # whose hash build came out non-exact (or on a later seed)
            # would otherwise reuse a program compiled for the other mode
            ukey = self._op_key("join_unique", jt, len(build_names),
                                schema_key, bool(exact), seed)

            def ubuilder():
                def ufn(bt_arrays, ranges_arrays, ldev, bpayload):
                    b_perm, b_keys, b_nvalid = bt_arrays
                    bt_l = joink.BuildTable(perm=b_perm, sorted_keys=b_keys,
                                            exact=bool(exact),
                                            num_valid=b_nvalid, seed=seed)
                    rg = joink.MatchRanges(*ranges_arrays)
                    return joink.join_unique(bt_l, rg, ldev, bpayload, jt,
                                             build_names)
                return ufn, None

            ufn, _ = self._jitted(ukey, dict_objs, ubuilder)
            out_dev = ufn((perm, sorted_keys, num_valid), (lo, cnt, usable),
                          left.device, build_payload)
            out_dicts = merged_dicts if jt not in ("semi", "anti") else left.dicts
            return HostBatch(out_dev, out_dicts)
        return self._join_expand(p, left, right, bt, ranges, build_payload,
                                 build_names, merged_dicts,
                                 inner_total=int(inner_total))

    def _try_partitioned_join(self, p: pn.JoinExec, left: HostBatch,
                              right: HostBatch) -> Optional[HostBatch]:
        """Out-of-core partitioned equi-join (reference role: DataFusion's
        spilling hash join via memory pools + temp files, application.yaml
        runtime.* — SURVEY.md §5 long-context analogue).

        When the inputs exceed ``execution.join_spill_rows``, both sides
        hash-partition on the join keys into temp parquet files; each
        partition pair joins independently (equal keys land in the same
        partition, so inner/left/full/semi/anti are all partition-wise
        exact), bounding the join step's peak memory to one pair plus its
        expansion. NULL keys hash to one partition, preserving outer/anti
        semantics."""
        from ..config import get as config_get

        try:
            threshold = int(config_get("execution.join_spill_rows",
                                       8_000_000))
        except (TypeError, ValueError):
            threshold = 8_000_000
        if threshold <= 0 or not p.left_keys:
            return None
        if p.join_type not in ("inner", "left", "full", "semi", "anti"):
            return None
        if p.null_aware:
            return None
        if getattr(self, "_in_join_spill", False):
            return None  # partition pairs run the in-memory join
        if left.device.capacity + right.device.capacity <= threshold:
            # capacities bound live rows: the spill could never engage —
            # skip the per-join device round trip entirely
            return None
        import jax
        n_left, n_right = jax.device_get(  # ONE round trip, not two
            (jnp.sum(left.device.sel), jnp.sum(right.device.sel)))
        n_left, n_right = int(n_left), int(n_right)
        if n_left + n_right <= threshold:
            return None

        import tempfile

        import pyarrow as pa
        import pyarrow.compute as pc
        import pyarrow.parquet as pq

        nparts = max(2, min(64, (n_left + n_right) // max(threshold // 2, 1)
                            + 1))
        lt = ai.to_arrow(left).rename_columns(
            [f.name for f in p.left.schema])
        rt = ai.to_arrow(right).rename_columns(
            [f.name for f in p.right.schema])

        def key_indices(keys):
            """Simple column refs only; anything fancier declines the
            spill path (the planner rewrites casts/exprs above the scan)."""
            idx = []
            for k in keys:
                if isinstance(k, rx.BoundRef):
                    idx.append(k.index)
                else:
                    return None
            return idx

        lidx = key_indices(p.left_keys)
        ridx = key_indices(p.right_keys)
        if lidx is None or ridx is None:
            return None
        modes = [_spill_key_mode(lt.column(li).type, rt.column(ri).type)
                 for li, ri in zip(lidx, ridx)]
        lh = _spill_partition_ids(lt, lidx, modes, nparts)
        rh = _spill_partition_ids(rt, ridx, modes, nparts)
        if lh is None or rh is None:
            return None

        tmpdir = tempfile.mkdtemp(prefix="sail_join_spill_")
        self._last_join_spill_dir = tmpdir  # observable in tests
        _record_metric("execution.spill_count", 1, kind="join")
        from .. import profiler
        spill_bytes = 0
        sides = []
        for name, table, h in (("l", lt, lh), ("r", rt, rh)):
            paths = []
            for part in range(nparts):
                mask = h == part
                sub = table.filter(pa.array(mask))
                fp = os.path.join(tmpdir, f"{name}{part}.parquet")
                pq.write_table(sub, fp)
                spill_bytes += os.path.getsize(fp)
                paths.append(fp)
            sides.append(paths)
        profiler.note_spill_bytes(spill_bytes)
        del lt, rt

        from .. import telemetry as tel
        from ..io.prefetch import Prefetcher

        rtf_conf = self._rtf_conf()

        def _empty_side(path):
            return pq.ParquetFile(path).schema_arrow.empty_table()

        def load_pair(part):
            # producer thread: the next partition pair decodes from temp
            # parquet while this thread joins the current pair on device.
            # Parquet footer row counts short-circuit BEFORE any decode:
            # a pair one side of which cannot contribute output skips
            # entirely, and build-empty left/anti/full pairs decode the
            # surviving side alone.
            lp, rp = sides[0][part], sides[1][part]
            ln = pq.ParquetFile(lp).metadata.num_rows
            rn = pq.ParquetFile(rp).metadata.num_rows
            jt = p.join_type
            if jt in ("inner", "semi") and (ln == 0 or rn == 0):
                return None
            if ln == 0 and rn == 0:
                return None
            if jt in ("left", "anti") and ln == 0:
                return None  # output rows come from the left side only
            if jt in ("left", "anti", "full") and rn == 0:
                return pq.read_table(lp), _empty_side(rp)
            if jt == "full" and ln == 0:
                return _empty_side(lp), pq.read_table(rp)
            lsub, rsub = pq.read_table(lp), pq.read_table(rp)
            if jt in ("inner", "semi") and rtf_conf.enabled:
                # runtime-filter the decoded probe chunk against the
                # build partition's exact key set before upload
                lsub = _spill_probe_mask(lsub, lidx, rsub, ridx,
                                         rtf_conf.in_list_max)
            return lsub, rsub

        pf = Prefetcher(range(nparts), transform=load_pair,
                        depth=self._prefetch_depth(), kind="spill_join")
        outs = []
        self._in_join_spill = True
        try:
            with pf:
                for pair in pf:
                    if pair is None:
                        continue
                    lsub, rsub = pair
                    if p.join_type in ("inner", "semi") and \
                            (lsub.num_rows == 0 or rsub.num_rows == 0):
                        continue
                    lhb = _positional(ai.from_arrow(lsub))
                    rhb = _positional(ai.from_arrow(rsub))
                    sub_out = self._join(p, lhb, rhb)
                    outs.append(ai.to_arrow(sub_out))
        finally:
            # the prefetcher is already closed (producer joined) before
            # this cleanup runs, so no reader races the rmtree
            self._in_join_spill = False
            import shutil
            shutil.rmtree(tmpdir, ignore_errors=True)
        tel.note("SpillJoinPrefetch", f"{nparts} partition pairs",
                 **pf.stats.as_extra())
        if not outs:
            schema = p.schema
            empty = pa.table({f"c{i}": pa.array(
                [], type=ai.spec_type_to_arrow(f.dtype))
                for i, f in enumerate(schema)})
            return _positional(ai.from_arrow(empty))
        merged = pa.concat_tables(outs, promote_options="permissive")
        return _positional(ai.from_arrow(merged))

    def _try_external_sort(self, p: pn.SortExec,
                           child: HostBatch) -> Optional[HostBatch]:
        """Out-of-core external sort (reference role: DataFusion's spilling
        ExternalSorter via memory pools + temp files — SURVEY.md §5
        out-of-core).

        When the input's live rows exceed ``execution.sort_spill_rows``,
        the wide rows spill to memory-mapped Arrow IPC runs while the
        global permutation is computed on the host from the key columns
        alone (a small fraction of the row width). The output gathers
        straight from the memory maps, so the O(n) sort workspace — the
        permuted column copies a device lexsort would materialize — never
        touches device HBM. Spark ordering semantics: nulls_first/last per
        key, NaN sorts greater than any non-null value (after +Inf)."""
        from ..config import get as config_get

        try:
            threshold = int(config_get("execution.sort_spill_rows",
                                       8_000_000))
        except (TypeError, ValueError):
            threshold = 8_000_000
        if threshold <= 0 or not p.keys:
            return None
        for k in p.keys:
            if not isinstance(k.expr, rx.BoundRef):
                return None  # expression keys stay on the in-memory path
        if child.device.capacity <= threshold:
            # capacity bounds live rows: the spill could never engage, so
            # skip the device round trip the exact count would cost
            return None
        import jax
        n = int(jax.device_get(jnp.sum(child.device.sel)))
        if n <= threshold:
            return None

        import shutil
        import tempfile

        import pandas as pd
        import pyarrow as pa
        import pyarrow.compute as pc
        import pyarrow.ipc as ipc

        table = ai.to_arrow(child)

        # -- sort-key frame (host memory; declines on exotic key types) --
        frame: Dict[str, object] = {}
        by: List[str] = []
        asc: List[bool] = []
        for i, k in enumerate(p.keys):
            col = table.column(k.expr.index).combine_chunks()
            if pa.types.is_dictionary(col.type):
                col = col.cast(col.type.value_type)
            t = col.type
            if not (pa.types.is_integer(t) or pa.types.is_floating(t)
                    or pa.types.is_boolean(t) or pa.types.is_string(t)
                    or pa.types.is_large_string(t) or pa.types.is_binary(t)
                    or pa.types.is_decimal(t) or pa.types.is_temporal(t)):
                return None
            null_mask = col.is_null().to_numpy(zero_copy_only=False)
            # nulls_first/last is independent of the key direction: the
            # null rank column always sorts ascending. Unset → Spark
            # default (ASC: NULLS FIRST, DESC: NULLS LAST).
            nulls_first = (k.nulls_first if k.nulls_first is not None
                           else k.ascending)
            frame[f"n{i}"] = ~null_mask if nulls_first else null_mask
            by.append(f"n{i}")
            asc.append(True)
            if pa.types.is_floating(t):
                # NaN (non-null) outranks every value including +Inf; the
                # rank column isolates it so the filled 0.0 can't leak in
                vals = col.to_numpy(zero_copy_only=False).astype(
                    np.float64, copy=True)
                nan_mask = np.isnan(vals) & ~null_mask
                frame[f"f{i}"] = nan_mask
                by.append(f"f{i}")
                asc.append(k.ascending)
                vals[np.isnan(vals)] = 0.0
                frame[f"k{i}"] = vals
            else:
                if null_mask.any():
                    non_null = col.drop_null()
                    if len(non_null) == 0:
                        continue  # all null: the null rank decides alone
                    col = pc.fill_null(col, non_null[0])
                frame[f"k{i}"] = col.to_pandas()
            by.append(f"k{i}")
            asc.append(k.ascending)

        from .. import telemetry as tel
        from ..io.prefetch import Prefetcher

        tmpdir = tempfile.mkdtemp(prefix="sail_sort_spill_")
        self._last_sort_spill_dir = tmpdir  # observable in tests
        _record_metric("execution.spill_count", 1, kind="sort")
        try:
            # -- spill the wide rows to memory-mappable runs, in the
            # background: the run data is already on disk once written, so
            # the queue carries only paths and the producer never needs to
            # stall — pass the full run count as depth (0 still disables)
            run_rows = max(1, threshold // 2)
            starts = list(enumerate(range(0, n, run_rows)))

            def write_run(i_start):
                i, start = i_start
                fp = os.path.join(tmpdir, f"run{i}.arrow")
                with pa.OSFile(fp, "wb") as f, \
                        ipc.new_file(f, table.schema) as writer:
                    writer.write_table(table.slice(start, run_rows))
                return fp

            depth = self._prefetch_depth()
            with Prefetcher(starts, transform=write_run,
                            depth=0 if depth <= 0 else len(starts),
                            kind="spill_sort") as pf:
                # the global key permutation computes WHILE runs spill
                perm = pd.DataFrame(frame).sort_values(
                    by, ascending=asc, kind="stable").index.to_numpy()
                if p.limit is not None:
                    perm = perm[:p.limit]
                paths = list(pf)
            del table
            from .. import profiler
            profiler.note_spill_bytes(
                sum(os.path.getsize(fp) for fp in paths))
            tel.note("SpillSortPrefetch", f"{len(paths)} runs",
                     **pf.stats.as_extra())

            # -- gather output rows straight off the memory maps --
            runs = [ipc.open_file(pa.memory_map(fp, "r")).read_all()
                    for fp in paths]
            out = pa.concat_tables(runs).take(
                pa.array(perm, type=pa.int64()))
            out = out.combine_chunks()  # own the buffers before cleanup
            return _positional(ai.from_arrow(out))
        finally:
            shutil.rmtree(tmpdir, ignore_errors=True)

    def _join_expand(self, p: pn.JoinExec, left: HostBatch, right: HostBatch,
                     bt, ranges, build_payload, build_names, merged_dicts,
                     inner_total=None) -> HostBatch:
        jt = p.join_type
        n_left = len(p.left.schema)
        total = int(joink.join_output_count(ranges, left.device.sel, "inner")) \
            if inner_total is None else inner_total
        cap = bucket_capacity(max(total, 1),
                              key=("join-expand", pst.node_fingerprint(p)))
        res = joink.join_expand(bt, ranges, left.device, build_payload,
                                "inner", list(build_payload.columns.keys()),
                                cap)
        exp_batch, pi, is_match = res.batch, res.probe_index, res.is_match
        bix = res.build_index
        ok = exp_batch.sel
        if p.residual is not None:
            comb_schema = tuple(p.left.schema) + tuple(p.right.schema)
            comp = ExprCompiler([f.dtype for f in comb_schema],
                                {i: merged_dicts[_col_name(i)]
                                 for i in range(len(comb_schema))
                                 if _col_name(i) in merged_dicts},
                                self._subquery_cache)
            c = comp.compile(p.residual)
            cols = [(exp_batch.columns[_col_name(i)].data,
                     exp_batch.columns[_col_name(i)].validity)
                    for i in range(len(comb_schema))]
            rdat, rval = c.fn(cols)
            res_ok = rdat.astype(jnp.bool_)
            if rval is not None:
                res_ok = res_ok & rval
            ok = ok & res_ok
        if jt == "inner":
            return HostBatch(exp_batch.with_sel(ok), merged_dicts)
        # probe rows with >= 1 surviving match
        probe_cap = left.device.capacity
        matched_probe = jnp.zeros(probe_cap, dtype=jnp.bool_).at[pi].max(
            ok, mode="drop")
        if jt == "semi":
            return HostBatch(left.device.with_sel(left.device.sel & matched_probe),
                             left.dicts)
        if jt == "anti":
            return HostBatch(left.device.with_sel(left.device.sel & ~matched_probe),
                             left.dicts)
        if jt in ("left", "full"):
            # surviving inner rows + unmatched probe rows with null build cols
            unmatched = left.device.sel & ~matched_probe
            out_cap = cap + probe_cap
            cols = {}
            for i in range(n_left):
                key = _col_name(i)
                ec = exp_batch.columns[key]
                lc = left.device.columns[key]
                data = jnp.concatenate([ec.data, lc.data])
                validity = None
                if ec.validity is not None or lc.validity is not None:
                    ev = ec.validity if ec.validity is not None else \
                        jnp.ones(cap, dtype=jnp.bool_)
                    lv = lc.validity if lc.validity is not None else \
                        jnp.ones(probe_cap, dtype=jnp.bool_)
                    validity = jnp.concatenate([ev, lv])
                cols[key] = Column(data, validity, ec.dtype)
            for key in build_payload.columns:
                ec = exp_batch.columns[key]
                pad_v = jnp.zeros(probe_cap, dtype=jnp.bool_)
                ev = ec.validity if ec.validity is not None else \
                    jnp.ones(cap, dtype=jnp.bool_)
                cols[key] = Column(
                    jnp.concatenate([ec.data, jnp.zeros(probe_cap, dtype=ec.data.dtype)]),
                    jnp.concatenate([ev, pad_v]), ec.dtype)
            sel = jnp.concatenate([ok, unmatched])
            out = DeviceBatch(cols, sel)
            if jt == "full":
                out = self._append_unmatched_build(
                    out, p, bt, ranges, left, build_payload, ok, bix,
                    has_residual=p.residual is not None)
            return HostBatch(out, merged_dicts)
        raise ExecutionError(f"join type {jt!r} not implemented")

    def _append_unmatched_build(self, out: DeviceBatch, p, bt, ranges, left,
                                build_payload, ok, bix,
                                has_residual=False) -> DeviceBatch:
        if has_residual:
            # A build row counts as matched only if at least one of its
            # expanded rows survived the residual filter; scatter the
            # surviving flags back to build positions.
            bcap0 = build_payload.sel.shape[0]
            matched_build = jnp.zeros(bcap0, dtype=jnp.bool_).at[bix].max(
                ok, mode="drop")
        else:
            matched_build = joink.build_matched_mask(bt, ranges, left.device.sel)
        unmatched = build_payload.sel & ~matched_build
        n_left = len(p.left.schema)
        bcap = matched_build.shape[0]
        cols = {}
        for i in range(n_left):
            key = _col_name(i)
            c = out.columns[key]
            cols[key] = Column(
                jnp.concatenate([c.data, jnp.zeros(bcap, dtype=c.data.dtype)]),
                jnp.concatenate([c.validity if c.validity is not None
                                 else jnp.ones(c.data.shape[0], dtype=jnp.bool_),
                                 jnp.zeros(bcap, dtype=jnp.bool_)]), c.dtype)
        for key, c in build_payload.columns.items():
            oc = out.columns[key]
            v = c.validity if c.validity is not None else jnp.ones(bcap, dtype=jnp.bool_)
            cols[key] = Column(
                jnp.concatenate([oc.data, c.data]),
                jnp.concatenate([oc.validity if oc.validity is not None
                                 else jnp.ones(oc.data.shape[0], dtype=jnp.bool_), v]),
                c.dtype)
        sel = jnp.concatenate([out.sel, unmatched])
        return DeviceBatch(cols, sel)

    def _cross_join(self, p: pn.JoinExec, left: HostBatch, right: HostBatch) -> HostBatch:
        import jax
        n_left_rows, n_right_rows = (
            int(x) for x in jax.device_get((left.device.num_rows(),
                                            right.device.num_rows())))
        total = n_left_rows * n_right_rows
        cap = bucket_capacity(max(total, 1),
                              key=("cross-join", pst.node_fingerprint(p)))
        lcomp = sortk.compact(left.device)
        rcomp_d = sortk.compact(right.device)
        idx = jnp.arange(cap, dtype=jnp.int32)
        li = jnp.clip(idx // max(n_right_rows, 1), 0, left.device.capacity - 1)
        ri = jnp.clip(idx % max(n_right_rows, 1), 0, right.device.capacity - 1)
        sel = idx < total
        cols = {}
        n_left = len(p.left.schema)
        for i in range(n_left):
            c = lcomp.columns[_col_name(i)]
            cols[_col_name(i)] = Column(c.data[li],
                                        None if c.validity is None else c.validity[li],
                                        c.dtype)
        dicts = dict(left.dicts)
        for i in range(len(p.right.schema)):
            c = rcomp_d.columns[_col_name(i)]
            cols[_col_name(n_left + i)] = Column(
                c.data[ri], None if c.validity is None else c.validity[ri], c.dtype)
            if _col_name(i) in right.dicts:
                dicts[_col_name(n_left + i)] = right.dicts[_col_name(i)]
        return HostBatch(DeviceBatch(cols, sel), dicts)

    # ------------------------------------------------------------------
    def _exec_WindowExec(self, p: pn.WindowExec) -> HostBatch:
        from ..ops import window as wink
        from ..ops.sort import order_bits
        child = self.run(p.input)
        dev = child.device
        in_schema = p.input.schema

        def builder():
            # precompute rank LUTs for dictionary-encoded order keys
            order_luts: Dict[int, jnp.ndarray] = {}
            for s in p.windows:
                for k in s.order_keys:
                    i = k.expr.index
                    name = _col_name(i)
                    if name in child.dicts and i not in order_luts:
                        order_luts[i] = jnp.asarray(
                            ai.dictionary_ranks(child.dicts[name]))
            # translate string lag/lead defaults to dictionary codes,
            # extending the dictionary when the default is unseen
            lag_defaults: Dict[int, object] = {}
            extended_dicts: Dict[int, pa.Array] = {}
            for j, s in enumerate(p.windows):
                opts = dict(s.options)
                default = opts.get("default")
                if s.function in ("lag", "lead") and isinstance(default, str):
                    src = _col_name(s.arg)
                    if src not in child.dicts:
                        raise ExecutionError(
                            f"{s.function}() string default over a "
                            f"non-string column")
                    vals = child.dicts[src].cast(pa.string()).to_pylist()
                    if default in vals:
                        lag_defaults[j] = vals.index(default)
                    else:
                        extended_dicts[j] = pa.array(vals + [default])
                        lag_defaults[j] = len(vals)
                elif s.function in ("lag", "lead"):
                    lag_defaults[j] = default

            def fn(cols, sel):
                ctx_cache = {}
                outs = []
                for j, s in enumerate(p.windows):
                    pkey = tuple(s.partition_indices)
                    okey = tuple((k.expr.index, k.ascending, k.nulls_first)
                                 for k in s.order_keys)
                    ck = (pkey, okey)
                    if ck not in ctx_cache:
                        part_cols = [Column(cols[i][0], cols[i][1],
                                            in_schema[i].dtype)
                                     for i in s.partition_indices]
                        order_keys = []
                        for k in s.order_keys:
                            i = k.expr.index
                            d, v = cols[i]
                            kdt = in_schema[i].dtype
                            if i in order_luts:
                                d = order_luts[i][d]
                                kdt = dt.IntegerType()
                            order_keys.append((d, v, kdt, k.ascending,
                                               k.nulls_first))
                        ctx = wink.build_window_context(part_cols, order_keys,
                                                        sel)
                        okbits = [(order_bits(d[ctx.perm], kdt, asc),
                                   None if v is None else v[ctx.perm])
                                  for (d, v, kdt, asc, nf) in order_keys]
                        ctx_cache[ck] = (ctx, okbits)
                    ctx, okbits = ctx_cache[ck]
                    opts = dict(s.options)
                    fnname = s.function
                    if fnname == "row_number":
                        outs.append((wink.row_number(ctx), None))
                    elif fnname == "rank":
                        outs.append((wink.rank(ctx, okbits), None))
                    elif fnname == "dense_rank":
                        outs.append((wink.dense_rank(ctx, okbits), None))
                    elif fnname == "percent_rank":
                        outs.append((wink.percent_rank(ctx, okbits), None))
                    elif fnname == "cume_dist":
                        outs.append((wink.cume_dist(ctx, okbits), None))
                    elif fnname == "ntile":
                        outs.append((wink.ntile(ctx, int(opts["n"])), None))
                    elif fnname in ("lag", "lead"):
                        arg = Column(cols[s.arg][0], cols[s.arg][1],
                                     in_schema[s.arg].dtype)
                        d, v = wink.shift(ctx, arg, int(opts["offset"]),
                                          lag_defaults.get(j))
                        outs.append((d, v))
                    elif fnname == "nth_value":
                        arg = Column(cols[s.arg][0], cols[s.arg][1],
                                     in_schema[s.arg].dtype)
                        peer = None
                        if s.frame_type == "range" or s.frame_lower is None:
                            peer = wink.peer_group_end(ctx, okbits)
                        d, v = wink.nth(ctx, arg, int(opts["n"]), peer)
                        outs.append((d, v))
                    else:
                        fnk = s.function
                        arg = None
                        inv_lut = None
                        if s.arg is not None:
                            adata, avalid = cols[s.arg]
                            adt = in_schema[s.arg].dtype
                            name = _col_name(s.arg)
                            if name in child.dicts and fnk in ("min", "max"):
                                # compare string codes in rank order, then
                                # map the winning rank back to a code
                                ranks = ai.dictionary_ranks(child.dicts[name])
                                inv = np.empty_like(ranks)
                                inv[ranks] = np.arange(len(ranks), dtype=ranks.dtype)
                                adata = jnp.asarray(ranks)[adata]
                                adt = dt.IntegerType()
                                inv_lut = jnp.asarray(inv)
                            arg = Column(adata, avalid, adt)
                        peer = None
                        if s.frame_type == "range":
                            if s.frame_lower is None and s.frame_upper == 0:
                                peer = wink.peer_group_end(ctx, okbits)
                            elif not (s.frame_lower is None and s.frame_upper is None):
                                raise ExecutionError(
                                    "RANGE frames with value offsets are not "
                                    "supported yet")
                        d, v = wink.framed_agg(ctx, arg, fnk,
                                               s.frame_lower, s.frame_upper,
                                               peer)
                        if inv_lut is not None:
                            d = inv_lut[jnp.clip(d, 0, inv_lut.shape[0] - 1)]
                        if fnk == "avg" and s.arg is not None and \
                                isinstance(in_schema[s.arg].dtype, dt.DecimalType):
                            d = d / (10.0 ** in_schema[s.arg].dtype.scale)
                        outs.append((d, v))
                return tuple(outs)

            return fn, extended_dicts

        key = self._op_key("window", p.windows,
                           tuple((f.name, f.dtype) for f in in_schema))
        fn, extended_dicts = self._jitted(key, self._dict_objs(child), builder)
        results = fn(self._cols(child), dev.sel)
        cols = dict(dev.columns)
        out_dicts = dict(child.dicts)
        n_in = len(in_schema)
        for j, (s, (d, v)) in enumerate(zip(p.windows, results)):
            keyn = _col_name(n_in + j)
            jdt = physical_jnp_dtype(s.out_dtype)
            if d.dtype != jnp.dtype(jdt):
                d = d.astype(jdt)
            cols[keyn] = Column(d, v, s.out_dtype)
            if s.arg is not None and s.function in ("lag", "lead", "min",
                                                    "max", "first", "last",
                                                    "nth_value"):
                src = _col_name(s.arg)
                if extended_dicts and j in extended_dicts:
                    out_dicts[keyn] = extended_dicts[j]
                elif src in child.dicts:
                    out_dicts[keyn] = child.dicts[src]
        return HostBatch(DeviceBatch(cols, dev.sel), out_dicts)

    def _exec_UnionExec(self, p: pn.UnionExec) -> HostBatch:
        parts = [self.run(c) for c in p.inputs]
        ncols = len(p.schema)
        total_cap = sum(b.device.capacity for b in parts)
        cols = {}
        dicts = {}
        for i in range(ncols):
            key = _col_name(i)
            f = p.schema[i]
            str_col = any(key in b.dicts for b in parts)
            if str_col and isinstance(f.dtype, (dt.ArrayType, dt.MapType,
                                                dt.StructType)):
                # complex dictionaries: concatenate with offset remapping
                import pyarrow as pa
                offset = 0
                datas = []
                chunks = []
                at = ai.spec_type_to_arrow(f.dtype)
                for b in parts:
                    d_b = b.dicts[key]
                    chunks.append(d_b)
                    datas.append(b.device.columns[key].data + offset)
                    offset += len(d_b)
                # unify branch nullability (e.g. struct<x not null> vs
                # struct<x>) to the union output type before concatenating
                dicts[key] = pa.concat_arrays(
                    [(c.combine_chunks() if isinstance(c, pa.ChunkedArray)
                      else c).cast(at) for c in chunks])
            elif str_col:
                from ..plan.compiler import _merge_dicts
                merged, remaps = _merge_dicts([b.dicts[key] for b in parts])
                datas = [jnp.asarray(rm)[b.device.columns[key].data]
                         for rm, b in zip(remaps, parts)]
                dicts[key] = merged
            else:
                jdt = physical_jnp_dtype(f.dtype)
                datas = [b.device.columns[key].data.astype(jdt) for b in parts]
            data = jnp.concatenate(datas)
            validities = []
            has_v = any(b.device.columns[key].validity is not None for b in parts)
            if has_v:
                for b in parts:
                    v = b.device.columns[key].validity
                    validities.append(v if v is not None else
                                      jnp.ones(b.device.capacity, dtype=jnp.bool_))
                validity = jnp.concatenate(validities)
            else:
                validity = None
            cols[key] = Column(data, validity, f.dtype)
        sel = jnp.concatenate([b.device.sel for b in parts])
        return HostBatch(DeviceBatch(cols, sel), dicts)


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _spill_key_mode(lt_type: "pa.DataType", rt_type: "pa.DataType") -> str:
    """Hash family for one spill-join key PAIR, agreed by both sides:
    integral keys hash exactly as int64 (float64 canonicalization would
    collapse int64 keys above 2^53 — adjacent keys share a double — and
    skew partition sizes); the float64 path is reserved for float inputs;
    everything else hashes its canonical string form."""
    def one(t):
        if pa.types.is_floating(t):
            return "float"
        if pa.types.is_integer(t) or pa.types.is_boolean(t):
            return "int"
        return "str"

    ml, mr = one(lt_type), one(rt_type)
    if "float" in (ml, mr):
        return "float"
    if ml == mr == "int":
        return "int"
    return "str"


# all NULL keys land in one partition regardless of hash family
_SPILL_NULL_HASH = np.uint64(0x9E3779B97F4A7C15)


def _spill_partition_ids(table: "pa.Table", idx, modes, nparts: int):
    """Partition ids from key VALUES (stable across both sides —
    dictionary codes are not). None → decline the spill path."""
    import pandas as pd
    import pyarrow.compute as pc

    h = None
    for i, mode in zip(idx, modes):
        col = table.column(i).combine_chunks()
        null_mask = None
        if mode == "float":
            # canonical float64: a NULLABLE int side otherwise hashes as
            # float-with-NaN while the other side hashes as int — same
            # value, different partition. Spark join equality:
            # -0.0 == 0.0 (+ 0.0 normalizes the sign) and NaN == NaN
            # (one canonical payload) — mirrors ops/hash.py
            # _normalize_float.
            vals = col.to_numpy(zero_copy_only=False) \
                .astype(np.float64) + 0.0
            vals[np.isnan(vals)] = np.nan
        elif mode == "int":
            # promote to the common integer width; exact above 2^53
            null_mask = col.is_null().to_numpy(zero_copy_only=False)
            vals = pc.fill_null(col.cast(pa.int64(), safe=False), 0) \
                .to_numpy(zero_copy_only=False)
        else:
            # strings/dates/decimals: canonical string form; anything
            # uncastable declines the spill path
            try:
                vals = pc.cast(col, pa.string()).to_numpy(
                    zero_copy_only=False)
            except Exception:  # noqa: BLE001
                return None
        part = pd.util.hash_array(vals, categorize=False) \
            .astype(np.uint64)
        if null_mask is not None and null_mask.any():
            part[null_mask] = _SPILL_NULL_HASH
        h = part if h is None else (h * np.uint64(31) + part)
    return (h % np.uint64(nparts)).astype(np.int64)


def _rtf_est_rows(p: pn.PlanNode) -> float:
    """Runtime-filter direction estimate: join_reorder's cardinality
    model, except cross joins count as the cartesian PRODUCT (GOO's max
    is fine for ordering decisions but makes a 250k-row cross product
    look like its 2.5k-row side, steering the filter the wrong way).
    Observed cardinalities from completed cluster stages (the adaptive
    stats-feedback loop) take precedence over the static model."""
    from ..plan import join_reorder as jr

    obs = jr.observed_rows(p)
    if obs is not None:
        return obs
    if isinstance(p, pn.JoinExec):
        lr, rr = _rtf_est_rows(p.left), _rtf_est_rows(p.right)
        if p.join_type in ("semi", "anti"):
            return lr * 0.5
        if p.join_type == "cross" or not p.left_keys:
            return lr * rr
        return max(lr, rr)
    if isinstance(p, pn.FilterExec):
        return _rtf_est_rows(p.input) * jr._conjunct_selectivity(
            p.condition)
    if isinstance(p, pn.AggregateExec):
        return max(_rtf_est_rows(p.input) * 0.1, 1.0)
    if isinstance(p, pn.UnionExec):
        return sum(_rtf_est_rows(c) for c in p.inputs)
    if isinstance(p, pn.ScanExec):
        return jr._scan_rows(p)
    child = getattr(p, "input", None)
    if isinstance(child, pn.PlanNode):
        return _rtf_est_rows(child)
    return jr._DEFAULT_ROWS


def _spill_probe_mask(lsub: "pa.Table", lidx, rsub: "pa.Table", ridx,
                      cap: int) -> "pa.Table":
    """Spill-join runtime filter: exact build-partition key membership
    applied to the probe partition before upload (inner/semi only).
    Multi-key joins intersect per-column membership — a superset of the
    true match set, so the mask is sound; NULL keys drop (they cannot
    equi-match). Skips columns whose distinct build keys exceed ``cap``
    and float keys (NaN set semantics differ from Spark's NaN ≡ NaN)."""
    import pyarrow.compute as pc

    mask = None
    for li, ri in zip(lidx, ridx):
        rcol = rsub.column(ri)
        t = rcol.type
        if not (pa.types.is_integer(t) or pa.types.is_boolean(t)
                or pa.types.is_string(t) or pa.types.is_large_string(t)
                or pa.types.is_date(t) or pa.types.is_decimal(t)):
            continue
        try:
            vals = pc.unique(rcol.combine_chunks())
            if len(vals) > cap:
                continue
            m = pc.is_in(lsub.column(li), value_set=vals)
        except (pa.ArrowInvalid, pa.ArrowNotImplementedError,
                pa.ArrowTypeError):
            continue
        mask = m if mask is None else pc.and_kleene(mask, m)
    if mask is None:
        return lsub
    before = lsub.num_rows
    out = lsub.filter(mask)  # null-mask rows drop with the non-members
    pruned = before - out.num_rows
    if pruned > 0:
        _record_metric("execution.runtime_filter.rows_pruned", pruned,
                       site="spill")
        _record_metric("execution.runtime_filter.pushed_count", 1,
                       site="spill")
    return out


def _apply_runtime_predicates(table: pa.Table, preds, schema):
    """Host-side application of runtime join-filter conjuncts to an
    in-memory Arrow table (order-preserving, so downstream results are
    bit-identical with filtering off). Returns (table, (before, after))
    or (table, None) when the conjuncts fail to convert."""
    from ..io.formats import rex_predicates_to_arrow

    expr = rex_predicates_to_arrow(preds, schema)
    if expr is None:
        return table, None
    before = table.num_rows
    try:
        table = table.filter(expr)
    except (pa.ArrowInvalid, pa.ArrowNotImplementedError, TypeError):
        return table, None  # advisory: an unapplied filter is still sound
    return table, (before, table.num_rows)


def _drop_mem_scan_entry(table: pa.Table) -> None:
    """Evict one in-memory table's fragment-cache entries (chunk
    pipelines would otherwise pin every decoded chunk in HBM)."""
    from .result_cache import FRAGMENT_CACHE
    FRAGMENT_CACHE.drop_mem(id(table))


def _positional(hb: HostBatch) -> HostBatch:
    """Rename columns to positional keys c0..cn."""
    dev = hb.device
    cols = {}
    dicts = {}
    for i, (name, col) in enumerate(dev.columns.items()):
        cols[_col_name(i)] = col
        if name in hb.dicts:
            dicts[_col_name(i)] = hb.dicts[name]
    return HostBatch(DeviceBatch(cols, dev.sel), dicts)


def _scan_cap_key(p: pn.ScanExec):
    """Pinned-bucket identity of one scan's decoded batch: structural
    (name + shape of the projected output), never data identity — so a
    continuous stream scan keeps ONE pin across every pushed interval
    even though each interval attaches a fresh memory table."""
    return ("scan-decode", p.table_name, p.format, p.projection,
            tuple((f.name, f.dtype) for f in p.out_schema))


def _shrink(dev: DeviceBatch, n_live: int, bucket_key=None) -> DeviceBatch:
    """Slice a front-compacted batch down to a smaller padded capacity."""
    cap = bucket_capacity(max(n_live, 1), key=bucket_key)
    if cap >= dev.capacity:
        return dev
    cols = {n: Column(c.data[:cap],
                      None if c.validity is None else c.validity[:cap], c.dtype)
            for n, c in dev.columns.items()}
    return DeviceBatch(cols, dev.sel[:cap])


def _flip_residual(r: Optional[rx.Rex], n_left: int, n_right: int) -> Optional[rx.Rex]:
    if r is None:
        return None

    def flip(x: rx.Rex) -> rx.Rex:
        if isinstance(x, rx.BoundRef):
            if x.index < n_left:
                return dataclasses.replace(x, index=x.index + n_right)
            return dataclasses.replace(x, index=x.index - n_left)
        if isinstance(x, rx.RCall):
            return dataclasses.replace(x, args=tuple(flip(a) for a in x.args))
        if isinstance(x, rx.RCast):
            return dataclasses.replace(x, child=flip(x.child))
        if isinstance(x, rx.RCase):
            return dataclasses.replace(
                x, branches=tuple((flip(c), flip(v)) for c, v in x.branches),
                else_value=None if x.else_value is None else flip(x.else_value))
        return x

    return flip(r)


def _reorder_right(hb: HostBatch, n_right: int, n_left: int) -> HostBatch:
    """After executing a flipped right join (as left join with sides swapped),
    restore the original column order: right-output cols [0..n_right) move
    after the left cols."""
    dev = hb.device
    cols = {}
    dicts = {}
    for i in range(n_left):
        src = _col_name(n_right + i)
        cols[_col_name(i)] = dev.columns[src]
        if src in hb.dicts:
            dicts[_col_name(i)] = hb.dicts[src]
    for i in range(n_right):
        src = _col_name(i)
        cols[_col_name(n_left + i)] = dev.columns[src]
        if src in hb.dicts:
            dicts[_col_name(n_left + i)] = hb.dicts[src]
    return HostBatch(DeviceBatch(cols, dev.sel), dicts)


def _node_rex(p: pn.PlanNode):
    if isinstance(p, pn.FilterExec):
        yield p.condition
    elif isinstance(p, pn.ProjectExec):
        for _, e in p.exprs:
            yield e
    elif isinstance(p, pn.JoinExec):
        yield from p.left_keys
        yield from p.right_keys
        if p.residual is not None:
            yield p.residual
    elif isinstance(p, pn.SortExec):
        for k in p.keys:
            yield k.expr


def _walk_part_rex(part):
    """Yield Rex nodes reachable inside an _op_key part (tuples of exprs,
    SortKeys, bare Rex, …)."""
    if isinstance(part, rx.Rex):
        yield part
    elif isinstance(part, pn.SortKey):
        yield part.expr
    elif isinstance(part, tuple):
        for item in part:
            yield from _walk_part_rex(item)
