"""Worker managers: how the driver acquires workers.

Reference role: crates/sail-execution/src/worker_manager/ — the
``WorkerManager`` trait with LocalWorkerManager (in-process) and
KubernetesWorkerManager (pods via the kube API, owner references, env-
injected identity; kubernetes.rs:34-289). Redesigned for this runtime:

- ThreadWorkerManager: actors in the driver process (the local-cluster
  test vehicle).
- ProcessWorkerManager: real OS processes running
  ``python -m sail_tpu worker`` — separate heaps/GILs, killable.
- KubernetesWorkerManager: worker pods created through a minimal REST
  client against the kube apiserver (injectable transport; no kubernetes
  client library in the image). Unit-tested against a fake API.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import uuid
from typing import Dict, List, Optional


class WorkerManager:
    """Start/stop workers for a driver at ``driver_addr``."""

    def start_worker(self, worker_id: str) -> object:
        raise NotImplementedError

    def stop_worker(self, handle: object):
        raise NotImplementedError

    def stop_all(self):
        raise NotImplementedError


class ThreadWorkerManager(WorkerManager):
    def __init__(self, driver_addr: str, task_slots: int = 2):
        self.driver_addr = driver_addr
        self.task_slots = task_slots
        self._workers: List = []

    def start_worker(self, worker_id: Optional[str] = None):
        from .cluster import WorkerActor
        wid = worker_id or f"worker-{uuid.uuid4().hex[:8]}"
        w = WorkerActor(wid, self.driver_addr, self.task_slots)
        w.start(wid)
        self._workers.append(w)
        return w

    def stop_worker(self, handle):
        handle.stop()
        if handle in self._workers:
            self._workers.remove(handle)

    def owns(self, worker_id: str) -> bool:
        """True when this manager started (and can stop) the worker —
        reaping a worker it can't stop would leave a zombie actor."""
        return any(getattr(w, "worker_id", None) == worker_id
                   for w in self._workers)

    def stop_worker_id(self, worker_id: str):
        """Stop by registered worker id (driver-side idle reaping)."""
        for w in list(self._workers):
            if getattr(w, "worker_id", None) == worker_id:
                self.stop_worker(w)
                return

    def stop_all(self):
        for w in list(self._workers):
            self.stop_worker(w)


class ProcessWorkerManager(WorkerManager):
    """Spawn workers as real OS processes (own heap, own GIL).

    Spawned workers default to the CPU jax backend: a single host TPU chip
    cannot be shared across processes; set SAIL_WORKER_PLATFORM to
    override.
    """

    def __init__(self, driver_addr: str, task_slots: int = 2,
                 host: str = "127.0.0.1", env: Optional[Dict] = None):
        self.driver_addr = driver_addr
        self.task_slots = task_slots
        self.host = host
        self.env = env
        self._procs: List[subprocess.Popen] = []

    def start_worker(self, worker_id: Optional[str] = None
                     ) -> subprocess.Popen:
        wid = worker_id or f"worker-{uuid.uuid4().hex[:8]}"
        env = dict(os.environ if self.env is None else self.env)
        env.setdefault("JAX_PLATFORMS",
                       os.environ.get("SAIL_WORKER_PLATFORM", "cpu"))
        proc = subprocess.Popen(
            [sys.executable, "-m", "sail_tpu", "worker",
             "--driver", self.driver_addr, "--host", self.host,
             "--task-slots", str(self.task_slots), "--worker-id", wid],
            env=env, cwd=os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))))
        self._procs.append(proc)
        return proc

    def stop_worker(self, handle: subprocess.Popen):
        handle.terminate()
        try:
            handle.wait(timeout=10)
        except subprocess.TimeoutExpired:
            handle.kill()
        if handle in self._procs:
            self._procs.remove(handle)

    def stop_all(self):
        for p in list(self._procs):
            self.stop_worker(p)


# ---------------------------------------------------------------------------
# Kubernetes
# ---------------------------------------------------------------------------

class KubeApi:
    """Minimal kube apiserver REST client (in-cluster service account).
    Injectable for tests; replaced wholesale by a fake in unit tests."""

    TOKEN_PATH = "/var/run/secrets/kubernetes.io/serviceaccount/token"
    CA_PATH = "/var/run/secrets/kubernetes.io/serviceaccount/ca.crt"

    def __init__(self, base_url: Optional[str] = None,
                 token: Optional[str] = None):
        host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        self.base_url = base_url or f"https://{host}:{port}"
        if token is None and os.path.exists(self.TOKEN_PATH):
            with open(self.TOKEN_PATH, "r", encoding="utf-8") as f:
                token = f.read().strip()
        self.token = token

    def request(self, method: str, path: str,
                body: Optional[dict] = None) -> dict:
        import ssl
        import urllib.request

        url = self.base_url + path
        data = None if body is None else json.dumps(body).encode()
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Content-Type", "application/json")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        ctx = ssl.create_default_context(
            cafile=self.CA_PATH if os.path.exists(self.CA_PATH) else None)
        with urllib.request.urlopen(req, context=ctx, timeout=30) as resp:
            return json.loads(resp.read() or b"{}")


class KubernetesWorkerManager(WorkerManager):
    """Create worker PODS via the kube API.

    Reference: crates/sail-execution/src/worker_manager/kubernetes.rs:
    pod per worker, image/namespace/labels from config, owner reference
    to the driver pod so workers are garbage-collected with it, identity
    injected through env vars.
    """

    def __init__(self, driver_addr: str, api: Optional[KubeApi] = None,
                 namespace: Optional[str] = None,
                 image: Optional[str] = None,
                 pod_name_prefix: str = "sail-worker-",
                 task_slots: int = 2,
                 owner_reference: Optional[dict] = None,
                 labels: Optional[Dict[str, str]] = None):
        from ..config import get as config_get
        self.driver_addr = driver_addr
        self.api = api or KubeApi()
        self.namespace = namespace or str(
            config_get("kubernetes.namespace", "default"))
        self.image = image or str(
            config_get("kubernetes.image", "sail-tpu:latest"))
        self.pod_name_prefix = pod_name_prefix
        self.task_slots = task_slots
        self.owner_reference = owner_reference
        self.labels = {"app.kubernetes.io/name": "sail-tpu",
                       "sail.role": "worker", **(labels or {})}
        self._pods: List[str] = []

    def _pod_manifest(self, worker_id: str) -> dict:
        meta: dict = {
            "name": f"{self.pod_name_prefix}{worker_id}",
            "namespace": self.namespace,
            "labels": dict(self.labels),
        }
        if self.owner_reference is not None:
            meta["ownerReferences"] = [self.owner_reference]
        return {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": meta,
            "spec": {
                "restartPolicy": "Never",
                "containers": [{
                    "name": "worker",
                    "image": self.image,
                    "args": ["worker", "--driver", self.driver_addr,
                             "--host", "0.0.0.0",
                             "--advertise-host", "$(SAIL_POD_IP)",
                             "--task-slots", str(self.task_slots),
                             "--worker-id", worker_id],
                    "env": [
                        {"name": "SAIL_WORKER_ID", "value": worker_id},
                        {"name": "SAIL_DRIVER_ADDR",
                         "value": self.driver_addr},
                        # downward API: the address peers dial
                        {"name": "SAIL_POD_IP", "valueFrom": {
                            "fieldRef": {"fieldPath": "status.podIP"}}},
                    ],
                }],
            },
        }

    def start_worker(self, worker_id: Optional[str] = None) -> str:
        wid = worker_id or uuid.uuid4().hex[:8]
        manifest = self._pod_manifest(wid)
        self.api.request(
            "POST", f"/api/v1/namespaces/{self.namespace}/pods", manifest)
        name = manifest["metadata"]["name"]
        self._pods.append(name)
        return name

    def stop_worker(self, handle: str):
        self.api.request(
            "DELETE", f"/api/v1/namespaces/{self.namespace}/pods/{handle}")
        if handle in self._pods:
            self._pods.remove(handle)

    def owns(self, worker_id: str) -> bool:
        """True when this manager created the worker's pod — the driver's
        drain path only retires workers it can actually delete."""
        return f"{self.pod_name_prefix}{worker_id}" in self._pods

    def stop_worker_id(self, worker_id: str):
        """Delete the pod backing a registered worker id (graceful-drain
        retirement and idle reaping route through here)."""
        name = f"{self.pod_name_prefix}{worker_id}"
        if name in self._pods:
            self.stop_worker(name)

    def stop_all(self):
        for name in list(self._pods):
            self.stop_worker(name)

    def list_workers(self) -> List[dict]:
        sel = ",".join(f"{k}={v}" for k, v in self.labels.items())
        out = self.api.request(
            "GET", f"/api/v1/namespaces/{self.namespace}/pods"
                   f"?labelSelector={sel}")
        return out.get("items", [])
