"""Generated protocol modules, loaded under namespaced names (the protoc
output uses flat imports; loading via importlib avoids polluting sys.path
and top-level module names)."""

import importlib.util
import os
import sys


def _load(name: str):
    mod_name = f"sail_tpu.exec.proto.{name}"
    if mod_name in sys.modules:
        return sys.modules[mod_name]
    path = os.path.join(os.path.dirname(__file__), f"{name}.py")
    spec = importlib.util.spec_from_file_location(mod_name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[mod_name] = mod
    spec.loader.exec_module(mod)
    return mod


control_plane_pb2 = _load("control_plane_pb2")
sql_service_pb2 = _load("sql_service_pb2")
