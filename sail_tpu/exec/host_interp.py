"""Host expression interpreter.

Reference role: the execution side of sail-function's wide scalar tail —
everything the device compiler declines (HostFallback) evaluates here over
python values. Device-compilable subtrees still run on device and download
once; only the host-only parts interpret row-wise. Results re-encode as
device columns (numerics) or dictionary-encoded host columns
(strings/arrays/maps/structs), so the surrounding jit pipeline is
undisturbed.
"""

from __future__ import annotations

import datetime
import decimal
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..columnar import arrow_interop as ai
from ..functions.registry import host_fn
from ..plan import rex as rx
from ..plan.compiler import ExprCompiler, HostFallback
from ..spec import data_type as dt

_UTC = datetime.timezone.utc


class HostEvalError(Exception):
    pass


# ---------------------------------------------------------------------------
# basic python semantics for core operators (used when a host-only subtree
# pulls an otherwise-device expression onto the host)
# ---------------------------------------------------------------------------

def _py_div(a, b):
    if b == 0:
        return None
    if isinstance(a, int) and isinstance(b, int):
        return a / b
    return a / b


def _py_eq(a, b):
    return a == b


_PY_BASIC = {
    "+": lambda a, b: a + b,
    "-": lambda a, b: a - b,
    "*": lambda a, b: a * b,
    "/": _py_div,
    "%": lambda a, b: None if b == 0 else a - b * int(a / b) if (
        isinstance(a, int) and isinstance(b, int)) else (
        None if b == 0 else float(np.fmod(a, b))),
    "div": lambda a, b: None if b == 0 else int(a / b),
    "pmod": lambda a, b: None if b == 0 else a % b if (a % b) * b >= 0
    else (a % b),
    "==": _py_eq,
    "=": _py_eq,
    "!=": lambda a, b: a != b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "abs": lambda a: abs(a),
    "negative": lambda a: -a,
    "concat": lambda *xs: _concat(*xs),
    "upper": lambda s: s.upper(),
    "ucase": lambda s: s.upper(),
    "lower": lambda s: s.lower(),
    "lcase": lambda s: s.lower(),
    "length": lambda s: len(s),
    "trim": lambda s: s.strip(),
    "substring": lambda s, p, *l: _substring(s, int(p),
                                             int(l[0]) if l else None),
    "substr": lambda s, p, *l: _substring(s, int(p),
                                          int(l[0]) if l else None),
    "reverse": lambda s: s[::-1] if not isinstance(s, list) else s[::-1],
    "greatest": lambda *xs: None if any(x is None for x in xs) else max(xs),
    "least": lambda *xs: None if any(x is None for x in xs) else min(xs),
    "power": lambda a, b: float(a) ** float(b),
    "sqrt": lambda a: float(a) ** 0.5 if a >= 0 else float("nan"),
    "floor": lambda a: _py_floor(a),
    "ceil": lambda a: _py_ceil(a),
    "ceiling": lambda a: _py_ceil(a),
    "round": lambda a, *d: _py_round(a, int(d[0]) if d else 0),
}


def _concat(*xs):
    if all(isinstance(x, (list, type(None))) for x in xs) and any(
            isinstance(x, list) for x in xs):
        out = []
        for x in xs:
            if x is None:
                return None
            out.extend(x)
        return out
    return "".join(str(x) for x in xs)


def _substring(s, pos, length):
    n = len(s)
    if pos > 0:
        i = pos - 1
    elif pos < 0:
        i = max(n + pos, 0)
    else:
        i = 0
    if length is None:
        return s[i:]
    return s[i:i + max(length, 0)]


def _py_floor(a):
    import math
    if isinstance(a, decimal.Decimal):
        return int(a.to_integral_value(rounding=decimal.ROUND_FLOOR))
    return math.floor(a)


def _py_ceil(a):
    import math
    if isinstance(a, decimal.Decimal):
        return int(a.to_integral_value(rounding=decimal.ROUND_CEILING))
    return math.ceil(a)


def _py_round(a, d):
    if isinstance(a, decimal.Decimal):
        q = decimal.Decimal(1).scaleb(-d)
        return a.quantize(q, rounding=decimal.ROUND_HALF_UP)
    import math
    f = 10 ** d
    return math.floor(abs(a) * f + 0.5) / f * (1 if a >= 0 else -1)


# null-tolerant basics
_PY_NULL_TOLERANT = {
    "and": None, "or": None, "not": None, "isnull": None, "isnotnull": None,
    "coalesce": None, "if": None, "nvl": None, "ifnull": None, "nvl2": None,
    "nullif": None, "in": None, "<=>": None, "isnan": None, "typeof": None,
    "concat_ws": None, "equal_null": None,
}


class HostInterpreter:
    """Evaluates a rex tree for every row of a batch on the host."""

    def __init__(self, executor, comp: ExprCompiler, child):
        self.ex = executor
        self.comp = comp
        self.child = child
        self.cap = child.device.capacity
        self._col_cache: Dict[int, List] = {}

    # -- columnar evaluation -------------------------------------------
    def values(self, e: rx.Rex) -> List:
        """Python values (len == capacity) for expression ``e``."""
        try:
            c = self.comp.compile(e)
        except HostFallback:
            return self._values_host(e)
        data, validity = self.ex._eval(c, self.child)
        arr = ai.column_values_to_arrow(
            np.asarray(data),
            None if validity is None else np.asarray(validity),
            c.dtype, c.dictionary)
        vals = arr.to_pylist()
        if isinstance(c.dtype, dt.YearMonthIntervalType):
            # host functions see YM intervals as int months, not MonthDayNano
            vals = [None if v is None else int(v[0]) for v in vals]
        if len(vals) != self.cap:
            # constant expressions over zero-column batches produce one row
            vals = (vals * self.cap)[:self.cap] if len(vals) == 1 else \
                vals + [None] * (self.cap - len(vals))
        return vals

    def _values_host(self, e: rx.Rex) -> List:
        if isinstance(e, rx.RLit):
            return [e.value.value] * self.cap
        if isinstance(e, rx.RCast):
            src = self.values(e.child)
            st, tt = rx.rex_type(e.child), e.dtype
            return [py_cast(v, st, tt, e.try_) for v in src]
        if isinstance(e, rx.RCase):
            conds = [self.values(c) for c, _ in e.branches]
            vals = [self.values(v) for _, v in e.branches]
            other = self.values(e.else_value) \
                if e.else_value is not None else [None] * self.cap
            out = []
            for i in range(self.cap):
                for cv, vv in zip(conds, vals):
                    if cv[i] is True:
                        out.append(vv[i])
                        break
                else:
                    out.append(other[i])
            return out
        if isinstance(e, rx.RCall):
            return self._call(e)
        raise HostEvalError(
            f"no host evaluation for {type(e).__name__}")

    def _call(self, e: rx.RCall) -> List:
        name = e.fn.lower()
        if name == "__pyudf":
            raise HostFallback("pyudf handled by the projection host path")
        # session-constant functions
        const = _session_constant(name)
        if const is not _NO_CONST:
            return [const] * self.cap
        if name == "typeof":
            return [rx.rex_type(e.args[0]).simple_string()] * self.cap
        if name == "uuid":
            import uuid as _uuid
            return [str(_uuid.uuid4()) for _ in range(self.cap)]
        if name == "monotonically_increasing_id":
            return list(range(self.cap))
        if name == "spark_partition_id":
            return [0] * self.cap
        if name in ("rand", "randn"):
            seed = None
            if e.args:
                a0 = e.args[0]
                if isinstance(a0, rx.RLit):
                    seed = 0 if a0.value.value is None \
                        else int(a0.value.value)
            from ..functions.rng import SparkXorShift
            if seed is not None:
                rng = SparkXorShift(seed)
                draw = rng.next_gaussian if name == "randn" \
                    else rng.next_double
                return [draw() for _ in range(self.cap)]
            import random as _random
            return [(_random.gauss(0.0, 1.0) if name == "randn"
                     else _random.random()) for _ in range(self.cap)]
        if name in ("hash", "xxhash64"):
            from ..functions.host_misc import spark_hash
            types = [rx.rex_type(a) for a in e.args]
            cols = [self.values(a) for a in e.args]
            variant = "mm3" if name == "hash" else "xxh64"
            return [spark_hash([c[i] for c in cols], types, variant)
                    for i in range(self.cap)]
        # arguments: lambdas become closures (per-row when the body
        # references outer columns)
        argv = []
        lambda_mask = []
        for a in e.args:
            if isinstance(a, rx.RLambda):
                outer_refs = rx.references(a.body)
                if outer_refs:
                    outer_vals = {i: self.values(rx.BoundRef(
                        i, f"c{i}", self.comp.column_types[i])) for i in outer_refs}
                    argv.append([self._closure(a, {("__col__", i): v[r]
                                                   for i, v in
                                                   outer_vals.items()})
                                 for r in range(self.cap)])
                else:
                    argv.append([self._closure(a)] * self.cap)
                lambda_mask.append(True)
            else:
                argv.append(self.values(a))
                lambda_mask.append(False)
        hf = host_fn(name)
        if hf is not None and hf.impl is not None:
            from ..functions.host_functions import NULL_TOLERANT
            tolerant = name in NULL_TOLERANT
            return self._map_rows(hf.impl, argv, lambda_mask, tolerant)
        impl = _PY_BASIC.get(name)
        if impl is not None:
            return self._map_rows(impl, argv, lambda_mask, False)
        return self._basic_null_tolerant(name, e, argv)

    def _map_rows(self, impl, argv, lambda_mask, tolerant) -> List:
        out = []
        for i in range(self.cap):
            row = [col[i] for col in argv]
            if not tolerant and any(
                    v is None for v, is_l in zip(row, lambda_mask)
                    if not is_l):
                out.append(None)
                continue
            out.append(impl(*row))
        return out

    def _basic_null_tolerant(self, name: str, e: rx.RCall, argv) -> List:
        out = []
        for i in range(self.cap):
            row = [col[i] for col in argv]
            out.append(_scalar_basic(name, row, e))
        return out

    # -- lambdas --------------------------------------------------------
    def _closure(self, lam: rx.RLambda, outer_env: Optional[Dict] = None):
        base = outer_env or {}

        def f(*vals):
            env = {**base, **dict(zip(lam.params, vals))}
            return _scalar_eval(lam.body, env)
        f.nargs = len(lam.params)
        return f


_NO_CONST = object()


def _session_constant(name: str):
    now = datetime.datetime.now(_UTC)
    if name in ("current_date", "curdate"):
        return now.date()
    if name in ("current_timestamp", "now"):
        return now
    if name == "localtimestamp":
        from ..utils.tz import session_zone
        return now.astimezone(session_zone()).replace(tzinfo=None)
    if name == "current_timezone":
        from ..utils.tz import session_timezone_name
        return session_timezone_name()
    if name in ("current_user", "user", "session_user"):
        return "sail"
    if name in ("current_catalog",):
        return "spark_catalog"
    if name in ("current_database", "current_schema"):
        return "default"
    if name == "version":
        return "4.0.0"
    return _NO_CONST


def _scalar_basic(name: str, row, e: rx.RCall):
    if name == "and":
        a, b = row
        if a is False or b is False:
            return False
        if a is None or b is None:
            return None
        return True
    if name == "or":
        a, b = row
        if a is True or b is True:
            return True
        if a is None or b is None:
            return None
        return False
    if name == "not":
        return None if row[0] is None else not row[0]
    if name == "isnull":
        return row[0] is None
    if name == "isnotnull":
        return row[0] is not None
    if name == "isnan":
        import math
        return isinstance(row[0], float) and math.isnan(row[0])
    if name in ("coalesce",):
        for v in row:
            if v is not None:
                return v
        return None
    if name in ("nvl", "ifnull"):
        return row[0] if row[0] is not None else row[1]
    if name == "nvl2":
        return row[1] if row[0] is not None else row[2]
    if name == "nullif":
        return None if row[0] == row[1] else row[0]
    if name == "if":
        return row[1] if row[0] is True else row[2]
    if name == "<=>" or name == "equal_null":
        return row[0] == row[1] if (row[0] is not None and
                                    row[1] is not None) else \
            (row[0] is None and row[1] is None)
    if name == "in":
        probe, *vals = row
        if probe is None:
            return None
        if probe in vals:
            return True
        return None if None in vals else False
    if name == "concat_ws":
        sep, *vals = row
        if sep is None:
            return None
        flat = []
        for v in vals:
            if v is None:
                continue
            if isinstance(v, list):
                flat.extend(str(x) for x in v if x is not None)
            else:
                flat.append(str(v))
        return sep.join(flat)
    raise HostEvalError(f"no host implementation for function {name!r}")


def _scalar_eval(e: rx.Rex, env: Dict[str, object]):
    """Per-row evaluation inside lambda bodies."""
    if isinstance(e, rx.RLambdaVar):
        return env[e.name]
    if isinstance(e, rx.BoundRef):
        key = ("__col__", e.index)
        if key in env:
            return env[key]
        raise HostEvalError(
            f"outer column {e.name!r} not bound in lambda scope")
    if isinstance(e, rx.RLit):
        return e.value.value
    if isinstance(e, rx.RCast):
        return py_cast(_scalar_eval(e.child, env), rx.rex_type(e.child),
                       e.dtype, e.try_)
    if isinstance(e, rx.RCase):
        for c, v in e.branches:
            if _scalar_eval(c, env) is True:
                return _scalar_eval(v, env)
        return _scalar_eval(e.else_value, env) \
            if e.else_value is not None else None
    if isinstance(e, rx.RCall):
        name = e.fn.lower()
        args = []
        for a in e.args:
            if isinstance(a, rx.RLambda):
                def cl(*vals, _l=a, _env=env):
                    return _scalar_eval(
                        _l.body, {**_env, **dict(zip(_l.params, vals))})
                cl.nargs = len(a.params)
                args.append(cl)
            else:
                args.append(_scalar_eval(a, env))
        hf = host_fn(name)
        from ..functions.host_functions import NULL_TOLERANT
        if hf is not None and hf.impl is not None:
            if name not in NULL_TOLERANT and any(
                    v is None for v, arg in zip(args, e.args)
                    if not isinstance(arg, rx.RLambda)):
                return None
            return hf.impl(*args)
        impl = _PY_BASIC.get(name)
        if impl is not None:
            if any(v is None for v, arg in zip(args, e.args)
                   if not isinstance(arg, rx.RLambda)):
                return None
            return impl(*args)
        return _scalar_basic(name, args, e)
    raise HostEvalError(f"no scalar evaluation for {type(e).__name__}")


# ---------------------------------------------------------------------------
# casts & encoding
# ---------------------------------------------------------------------------

def py_cast(v, src: dt.DataType, target: dt.DataType, try_: bool = False):
    if v is None:
        return None
    try:
        if isinstance(target, dt.StringType):
            return _cast_str(v)
        if isinstance(target, dt.BooleanType):
            if isinstance(v, str):
                s = v.strip().lower()
                if s in ("true", "t", "yes", "y", "1"):
                    return True
                if s in ("false", "f", "no", "n", "0"):
                    return False
                return None
            return bool(v)
        if target.is_integer:
            if isinstance(v, str):
                v = float(v.strip()) if "." in v or "e" in v.lower() \
                    else int(v.strip())
            return int(v)
        if isinstance(target, (dt.FloatType, dt.DoubleType)):
            return float(v)
        if isinstance(target, dt.DecimalType):
            d = decimal.Decimal(str(v))
            q = decimal.Decimal(1).scaleb(-target.scale)
            return d.quantize(q, rounding=decimal.ROUND_HALF_UP)
        if isinstance(target, dt.DateType):
            from ..functions.host_datetime import _to_date
            return _to_date(v)
        if isinstance(target, dt.TimestampType):
            from ..functions.host_datetime import _to_ts
            out = _to_ts(v)
            if out is not None and target.timezone is None:
                out = out.replace(tzinfo=None)
            return out
        if isinstance(target, dt.BinaryType):
            return v if isinstance(v, bytes) else str(v).encode()
        if isinstance(target, (dt.ArrayType, dt.MapType, dt.StructType)):
            return v
    except (ValueError, TypeError, decimal.InvalidOperation,
            OverflowError):
        # non-ANSI null-on-error semantics: CAST and TRY_CAST both yield
        # NULL here (ANSI mode would make plain CAST raise)
        return None
    return v


def _cast_str(v):
    from ..utils.format import format_double

    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float):
        return format_double(v)
    if isinstance(v, decimal.Decimal):
        return format(v, "f")
    if isinstance(v, datetime.datetime):
        if v.tzinfo is not None:
            from ..utils.tz import session_zone
            v = v.astimezone(session_zone())
        s = v.strftime("%Y-%m-%d %H:%M:%S")
        if v.microsecond:
            s += f".{v.microsecond:06d}".rstrip("0")
        return s
    if isinstance(v, datetime.date):
        return v.isoformat()
    return str(v)


def encode_host_column(values: Sequence, t: dt.DataType, cap: int):
    """Python values → (jnp data, validity, dictionary|None)."""
    import jax.numpy as jnp
    import pyarrow as pa

    assert len(values) == cap, (len(values), cap)
    if isinstance(t, (dt.StringType, dt.BinaryType, dt.ArrayType,
                      dt.MapType, dt.StructType, dt.NullType)):
        at = None if isinstance(t, dt.NullType) else ai.spec_type_to_arrow(t)
        try:
            arr = pa.array([_pyarrowable(v, t) for v in values], type=at)
        except (pa.ArrowInvalid, pa.ArrowTypeError, OverflowError):
            arr = pa.array([None if v is None else str(v) for v in values],
                           type=pa.string())
        import pyarrow.compute as pc
        if pa.types.is_nested(arr.type):
            # dictionary_encode has no nested kernels: use positional codes
            # (a dictionary need not be distinct-valued)
            codes = np.arange(cap, dtype=np.int32)
            validity = jnp.asarray(np.asarray(pc.is_valid(arr)))
            return jnp.asarray(codes), validity, arr
        enc = arr.dictionary_encode()
        codes = np.asarray(enc.indices.fill_null(0)).astype(np.int32)
        validity = jnp.asarray(np.asarray(pc.is_valid(arr)))
        return jnp.asarray(codes), validity, enc.dictionary
    # physical numeric/temporal encoding
    from ..columnar.batch import physical_jnp_dtype
    jdt = physical_jnp_dtype(t)
    data = np.zeros(cap, dtype=jdt)
    mask = np.zeros(cap, dtype=bool)
    for i, v in enumerate(values):
        if v is None:
            continue
        mask[i] = True
        data[i] = _physical(v, t)
    validity = jnp.asarray(mask) if not all(mask) else None
    return jnp.asarray(data), validity, None


def _physical(v, t: dt.DataType):
    if isinstance(t, dt.DateType):
        if isinstance(v, datetime.datetime):
            v = v.date()
        return (v - datetime.date(1970, 1, 1)).days
    if isinstance(t, dt.TimestampType):
        if isinstance(v, datetime.date) and not isinstance(
                v, datetime.datetime):
            v = datetime.datetime(v.year, v.month, v.day)
        if v.tzinfo is None:
            v = v.replace(tzinfo=_UTC)
        return int(v.timestamp() * 1_000_000)
    if isinstance(t, dt.DecimalType) and t.physical_dtype == "int64":
        return int(decimal.Decimal(str(v)).scaleb(t.scale)
                   .to_integral_value(rounding=decimal.ROUND_HALF_UP))
    if isinstance(t, dt.DayTimeIntervalType):
        if isinstance(v, datetime.timedelta):
            return round(v.total_seconds() * 1e6)
        return int(v)
    if isinstance(t, dt.TimeType):
        if isinstance(v, datetime.time):
            return dt.time_to_micros(v)
        return int(v)
    if isinstance(t, dt.YearMonthIntervalType):
        return int(v)
    if isinstance(t, dt.BooleanType):
        return bool(v)
    return v


def _pyarrowable(v, t: dt.DataType):
    if v is None:
        return None
    if isinstance(t, dt.YearMonthIntervalType) and isinstance(v, int):
        return (v, 0, 0)
    if isinstance(t, dt.MapType) and isinstance(v, dict):
        return list(v.items())
    if isinstance(t, dt.ArrayType) and isinstance(v, (list, tuple)):
        return [_pyarrowable(x, t.element_type) for x in v]
    if isinstance(t, dt.StructType) and isinstance(v, dict):
        if all(f.name in v for f in t.fields):
            return {f.name: _pyarrowable(v[f.name], f.data_type)
                    for f in t.fields}
        # positional mapping (impl used generic keys)
        vals = list(v.values())
        return {f.name: _pyarrowable(vals[i] if i < len(vals) else None,
                                     f.data_type)
                for i, f in enumerate(t.fields)}
    return v
