"""Adaptive query execution: stage-boundary replanning from observed
shuffle statistics.

Reference role: Spark AQE in the reference Sail architecture (PAPER.md)
and Theseus' thesis (arXiv:2508.05029) that at scale the engine is a
data-movement scheduler — plan decisions should be made when the
data-movement facts are in, not before the first byte is read. The
driver already learns every completed task's per-channel compressed
bytes and raw (decoded) bytes from success reports; this module
re-examines the NOT-yet-launched suffix of the job graph at every
shuffle stage boundary and applies four rewrites, each individually
gated under ``adaptive.*`` (surfaced as ``spark.sail.adaptive.``):

1. **Coalesce** (``adaptive.coalesce``): runs of small shuffle channels
   merge into one consumer task against ``target_mb`` of decoded input,
   so a thousand near-empty partitions do not pay a thousand task
   dispatches and fetch round trips.
2. **Skew split** (``adaptive.skew``): a hot join channel (>
   ``factor`` × the median channel, ≥ ``min_mb``) splits across up to
   ``max_subtasks`` consumer tasks by producer-partition ranges; the
   build side's matching channel is REPLICATED to every subtask
   (partial-broadcast of the hot keys) — sound for inner/left/semi/anti
   joins because every probe row still meets the full build set exactly
   once.
3. **Broadcast conversion** (``adaptive.broadcast``): an eligible
   shuffle join's probe producer is barriered behind the build side
   (``Stage.launch_after``); when the build's observed decoded size
   lands under ``threshold_mb`` the probe producer drops its shuffle
   write entirely and each join task reads its own probe partition
   FORWARD plus the whole build output.
4. **Reorder re-entry** (``adaptive.reorder``): once every input of the
   driver-run root stage is complete, ``join_reorder`` re-runs over the
   root's join tree with OBSERVED stage output rows as leaf estimates;
   the rewrite is adopted only when the observed sizes actually invert
   the ordering the static estimates produce.

Every rewrite is validated (``validate_adaptive_rewrite``: frozen
launched/completed stages untouched + the full job-graph stage-boundary
check) before it replaces the pending suffix, and rolled back when
validation fails. Decisions depend ONLY on the observed byte/row
statistics of completed stages — which are bit-identical across retries,
speculation, and fault recovery — so the decision sequence is
deterministic per fault seed regardless of thread interleaving.
"""

from __future__ import annotations

import json
import math
import statistics
from typing import Dict, List, Optional, Set, Tuple

from .. import events
from ..config import get as config_get
from ..config import truthy
from ..events import EventType
from ..metrics import record as _record_metric
from ..plan import nodes as pn
from . import job_graph as jg

_MB = 1 << 20

#: metric per decision kind — literal names so the registry drift lint
#: sees the declaration exercised
_DECISION_METRICS = {
    "coalesce": "cluster.adaptive.coalesced_count",
    "split": "cluster.adaptive.split_count",
    "broadcast": "cluster.adaptive.broadcast_count",
    "reorder": "cluster.adaptive.reordered_count",
}

#: join types for which replicating the RIGHT (build) side over a split
#: or broadcast-converted probe is sound: output rows are a function of
#: probe rows × the full build set, so probe rows may be partitioned
#: freely while build rows duplicate
_REPLICATE_SAFE_JOINS = ("inner", "left", "semi", "anti")


def _conf_float(key: str, default: float) -> float:
    try:
        return float(config_get(key, default))
    except (TypeError, ValueError):
        return default


def _conf_int(key: str, default: int) -> int:
    try:
        return int(config_get(key, default))
    except (TypeError, ValueError):
        return default


def enabled() -> bool:
    """Master switch (``spark.sail.adaptive.enabled``)."""
    return truthy("adaptive.enabled")


class AdaptiveState:
    """Per-job adaptive bookkeeping, owned by the driver actor thread."""

    def __init__(self):
        self.stages_done: Set[int] = set()      # completion transitions
        self.considered: Set[int] = set()       # coalesce/split evaluated
        self.reorder_done = False
        # flight-recorder envelope of the owning job/query (stamped by
        # _Job.__init__ and LocalCluster.run_job before submit)
        self.job_id = ""
        self.query_id = ""
        self.trace_id: Optional[str] = None
        self.coalesced = 0
        self.split = 0
        self.broadcast = 0
        self.reordered = 0
        self.events: List[dict] = []
        self.skew: List[dict] = []              # per shuffle-producer stage
        self.channel_report: List[dict] = []    # satellite: per-channel sizes

    def counts(self) -> Dict[str, int]:
        return {"coalesced": self.coalesced, "split": self.split,
                "broadcast": self.broadcast, "reordered": self.reordered}

    def note(self, kind: str, **info) -> None:
        event = {"kind": kind}
        event.update(sorted(info.items()))
        recorded = len(self.events) < 128
        if recorded:
            self.events.append(event)
        metric = _DECISION_METRICS.get(kind)
        if metric is not None:
            try:
                _record_metric(metric, 1)
            except Exception:  # noqa: BLE001 — telemetry never fails a job
                pass
        # the decision record rides the event log as canonical JSON;
        # the emission honors the SAME 128-entry cap as the profile's
        # decision list, so replaying the log reconstructs the
        # profile's sequence bit-identically even for pathological
        # jobs that overflow it
        if not recorded:
            return
        try:
            events.emit(EventType.ADAPTIVE_APPLIED,
                        query_id=self.query_id, trace_id=self.trace_id,
                        job_id=self.job_id, kind=kind,
                        detail=json.dumps(event, sort_keys=True))
        except Exception:  # noqa: BLE001 — telemetry never fails a job
            pass


# ---------------------------------------------------------------------------
# graph planning (split_job): broadcast-conversion barriers
# ---------------------------------------------------------------------------

def plan_graph(graph: jg.JobGraph) -> None:
    """Register broadcast-conversion candidates: for every eligible
    shuffle join whose build side is plausibly small, barrier the probe
    producer behind the build producer so the conversion decision can be
    made from the build's OBSERVED size before the probe shuffles."""
    if not (enabled() and truthy("adaptive.broadcast.enabled")):
        return
    max_est = _conf_float("adaptive.broadcast.max_est_rows", 2_000_000.0)
    consumers: Dict[int, int] = {}
    for stage in graph.stages:
        for i in stage.inputs:
            consumers[i.stage_id] = consumers.get(i.stage_id, 0) + 1
    for stage in graph.stages:
        cand = _bcast_candidate(stage)
        if cand is None:
            continue
        probe_sid, build_sid = cand
        # the probe producer's shuffle write must serve ONLY this join
        # (the builder emits single-consumer stages; assert it anyway)
        if consumers.get(probe_sid, 0) != 1:
            continue
        build = graph.stages[build_sid]
        # a build whose plan bottoms out in exchange leaves has NO
        # grounded size estimate (the model would fall back to default
        # rows, always under max_est) — never pay the probe barrier on
        # a guess, only when real leaf stats predict a small build
        if any(isinstance(n, jg.StageInputExec)
               for n in pn.walk_plan(build.plan)):
            continue
        if _est_stage_rows(build, graph) > max_est:
            continue
        probe = graph.stages[probe_sid]
        if probe.num_partitions != stage.num_partitions and \
                _has_forward_consumer(graph, stage.stage_id):
            continue  # conversion would change the join's task count
        stage.bcast_candidate = (probe_sid, build_sid)
        probe.launch_after = tuple(sorted(
            set(probe.launch_after) | {build_sid}))


def _has_forward_consumer(graph: jg.JobGraph, sid: int) -> bool:
    """True when some stage reads ``sid`` over FORWARD: its task count
    was frozen to this stage's partition count at graph build (FORWARD
    task p reads producer partition p), so a rewrite that changes
    ``num_partitions`` would strand consumer tasks waiting on partitions
    that never appear (fewer) or silently drop the extras (more)."""
    return any(i.stage_id == sid and i.mode == jg.InputMode.FORWARD
               for st in graph.stages for i in st.inputs)


def _stage_join(stage: jg.Stage) -> Optional[pn.JoinExec]:
    """The shuffle join at the heart of a builder-emitted join stage.
    The builder fuses pipeline Filters/Projects and the partial
    aggregate ABOVE the join into the same stage plan, so dig through
    single-input operators; the join's children must be the stage's
    exchange leaves. Replication-safety note: everything the builder
    fuses above the join (Filter, Project, partial/dedup aggregates) is
    row-local or merge-safe, so probe rows may be re-partitioned across
    tasks as long as each still meets the full matching build set."""
    p = stage.plan
    while isinstance(p, (pn.FilterExec, pn.ProjectExec,
                         pn.AggregateExec)):
        p = p.input
    if not isinstance(p, pn.JoinExec):
        return None
    if p.join_type not in _REPLICATE_SAFE_JOINS or p.null_aware:
        return None
    if not (isinstance(p.left, jg.StageInputExec)
            and isinstance(p.right, jg.StageInputExec)):
        return None
    if p.left.stage_id == p.right.stage_id:
        return None
    return p


def _bcast_candidate(stage: jg.Stage) -> Optional[Tuple[int, int]]:
    """(probe sid, build sid) when ``stage`` is a shuffle join whose
    build side could convert to a broadcast read."""
    if stage.on_driver:
        return None
    p = _stage_join(stage)
    if p is None:
        return None
    modes = {i.stage_id: i.mode for i in stage.inputs}
    probe_sid, build_sid = p.left.stage_id, p.right.stage_id
    if modes.get(probe_sid) != jg.InputMode.SHUFFLE or \
            modes.get(build_sid) != jg.InputMode.SHUFFLE:
        return None
    return probe_sid, build_sid


def _est_stage_rows(stage: jg.Stage, graph: jg.JobGraph) -> float:
    """Static estimate of a stage's output rows — join_reorder's
    cardinality model, taught about driver-stripped memory scans and
    exchange leaves."""
    from ..plan import join_reorder as jr

    def est(node):
        if isinstance(node, pn.ScanExec) and node.format == "__driver__":
            t = graph.scan_tables.get(node.table_name)
            return None if t is None else float(t.num_rows)
        if isinstance(node, jg.StageInputExec):
            return jr._DEFAULT_ROWS
        return None

    try:
        return jr._est_rows(stage.plan, est)
    except Exception:  # noqa: BLE001 — estimation is advisory
        return float("inf")


# ---------------------------------------------------------------------------
# observed statistics
# ---------------------------------------------------------------------------

def _decoded_entry(job, sid: int, p: int):
    """(per-channel decoded bytes, decoded total) for one producer
    partition, scaling compressed channel bytes by the partition's
    raw/compressed ratio. None while the report has not landed."""
    entry = job.channel_bytes.get((sid, p))
    if entry is None:
        return None
    chans, raw = entry
    comp_total = sum(chans)
    scale = (raw / comp_total) if comp_total else 1.0
    return [c * scale for c in chans], raw


def _channel_totals(job, sid: int) -> Optional[List[float]]:
    """Decoded bytes per channel of a completed shuffle producer,
    summed over its partitions. None if any partition is unreported."""
    stage = job.graph.stages[sid]
    totals: Optional[List[float]] = None
    for p in range(stage.num_partitions):
        got = _decoded_entry(job, sid, p)
        if got is None:
            return None
        chans, _raw = got
        if totals is None:
            totals = [0.0] * len(chans)
        for c, v in enumerate(chans):
            if c < len(totals):
                totals[c] += v
    return totals


def _stage_decoded_bytes(job, sid: int) -> Optional[float]:
    stage = job.graph.stages[sid]
    total = 0.0
    for p in range(stage.num_partitions):
        got = _decoded_entry(job, sid, p)
        if got is None:
            return None
        total += got[1]
    return total


# ---------------------------------------------------------------------------
# the stage-boundary hook (driver actor thread)
# ---------------------------------------------------------------------------

def on_stage_complete(driver, job, stage_id: int) -> None:
    """Called by the driver exactly once per stage completion, BEFORE
    any newly-unblocked consumer is scheduled. Records skew telemetry
    unconditionally; applies rewrites to the pending suffix when
    adaptive execution is on."""
    graph = job.graph
    stage = graph.stages[stage_id]
    if stage.shuffle_keys is not None and stage.num_channels > 1:
        _note_skew(job, stage_id)
    if not enabled():
        return
    for s in graph.stages:
        if s.bcast_candidate is not None and \
                s.bcast_candidate[1] == stage_id:
            _maybe_broadcast(driver, job, s)
    for s in graph.stages:
        if any(i.stage_id == stage_id for i in s.inputs):
            _maybe_coalesce_split(driver, job, s)
    _maybe_reorder(driver, job)


def _note_skew(job, sid: int) -> None:
    """Satellite surface: per-channel shuffle sizes and the max/median
    skew ratio of every completed shuffle producer — visible in the
    profile (``skew:`` line, FORMAT JSON, query_profiles) even when
    adaptive execution is off."""
    st = job.adaptive
    totals = _channel_totals(job, sid)
    if not totals:
        return
    raw_total = 0
    comp: List[int] = []
    stage = job.graph.stages[sid]
    for p in range(stage.num_partitions):
        entry = job.channel_bytes.get((sid, p))
        if entry is None:
            continue
        chans, raw = entry
        raw_total += raw
        if not comp:
            comp = [0] * len(chans)
        for c, v in enumerate(chans):
            if c < len(comp):
                comp[c] += v
    if len(st.channel_report) < 32:
        st.channel_report.append({
            "stage": sid, "raw_bytes": int(raw_total),
            "compressed_bytes": [int(v) for v in comp[:64]]})
    if len(totals) < 2:
        return
    med = statistics.median(totals)
    mx = max(totals)
    ratio = (mx / med) if med > 0 else (float(len(totals)) if mx else 1.0)
    entry = {"stage": sid, "channels": len(totals),
             "max_bytes": int(mx), "median_bytes": int(med),
             "ratio": round(ratio, 3)}
    if len(st.skew) < 32:
        st.skew.append(entry)
    try:
        _record_metric("cluster.shuffle.skew_ratio", ratio)
    except Exception:  # noqa: BLE001
        pass


# ---------------------------------------------------------------------------
# rewrite plumbing
# ---------------------------------------------------------------------------

def _frozen_stages(job) -> Set[int]:
    frozen = set(job.scheduled)
    frozen.update(sid for sid, _p in job.launched)
    frozen.update(job.adaptive.stages_done)
    frozen.update(sid for sid, _p in job.live)
    return frozen


def _stage_started(job, sid: int) -> bool:
    return sid in job.scheduled or \
        any(k[0] == sid for k in job.launched) or \
        any(k[0] == sid for k in job.live)


def _snapshot(stage: jg.Stage) -> dict:
    return {"plan": stage.plan, "inputs": stage.inputs,
            "num_partitions": stage.num_partitions,
            "shuffle_keys": stage.shuffle_keys,
            "num_channels": stage.num_channels,
            "launch_after": stage.launch_after,
            "bcast_candidate": stage.bcast_candidate}


def _restore(stage: jg.Stage, snap: dict) -> None:
    for k, v in snap.items():
        setattr(stage, k, v)


def _apply_rewrite(job, kind: str, touched: Set[int], fn) -> bool:
    """Apply ``fn`` (which mutates stages in ``touched``), then enforce
    the adaptive invariant; roll the mutation back if anything fails.
    Returns True when the rewrite stuck."""
    from ..analysis.invariants import (stage_signature,
                                       validate_adaptive_rewrite)
    graph = job.graph
    frozen = _frozen_stages(job)
    if touched & frozen:
        return False
    before = {s.stage_id: stage_signature(s) for s in graph.stages}
    saved = {sid: _snapshot(graph.stages[sid]) for sid in touched}
    try:
        fn()
        validate_adaptive_rewrite(graph, frozen=frozen, before=before)
    except Exception:  # noqa: BLE001 — a refused rewrite must not fail the job
        for sid, snap in saved.items():
            _restore(graph.stages[sid], snap)
        st = job.adaptive
        try:
            events.emit(EventType.ADAPTIVE_ROLLBACK,
                        query_id=st.query_id, trace_id=st.trace_id,
                        job_id=st.job_id, kind=kind,
                        stages=",".join(str(s)
                                        for s in sorted(touched)))
        except Exception:  # noqa: BLE001
            pass
        return False
    return True


# ---------------------------------------------------------------------------
# rewrite 3: shuffle join → broadcast join
# ---------------------------------------------------------------------------

def _maybe_broadcast(driver, job, s: jg.Stage) -> None:
    graph = job.graph
    st = job.adaptive
    probe_sid, build_sid = s.bcast_candidate
    s.bcast_candidate = None  # one decision per join
    if not truthy("adaptive.broadcast.enabled"):
        return
    if _stage_started(job, s.stage_id) or _stage_started(job, probe_sid):
        return
    total = _stage_decoded_bytes(job, build_sid)
    if total is None:
        return
    threshold = _conf_float("adaptive.broadcast.threshold_mb", 16.0) * _MB
    if total > threshold:
        return
    probe = graph.stages[probe_sid]
    build = graph.stages[build_sid]
    # re-checked at decision time: a downstream conversion may have
    # added a FORWARD consumer of this join since plan_graph ran
    if probe.num_partitions != s.num_partitions and \
            _has_forward_consumer(graph, s.stage_id):
        return

    def apply():
        probe.shuffle_keys = None
        probe.num_channels = 1
        s.num_partitions = probe.num_partitions
        # channel -2 = every channel of the producer in ONE stream:
        # num_partitions round trips per task, not partitions×channels
        pairs = tuple((p, -2) for p in range(build.num_partitions))
        new_inputs = []
        for i in s.inputs:
            if i.stage_id == probe_sid:
                new_inputs.append(jg.StageInput(probe_sid,
                                                jg.InputMode.FORWARD))
            elif i.stage_id == build_sid:
                new_inputs.append(jg.StageInput(
                    build_sid, jg.InputMode.SHUFFLE,
                    fetch_plan=(pairs,) * s.num_partitions))
            else:
                new_inputs.append(i)
        s.inputs = tuple(new_inputs)

    if _apply_rewrite(job, "broadcast", {s.stage_id, probe_sid}, apply):
        st.broadcast += 1
        st.note("broadcast", stage=s.stage_id, probe=probe_sid,
                build=build_sid, build_bytes=int(total))


# ---------------------------------------------------------------------------
# rewrites 1 + 2: coalesce small channels, split skewed ones
# ---------------------------------------------------------------------------

def _maybe_coalesce_split(driver, job, s: jg.Stage) -> None:
    graph = job.graph
    st = job.adaptive
    if s.stage_id in st.considered:
        return
    if s.on_driver or s.num_partitions <= 1:
        return
    if not s.inputs or any(
            i.mode != jg.InputMode.SHUFFLE or i.fetch_plan is not None
            for i in s.inputs):
        return
    if not all(driver._stage_complete(job, i.stage_id) for i in s.inputs):
        return
    if _stage_started(job, s.stage_id):
        return
    if _has_forward_consumer(graph, s.stage_id):
        # a pipelined consumer's task count is frozen to this stage's
        # partition count — coalesce/split would change it
        return
    st.considered.add(s.stage_id)
    do_coalesce = truthy("adaptive.coalesce.enabled")
    do_split = truthy("adaptive.skew.enabled")
    if not (do_coalesce or do_split):
        return
    per_input: Dict[int, List[float]] = {}
    for i in s.inputs:
        totals = _channel_totals(job, i.stage_id)
        if totals is None:
            return
        per_input[i.stage_id] = totals
    n_tasks = s.num_partitions  # task r consumes channel r
    sizes = [sum(t[c] for t in per_input.values() if c < len(t))
             for c in range(n_tasks)]
    target = max(1.0, _conf_float("adaptive.coalesce.target_mb", 64.0)
                 * _MB)

    probe_sid = _split_probe_sid(s) if do_split else None
    hot: Dict[int, List[Tuple[int, ...]]] = {}
    if probe_sid is not None:
        hot = _find_hot_channels(job, s, probe_sid,
                                 per_input[probe_sid][:n_tasks], target)

    # assignment: ("chan", channels tuple) keeps whole channels per
    # task; ("split", channel, producer-partition subset) splits a hot
    # probe channel by producer ranges
    assign: List[tuple] = []
    group: List[int] = []
    group_bytes = 0.0

    def flush():
        nonlocal group, group_bytes
        if group:
            assign.append(("chan", tuple(group)))
        group, group_bytes = [], 0.0

    for c in range(n_tasks):
        if c in hot:
            flush()
            for subset in hot[c]:
                assign.append(("split", c, subset))
            continue
        if not do_coalesce:
            assign.append(("chan", (c,)))
            continue
        if group and group_bytes + sizes[c] > target:
            flush()
        group.append(c)
        group_bytes += sizes[c]
    flush()

    n_groups = sum(1 for a in assign if a[0] == "chan" and len(a[1]) > 1)
    if not hot and n_groups == 0:
        return

    def apply():
        new_inputs = []
        for i in s.inputs:
            up = graph.stages[i.stage_id]
            nparts = up.num_partitions
            plans = []
            for a in assign:
                if a[0] == "chan":
                    plans.append(tuple((p, c) for c in a[1]
                                       for p in range(nparts)))
                else:
                    _kind, c, subset = a
                    if i.stage_id == probe_sid:
                        plans.append(tuple((p, c) for p in subset))
                    else:
                        # replicate the other side's hot channel to
                        # every subtask (partial broadcast of hot keys)
                        plans.append(tuple((p, c) for p in range(nparts)))
            new_inputs.append(jg.StageInput(i.stage_id, i.mode,
                                            fetch_plan=tuple(plans)))
        s.inputs = tuple(new_inputs)
        s.num_partitions = len(assign)

    if _apply_rewrite(job, "coalesce" if not hot else "split",
                      {s.stage_id}, apply):
        if n_groups:
            st.coalesced += n_groups
            st.note("coalesce", stage=s.stage_id, groups=n_groups,
                    tasks=len(assign), channels=n_tasks)
        for c in sorted(hot):
            st.split += 1
            st.note("split", stage=s.stage_id, channel=c,
                    subtasks=len(hot[c]),
                    channel_bytes=int(per_input[probe_sid][c]))


def _split_probe_sid(s: jg.Stage) -> Optional[int]:
    """The probe-side input of a join stage whose hot channels may be
    split (the other side's channel replicates to every subtask)."""
    p = _stage_join(s)
    return None if p is None else p.left.stage_id


def _find_hot_channels(job, s: jg.Stage, probe_sid: int,
                       probe_totals: List[float], target: float
                       ) -> Dict[int, List[Tuple[int, ...]]]:
    factor = _conf_float("adaptive.skew.factor", 4.0)
    min_bytes = _conf_float("adaptive.skew.min_mb", 32.0) * _MB
    max_sub = max(2, _conf_int("adaptive.skew.max_subtasks", 8))
    if len(probe_totals) < 2:
        return {}
    med = statistics.median(probe_totals)
    out: Dict[int, List[Tuple[int, ...]]] = {}
    for c, size in enumerate(probe_totals):
        if size < min_bytes or size <= factor * max(med, 1.0):
            continue
        k = min(max_sub, max(2, math.ceil(size / max(target, 1.0))))
        subsets = _split_producer_parts(job, probe_sid, c, k)
        if len(subsets) >= 2:
            out[c] = subsets
    return out


def _split_producer_parts(job, sid: int, channel: int, k: int
                          ) -> List[Tuple[int, ...]]:
    """Partition a producer's partitions into ≤ k contiguous ranges of
    roughly equal channel-``channel`` bytes. Deterministic: driven only
    by the reported sizes."""
    stage = job.graph.stages[sid]
    weights: List[float] = []
    for p in range(stage.num_partitions):
        got = _decoded_entry(job, sid, p)
        if got is None:
            return []
        chans, _raw = got
        weights.append(chans[channel] if channel < len(chans) else 0.0)
    total = sum(weights)
    if total <= 0 or len(weights) < 2:
        return []
    per = total / k
    subsets: List[Tuple[int, ...]] = []
    cur: List[int] = []
    acc = 0.0
    for p, w in enumerate(weights):
        cur.append(p)
        acc += w
        if acc >= per and len(subsets) < k - 1:
            subsets.append(tuple(cur))
            cur, acc = [], 0.0
    if cur:
        subsets.append(tuple(cur))
    return subsets


# ---------------------------------------------------------------------------
# rewrite 4: join-reorder re-entry for the driver-run suffix
# ---------------------------------------------------------------------------

def _maybe_reorder(driver, job) -> None:
    st = job.adaptive
    if st.reorder_done or not truthy("adaptive.reorder.enabled"):
        return
    root = job.graph.root
    if not all(driver._stage_complete(job, i.stage_id)
               for i in root.inputs):
        return
    st.reorder_done = True
    joins = [n for n in pn.walk_plan(root.plan)
             if isinstance(n, pn.JoinExec)]
    if len(joins) < 2:
        return
    from ..plan import join_reorder as jr
    from ..plan.optimizer import _strip_runtime_filters

    def static(node):
        # both passes resolve driver-stripped memory scans to their real
        # row counts, so the ONLY difference between them is whether the
        # exchange leaves use observed stage output rows
        if isinstance(node, pn.ScanExec) and node.format == "__driver__":
            t = job.graph.scan_tables.get(node.table_name)
            return None if t is None else float(t.num_rows)
        return None

    def observed(node):
        if isinstance(node, jg.StageInputExec):
            rows = job.stage_rows.get(node.stage_id)
            return None if rows is None else float(rows)
        return static(node)

    try:
        stripped = _strip_runtime_filters(root.plan)
        baseline = jr.reorder_joins(stripped, est=static)
        informed = jr.reorder_joins(stripped, est=observed)
        # adopt only when the observed sizes actually INVERT the static
        # ordering — otherwise keep the original (annotated) plan
        if pn.explain(informed) == pn.explain(baseline):
            return
        # the strip dropped the original plan's runtime-filter edges;
        # re-derive them against the reordered node identities (the
        # optimizer pipeline re-annotates after its reorder pass too)
        from ..plan.optimizer import _maybe_annotate_runtime_filters
        informed = _maybe_annotate_runtime_filters(informed)
        from ..analysis.invariants import validate_plan
        validate_plan(informed, after="adaptive.reorder")
    except Exception:  # noqa: BLE001 — a refused rewrite keeps the plan
        return
    old_schema = tuple((f.name, f.dtype) for f in root.plan.schema)
    new_schema = tuple((f.name, f.dtype) for f in informed.schema)
    if old_schema != new_schema:
        return
    root.plan = informed
    st.reordered += 1
    st.note("reorder", stage=root.stage_id, joins=len(joins))
