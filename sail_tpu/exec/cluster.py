"""Driver/worker cluster runtime over gRPC.

Reference role: sail-execution's DriverActor/WorkerActor, worker pool with
heartbeats, task scheduler with retry, and the RPC services
(crates/sail-execution/src/driver/, src/worker/ — SURVEY.md §2.5/§3.3).
v0 shape:

- DriverActor owns the worker registry (heartbeat timestamps, lost-worker
  probing), the job table, and task scheduling (round-robin over live
  workers, per-task attempts with retry on worker failure).
- WorkerActor runs task fragments on its local executor; results return in
  ReportTaskStatus as Arrow IPC (a Flight-style peer-to-peer stream data
  plane replaces this for shuffle stages in a later round).
- Local-cluster mode (the reference's test vehicle) runs driver + workers
  in threads speaking REAL gRPC over localhost.

Transport: grpc generic handlers over protoc-generated messages
(sail_tpu/exec/proto/control_plane.proto).
"""

from __future__ import annotations

import sys
import os
import threading
import time
import uuid
from concurrent import futures
from typing import Dict, List, Optional, Tuple

import grpc

from .proto import control_plane_pb2 as pb

from .actor import Actor
from . import job_graph as jg  # noqa: E402

_DRIVER_SERVICE = "sail_tpu.control.DriverService"
_WORKER_SERVICE = "sail_tpu.control.WorkerService"


def _unary(fn, req_cls):
    return grpc.unary_unary_rpc_method_handler(
        fn, request_deserializer=req_cls.FromString,
        response_serializer=lambda m: m.SerializeToString())


# ---------------------------------------------------------------------------
# Worker
# ---------------------------------------------------------------------------

class WorkerActor(Actor):
    def __init__(self, worker_id: str, driver_addr: str, task_slots: int = 2):
        super().__init__()
        self.worker_id = worker_id
        self.driver_addr = driver_addr
        self.task_slots = task_slots
        self.port = 0
        self._server: Optional[grpc.Server] = None
        self._driver_channel: Optional[grpc.Channel] = None
        self._running: Dict[Tuple[str, int, int], threading.Thread] = {}
        self._pool = futures.ThreadPoolExecutor(max_workers=task_slots)
        self._hb_stop = threading.Event()

    # -- rpc service -----------------------------------------------------
    def _service(self):
        def run_task(request: pb.RunTaskRequest, context):
            self.handle.send(("run_task", request.task))
            return pb.RunTaskResponse(accepted=True)

        def stop_task(request: pb.StopTaskRequest, context):
            self.handle.send(("stop_task", request))
            return pb.StopTaskResponse()

        return grpc.method_handlers_generic_handler(_WORKER_SERVICE, {
            "RunTask": _unary(run_task, pb.RunTaskRequest),
            "StopTask": _unary(stop_task, pb.StopTaskRequest),
        })

    def on_start(self):
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        self._server.add_generic_rpc_handlers((self._service(),))
        self.port = self._server.add_insecure_port("127.0.0.1:0")
        self._server.start()
        self._driver_channel = grpc.insecure_channel(self.driver_addr)
        resp = self._call_driver("RegisterWorker", pb.RegisterWorkerRequest(
            worker_id=self.worker_id, host="127.0.0.1", port=self.port,
            task_slots=self.task_slots), pb.RegisterWorkerResponse)
        if not resp.accepted:
            raise RuntimeError("driver rejected worker registration")
        threading.Thread(target=self._heartbeat_loop, daemon=True).start()

    def on_stop(self):
        self._hb_stop.set()
        if self._server is not None:
            self._server.stop(grace=0.5)

    def _call_driver(self, method: str, msg, resp_cls):
        rpc = self._driver_channel.unary_unary(
            f"/{_DRIVER_SERVICE}/{method}",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=resp_cls.FromString)
        return rpc(msg, timeout=30)

    def _heartbeat_loop(self):
        while not self._hb_stop.wait(1.0):
            try:
                self._call_driver("Heartbeat", pb.HeartbeatRequest(
                    worker_id=self.worker_id,
                    running_tasks=len(self._running)), pb.HeartbeatResponse)
            except grpc.RpcError:
                pass

    # -- actor -----------------------------------------------------------
    def receive(self, message):
        kind, payload = message
        if kind == "run_task":
            task: pb.TaskDefinition = payload
            self._pool.submit(self._run_task, task)
        elif kind == "stop_task":
            pass  # cooperative cancel lands with the streaming runtime

    def _run_task(self, task: pb.TaskDefinition):
        import pyarrow as pa
        from .local import LocalExecutor
        try:
            self._report(task, "running", b"")
            plan = jg.decode_fragment(task.plan, task.scan_table or None,
                                      task.partition,
                                      max(task.num_partitions, 1))
            table = LocalExecutor().execute(plan)
            sink = pa.BufferOutputStream()
            with pa.ipc.new_stream(sink, table.schema) as w:
                w.write_table(table)
            self._report(task, "succeeded", sink.getvalue().to_pybytes())
        except Exception as e:  # noqa: BLE001 — full cause goes to the driver
            self._report(task, "failed", b"", str(e))

    def _report(self, task: pb.TaskDefinition, state: str, result: bytes,
                error: str = ""):
        try:
            self._call_driver("ReportTaskStatus", pb.ReportTaskStatusRequest(
                worker_id=self.worker_id, job_id=task.job_id,
                stage=task.stage, partition=task.partition,
                attempt=task.attempt, state=state, error=error,
                result=result), pb.ReportTaskStatusResponse)
        except grpc.RpcError:
            pass


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------

class _Job:
    def __init__(self, job_id: str, graph: jg.JobGraph):
        self.job_id = job_id
        self.graph = graph
        self.results: Dict[int, bytes] = {}
        self.failed: Optional[str] = None
        self.attempts: Dict[int, int] = {}
        self.done = threading.Event()


class DriverActor(Actor):
    HEARTBEAT_TIMEOUT_S = 10.0
    MAX_TASK_ATTEMPTS = 3

    def __init__(self):
        super().__init__()
        self.driver_id = uuid.uuid4().hex[:8]
        self.workers: Dict[str, dict] = {}
        self.jobs: Dict[str, _Job] = {}
        self._server: Optional[grpc.Server] = None
        self.port = 0
        self._rr = 0

    # -- rpc service -----------------------------------------------------
    def _service(self):
        def register(request: pb.RegisterWorkerRequest, context):
            self.handle.send(("register", request))
            return pb.RegisterWorkerResponse(accepted=True,
                                             driver_id=self.driver_id)

        def heartbeat(request: pb.HeartbeatRequest, context):
            self.handle.send(("heartbeat", request))
            return pb.HeartbeatResponse(known=True)

        def report(request: pb.ReportTaskStatusRequest, context):
            self.handle.send(("task_status", request))
            return pb.ReportTaskStatusResponse()

        return grpc.method_handlers_generic_handler(_DRIVER_SERVICE, {
            "RegisterWorker": _unary(register, pb.RegisterWorkerRequest),
            "Heartbeat": _unary(heartbeat, pb.HeartbeatRequest),
            "ReportTaskStatus": _unary(report, pb.ReportTaskStatusRequest),
        })

    def on_start(self):
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        self._server.add_generic_rpc_handlers((self._service(),))
        self.port = self._server.add_insecure_port("127.0.0.1:0")
        self._server.start()
        threading.Thread(target=self._probe_loop, daemon=True).start()

    def on_stop(self):
        if self._server is not None:
            self._server.stop(grace=0.5)

    def _probe_loop(self):
        while True:
            time.sleep(2.0)
            self.handle.send(("probe", None))

    # -- actor -----------------------------------------------------------
    def receive(self, message):
        kind, payload = message
        if kind == "register":
            r: pb.RegisterWorkerRequest = payload
            self.workers[r.worker_id] = {
                "addr": f"{r.host}:{r.port}", "slots": r.task_slots,
                "last_seen": time.time(),
                "channel": grpc.insecure_channel(f"{r.host}:{r.port}"),
                "tasks": set(),
            }
        elif kind == "heartbeat":
            w = self.workers.get(payload.worker_id)
            if w is not None:
                w["last_seen"] = time.time()
        elif kind == "probe":
            self._probe_workers()
        elif kind == "submit":
            job, reply = payload
            self.jobs[job.job_id] = job
            self._schedule_leaf_tasks(job)
            if reply is not None:
                reply.set(job)
        elif kind == "task_status":
            self._on_task_status(payload)

    def _probe_workers(self):
        now = time.time()
        lost = [wid for wid, w in self.workers.items()
                if now - w["last_seen"] > self.HEARTBEAT_TIMEOUT_S]
        for wid in lost:
            w = self.workers.pop(wid)
            # reschedule that worker's running tasks
            for (job_id, stage, partition) in list(w["tasks"]):
                job = self.jobs.get(job_id)
                if job is not None and not job.done.is_set():
                    self._launch_task(job, partition,
                                      job.attempts.get(partition, 0) + 1)

    def _schedule_leaf_tasks(self, job: _Job):
        leaf = job.graph.stages[0]
        for partition in range(leaf.num_partitions):
            self._launch_task(job, partition, 0)

    def _launch_task(self, job: _Job, partition: int, attempt: int):
        if attempt >= self.MAX_TASK_ATTEMPTS:
            job.failed = f"task {partition} exceeded max attempts"
            job.done.set()
            return
        live = list(self.workers.items())
        if not live:
            job.failed = "no live workers"
            job.done.set()
            return
        self._rr = (self._rr + 1) % len(live)
        wid, w = live[self._rr]
        job.attempts[partition] = attempt
        leaf = job.graph.stages[0]
        plan_bytes, table_ipc = jg.encode_fragment(leaf.plan)
        task = pb.TaskDefinition(job_id=job.job_id, stage=0,
                                 partition=partition, attempt=attempt,
                                 plan=plan_bytes,
                                 scan_table=table_ipc or b"",
                                 num_partitions=job.graph.stages[0].num_partitions)
        w["tasks"].add((job.job_id, 0, partition))
        rpc = w["channel"].unary_unary(
            f"/{_WORKER_SERVICE}/RunTask",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.RunTaskResponse.FromString)
        try:
            rpc(pb.RunTaskRequest(task=task), timeout=30)
        except grpc.RpcError:
            # dispatch failure = dead worker: evict immediately and redo the
            # SAME attempt elsewhere (a launch failure is not a task failure)
            self.workers.pop(wid, None)
            self._launch_task(job, partition, attempt)

    def _on_task_status(self, r: pb.ReportTaskStatusRequest):
        job = self.jobs.get(r.job_id)
        if job is None or job.done.is_set():
            return
        w = self.workers.get(r.worker_id)
        if r.state in ("succeeded", "failed", "canceled") and w is not None:
            w["tasks"].discard((r.job_id, r.stage, r.partition))
        if r.state == "succeeded":
            if r.attempt == job.attempts.get(r.partition, 0):
                job.results[r.partition] = r.result
                leaf = job.graph.stages[0]
                if len(job.results) == leaf.num_partitions:
                    job.done.set()
        elif r.state == "failed":
            self._launch_task(job, r.partition, r.attempt + 1)


# ---------------------------------------------------------------------------
# Local-cluster runner (the reference's local-cluster mode / test vehicle)
# ---------------------------------------------------------------------------

class LocalCluster:
    def __init__(self, num_workers: int = 2, task_slots: int = 2):
        self.driver = DriverActor()
        self.driver.start("driver")
        # wait for the driver's server port
        deadline = time.time() + 10
        while self.driver.port == 0 and time.time() < deadline:
            time.sleep(0.01)
        self.workers: List[WorkerActor] = []
        for i in range(num_workers):
            w = WorkerActor(f"worker-{i}", f"127.0.0.1:{self.driver.port}",
                            task_slots)
            w.start(f"worker-{i}")
            self.workers.append(w)
        deadline = time.time() + 10
        while len(self.driver.workers) < num_workers and time.time() < deadline:
            time.sleep(0.02)

    def run_job(self, plan, num_partitions: Optional[int] = None, timeout=120):
        """Distribute a plan; returns the result pyarrow Table."""
        import pyarrow as pa
        from ..columnar import arrow_interop as ai
        from .local import LocalExecutor

        nparts = num_partitions or max(1, len(self.workers))
        graph = jg.split_job(plan, nparts)
        if graph is None:
            return LocalExecutor().execute(plan)
        job = _Job(uuid.uuid4().hex[:12], graph)
        self.driver.handle.ask(lambda reply: ("submit", (job, reply)))
        if not job.done.wait(timeout):
            raise TimeoutError("cluster job timed out")
        if job.failed:
            raise RuntimeError(f"cluster job failed: {job.failed}")
        parts = []
        for i in range(nparts):
            buf = job.results[i]
            parts.append(pa.ipc.open_stream(buf).read_all())
        merged = pa.concat_tables(parts, promote_options="permissive")
        # run the root stage locally over the merged leaf output
        root = graph.root
        root_plan = _attach_stage_input(root.plan, merged)
        return LocalExecutor().execute(root_plan)

    def stop(self):
        for w in self.workers:
            w.stop()
        self.driver.stop()


def _attach_stage_input(plan, table):
    import dataclasses as dc
    from ..plan import nodes as pn

    def replace(p):
        if isinstance(p, jg._StageInput):
            return pn.ScanExec(tuple(p.schema), table, (), "memory")
        if isinstance(p, pn.JoinExec):
            return dc.replace(p, left=replace(p.left), right=replace(p.right))
        if isinstance(p, pn.UnionExec):
            return dc.replace(p, inputs=tuple(replace(c) for c in p.inputs))
        if hasattr(p, "input") and p.input is not None:
            return dc.replace(p, input=replace(p.input))
        return p

    return replace(plan)
