"""Driver/worker cluster runtime over gRPC with a peer stream data plane.

Reference role: sail-execution's DriverActor/WorkerActor, worker pool with
heartbeats, stage scheduler with retry, the WorkerService/DriverService
RPCs, and the task-stream data plane
(crates/sail-execution/src/driver/, src/worker/, src/stream_service/ —
SURVEY.md §2.5/§3.3). Shape:

- the driver schedules stages in dependency order; tasks are assigned to
  the least-loaded live workers; per-task attempts with retry; heartbeat
  timeout eviction reschedules a lost worker's tasks.
- workers execute plan fragments on the local (jax) executor, hash-route
  shuffle outputs into channels, and serve them to PEERS over a
  FetchStream RPC (Arrow IPC) — results no longer ride task reports.
- memory-table scans are served by the DRIVER's stream service and sliced
  per task, so a stage ships the table at most once per consuming task's
  slice (not whole-table × partitions).
- local-cluster mode (the reference's test vehicle) runs driver + workers
  as threads speaking real gRPC over localhost.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from concurrent import futures
from typing import Dict, List, Optional, Set, Tuple

import grpc

from .proto import control_plane_pb2 as pb

from .actor import Actor
from . import job_graph as jg
from .. import tracing as tr
from ..metrics import record as _record_metric

_DRIVER_SERVICE = "sail_tpu.control.DriverService"
_WORKER_SERVICE = "sail_tpu.control.WorkerService"


def _unary(fn, req_cls):
    return grpc.unary_unary_rpc_method_handler(
        fn, request_deserializer=req_cls.FromString,
        response_serializer=lambda m: m.SerializeToString())


def _table_to_ipc(table) -> bytes:
    import pyarrow as pa
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    return sink.getvalue().to_pybytes()


def _ipc_to_table(buf: bytes):
    import pyarrow as pa
    return pa.ipc.open_stream(buf).read_all()


class _StreamStore:
    """Task output channels served over FetchStream, with disk spill.

    Reference role: the stream storage behind TaskStreamFlightServer
    (src/stream_manager/) + TaskWriteLocation::Local{Memory|Disk}
    (src/stream/writer.rs:11-29): channels stay in memory up to a cap;
    beyond it they spill to a per-store temp directory and are served
    from disk."""

    def __init__(self, memory_cap_bytes: Optional[int] = None):
        from ..config import get as config_get
        if memory_cap_bytes is None:
            memory_cap_bytes = int(config_get(
                "cluster.shuffle_memory_cap_mb", 256)) << 20
        self._cap = memory_cap_bytes
        self._mem_bytes = 0
        self._streams: Dict[Tuple[str, int, int], Dict[int, object]] = {}
        self._lock = threading.Lock()
        self._spill_dir: Optional[str] = None
        self.spill_count = 0

    def _spill_path(self, job_id: str, stage: int, partition: int,
                    channel: int) -> str:
        import tempfile
        if self._spill_dir is None:
            self._spill_dir = tempfile.mkdtemp(prefix="sail_shuffle_")
        return os.path.join(
            self._spill_dir, f"{job_id}_{stage}_{partition}_{channel}.ipc")

    def put(self, job_id: str, stage: int, partition: int,
            channels: Dict[int, bytes]):
        with self._lock:
            # a task retry can overwrite a previous attempt's entry:
            # release its memory/disk accounting first
            prev = self._streams.pop((job_id, stage, partition), None)
            if prev is not None:
                for entry in prev.values():
                    if isinstance(entry, tuple):
                        try:
                            os.unlink(entry[1])
                        except OSError:
                            pass
                    else:
                        self._mem_bytes -= len(entry)
            stored: Dict[int, object] = {}
            for c, buf in channels.items():
                if self._mem_bytes + len(buf) > self._cap:
                    path = self._spill_path(job_id, stage, partition, c)
                    with open(path, "wb") as f:
                        f.write(buf)
                    stored[c] = ("disk", path)
                    self.spill_count += 1
                    _record_metric("execution.spill_count", 1,
                                   kind="shuffle")
                else:
                    self._mem_bytes += len(buf)
                    stored[c] = buf
            self._streams[(job_id, stage, partition)] = stored

    def get(self, job_id: str, stage: int, partition: int,
            channel: int) -> Optional[bytes]:
        with self._lock:
            chans = self._streams.get((job_id, stage, partition))
            entry = None if chans is None else chans.get(channel)
        if entry is None:
            return None
        if isinstance(entry, tuple):
            try:
                with open(entry[1], "rb") as f:
                    return f.read()
            except FileNotFoundError:
                # raced clean_job's unlink — behave as channel-not-found so
                # the fetch retry path (NOT_FOUND) handles it
                return None
        return entry

    def clean_job(self, job_id: str):
        with self._lock:
            for key in [k for k in self._streams if k[0] == job_id]:
                for entry in self._streams[key].values():
                    if isinstance(entry, tuple):
                        try:
                            os.unlink(entry[1])
                        except OSError:
                            pass
                    else:
                        self._mem_bytes -= len(entry)
                del self._streams[key]


_FETCH_CHUNK_BYTES = 1 << 20


def _task_metrics_enabled() -> bool:
    """Workers collect per-operator metrics for every task unless
    ``cluster.task_metrics`` turns it off (the collection forces one
    device sync per operator)."""
    from ..config import get as config_get
    return str(config_get("cluster.task_metrics", "true")) \
        .strip().lower() not in ("0", "false", "no", "off")


def _fetch_stream_handler(store: _StreamStore, scan_tables=None):
    """Server-streaming fetch: the channel's IPC bytes stream as bounded
    chunks — no gRPC message-size cap, no full-buffer single message on
    the wire (reference: stream_service/server.rs record-batch streams)."""

    def fetch(request: pb.FetchStreamRequest, context):
        if request.scan_id:
            tables = scan_tables() if scan_tables is not None else {}
            entry = tables.get((request.job_id, request.scan_id))
            if entry is None:
                context.abort(grpc.StatusCode.NOT_FOUND,
                              f"unknown scan {request.scan_id}")
            n = entry.num_rows
            nparts = max(request.num_partitions, 1)
            per = -(-n // nparts) if n else 0
            part = entry.slice(request.partition * per, per) if per \
                else entry.slice(0, 0)
            buf = _table_to_ipc(part)
        else:
            buf = store.get(request.job_id, request.stage,
                            request.partition, request.channel)
            if buf is None:
                context.abort(
                    grpc.StatusCode.NOT_FOUND,
                    f"no stream for job={request.job_id} "
                    f"stage={request.stage} "
                    f"partition={request.partition} "
                    f"channel={request.channel}")
        for off in range(0, max(len(buf), 1), _FETCH_CHUNK_BYTES):
            chunk = buf[off:off + _FETCH_CHUNK_BYTES]
            yield pb.FetchChunk(data=chunk,
                                last=off + _FETCH_CHUNK_BYTES >= len(buf))

    return fetch


def _fetch_from(addr: str, req: pb.FetchStreamRequest, service: str,
                timeout: float = 120.0) -> bytes:
    channel = grpc.insecure_channel(addr)
    try:
        rpc = channel.unary_stream(
            f"/{service}/FetchStream",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.FetchChunk.FromString)
        parts = [chunk.data for chunk in
                 rpc(req, timeout=timeout, metadata=tr.inject_context())]
        return b"".join(parts)
    finally:
        channel.close()


# ---------------------------------------------------------------------------
# Worker
# ---------------------------------------------------------------------------

class WorkerActor(Actor):
    def __init__(self, worker_id: str, driver_addr: str, task_slots: int = 2,
                 host: str = "127.0.0.1", advertise_host: Optional[str] = None):
        super().__init__()
        self.worker_id = worker_id
        self.driver_addr = driver_addr
        self.task_slots = task_slots
        self.host = host
        # the address peers/driver dial; differs from the bind address when
        # binding 0.0.0.0 in a pod (reference kubernetes.rs: pod IP)
        self.advertise_host = advertise_host or host
        self.port = 0
        self._server: Optional[grpc.Server] = None
        self._driver_channel: Optional[grpc.Channel] = None
        self._running: Dict[Tuple[str, int, int], threading.Event] = {}
        self._pool = futures.ThreadPoolExecutor(max_workers=task_slots)
        self._hb_stop = threading.Event()
        self.streams = _StreamStore()

    # -- rpc service -----------------------------------------------------
    def _service(self):
        def run_task(request: pb.RunTaskRequest, context):
            parent = tr.extract_context(context.invocation_metadata())
            self.handle.send(("run_task", (request.task, parent)))
            return pb.RunTaskResponse(accepted=True)

        def stop_task(request: pb.StopTaskRequest, context):
            key = (request.job_id, request.stage, request.partition)
            ev = self._running.get(key)
            if ev is not None:
                ev.set()  # cooperative cancel: checked between pipeline steps
            return pb.StopTaskResponse(stopped=ev is not None)

        def clean_up_job(request: pb.CleanUpJobRequest, context):
            self.streams.clean_job(request.job_id)
            for key in [k for k in self._running
                        if k[0] == request.job_id]:
                self._running[key].set()
            return pb.CleanUpJobResponse()

        return grpc.method_handlers_generic_handler(_WORKER_SERVICE, {
            "RunTask": _unary(run_task, pb.RunTaskRequest),
            "StopTask": _unary(stop_task, pb.StopTaskRequest),
            "CleanUpJob": _unary(clean_up_job, pb.CleanUpJobRequest),
            "FetchStream": grpc.unary_stream_rpc_method_handler(
                _fetch_stream_handler(self.streams),
                request_deserializer=pb.FetchStreamRequest.FromString,
                response_serializer=lambda m: m.SerializeToString()),
        })

    def on_start(self):
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        self._server.add_generic_rpc_handlers((self._service(),))
        self.port = self._server.add_insecure_port(f"{self.host}:0")
        self._server.start()
        self._driver_channel = grpc.insecure_channel(self.driver_addr)
        resp = self._call_driver("RegisterWorker", pb.RegisterWorkerRequest(
            worker_id=self.worker_id, host=self.advertise_host,
            port=self.port,
            task_slots=self.task_slots), pb.RegisterWorkerResponse)
        if not resp.accepted:
            raise RuntimeError("driver rejected worker registration")
        threading.Thread(target=self._heartbeat_loop, daemon=True).start()

    def on_stop(self):
        self._hb_stop.set()
        if self._server is not None:
            self._server.stop(grace=0.5)

    def _call_driver(self, method: str, msg, resp_cls):
        rpc = self._driver_channel.unary_unary(
            f"/{_DRIVER_SERVICE}/{method}",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=resp_cls.FromString)
        return rpc(msg, timeout=30, metadata=tr.inject_context())

    def _heartbeat_loop(self):
        while not self._hb_stop.wait(1.0):
            try:
                self._call_driver("Heartbeat", pb.HeartbeatRequest(
                    worker_id=self.worker_id,
                    running_tasks=len(self._running)), pb.HeartbeatResponse)
            except grpc.RpcError:
                pass

    # -- actor -----------------------------------------------------------
    def receive(self, message):
        kind, payload = message
        if kind == "run_task":
            task, parent = payload
            key = (task.job_id, task.stage, task.partition)
            self._running[key] = threading.Event()
            self._pool.submit(self._run_task, task, parent)

    # -- task execution --------------------------------------------------
    def _fetch_inputs(self, task: pb.TaskDefinition):
        """Pull upstream stage outputs over the peer data plane."""
        import pyarrow as pa

        tables: Dict[int, object] = {}
        for inp in task.inputs:
            parts = []
            addrs = list(inp.worker_addrs)
            if inp.mode == "shuffle":
                wanted = [(i, task.partition) for i in range(len(addrs))]
            elif inp.mode == "forward":
                wanted = [(task.partition, -1)]
                addrs = [addrs[task.partition]]
            else:  # merge | broadcast: everything from every producer
                wanted = [(i, -1) for i in range(len(addrs))]
            for (up_part, chan), addr in zip(wanted, addrs):
                try:
                    buf = _fetch_from(addr, pb.FetchStreamRequest(
                        job_id=task.job_id, stage=inp.stage_id,
                        partition=up_part, channel=chan), _WORKER_SERVICE)
                except grpc.RpcError as e:
                    raise _FetchFailed(inp.stage_id, up_part) from e
                parts.append(_ipc_to_table(buf))
            tables[inp.stage_id] = pa.concat_tables(
                parts, promote_options="permissive") if len(parts) > 1 \
                else parts[0]
        return tables

    def _run_task(self, task: pb.TaskDefinition, parent=None):
        from .local import LocalExecutor
        key = (task.job_id, task.stage, task.partition)
        with tr.span(f"worker:task s{task.stage}p{task.partition}",
                     {"job_id": task.job_id, "stage": task.stage,
                      "partition": task.partition,
                      "worker": self.worker_id}, parent=parent):
            self._run_task_inner(task, key)

    def _run_task_inner(self, task: pb.TaskDefinition, key):
        from .local import LocalExecutor
        try:
            self._report(task, "running")
            plan = jg.decode_fragment(task.plan, task.partition,
                                      max(task.num_partitions, 1))
            plan = _resolve_driver_scans(plan, task)
            if task.runtime_filters_json:
                # driver-derived runtime join filters: prune this task's
                # scan before upload/shuffle (applied before stage inputs
                # attach so scan ordinals match the driver's counting)
                plan = jg.apply_task_runtime_filters(
                    plan, task.runtime_filters_json)
            if task.inputs:
                plan = jg.attach_stage_inputs(plan, self._fetch_inputs(task))
            if self._running.get(key, threading.Event()).is_set():
                self._report(task, "canceled")
                return
            metrics_json = ""
            if _task_metrics_enabled():
                # per-operator metrics ride the success report so the
                # driver's query profile sees below the stage boundary
                import json as _json

                from .. import telemetry as tel
                with tel.collect_metrics() as collector:
                    table = LocalExecutor().execute(plan)
                try:
                    metrics_json = _json.dumps(
                        [m.to_dict() for m in collector])
                except (TypeError, ValueError):
                    metrics_json = ""
            else:
                table = LocalExecutor().execute(plan)
            if task.HasField("shuffle_write") and \
                    task.shuffle_write.num_channels > 1:
                # shuffle consumers only ever fetch hash channels — do not
                # retain a second full copy of the output
                sw = task.shuffle_write
                parts = jg.hash_partition_table(
                    table, list(sw.key_columns), sw.num_channels)
                channels: Dict[int, bytes] = {
                    c: _table_to_ipc(part) for c, part in enumerate(parts)}
            else:
                channels = {-1: _table_to_ipc(table)}
            self.streams.put(task.job_id, task.stage, task.partition,
                             channels)
            self._report(task, "succeeded", rows=table.num_rows,
                         metrics_json=metrics_json)
        except _FetchFailed as e:
            # a producer's streams are gone (dead peer): the driver re-runs
            # the producer and re-schedules this task, not as our failure
            self._report(task, "failed",
                         error=f"FETCH_FAILED:{e.stage_id}:{e.partition}")
        except Exception as e:  # noqa: BLE001 — full cause goes to the driver
            self._report(task, "failed", error=f"{type(e).__name__}: {e}")
        finally:
            self._running.pop(key, None)

    def _report(self, task: pb.TaskDefinition, state: str, error: str = "",
                rows: int = 0, metrics_json: str = ""):
        try:
            self._call_driver("ReportTaskStatus", pb.ReportTaskStatusRequest(
                worker_id=self.worker_id, job_id=task.job_id,
                stage=task.stage, partition=task.partition,
                attempt=task.attempt, state=state, error=error,
                rows_out=rows, metrics_json=metrics_json),
                pb.ReportTaskStatusResponse)
        except grpc.RpcError:
            pass


def _reattach_local_scans(plan, scan_tables):
    import dataclasses as dc
    from ..plan import nodes as pn

    def repl(p):
        if isinstance(p, pn.ScanExec) and p.format == "__driver__":
            return dc.replace(p, source=scan_tables[p.table_name],
                              format="memory", table_name="")
        if isinstance(p, pn.JoinExec):
            return dc.replace(p, left=repl(p.left), right=repl(p.right))
        if isinstance(p, pn.UnionExec):
            return dc.replace(p, inputs=tuple(repl(c) for c in p.inputs))
        if hasattr(p, "input") and p.input is not None:
            return dc.replace(p, input=repl(p.input))
        return p

    return repl(plan)


class _FetchFailed(Exception):
    def __init__(self, stage_id: int, partition: int):
        super().__init__(f"stage {stage_id} partition {partition}")
        self.stage_id = stage_id
        self.partition = partition


def _resolve_driver_scans(plan, task: pb.TaskDefinition):
    """Fetch this task's slice of driver-hosted memory tables."""
    import dataclasses as dc
    from ..plan import nodes as pn

    def repl(p):
        if isinstance(p, pn.ScanExec) and p.format == "__driver__":
            buf = _fetch_from(task.driver_addr, pb.FetchStreamRequest(
                job_id=task.job_id, scan_id=p.table_name,
                partition=task.partition,
                num_partitions=max(task.num_partitions, 1)),
                _DRIVER_SERVICE)
            return dc.replace(p, source=_ipc_to_table(buf), format="memory",
                              table_name="")
        if isinstance(p, pn.JoinExec):
            return dc.replace(p, left=repl(p.left), right=repl(p.right))
        if isinstance(p, pn.UnionExec):
            return dc.replace(p, inputs=tuple(repl(c) for c in p.inputs))
        if hasattr(p, "input") and p.input is not None:
            return dc.replace(p, input=repl(p.input))
        return p

    return repl(plan)


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------

class _Job:
    def __init__(self, job_id: str, graph: jg.JobGraph,
                 trace_ctx=None):
        self.job_id = job_id
        self.graph = graph
        self.trace_ctx = trace_ctx
        self.failed: Optional[str] = None
        self.done = threading.Event()
        # per stage: partition → worker addr (set on success)
        self.locations: Dict[int, Dict[int, str]] = {
            s.stage_id: {} for s in graph.stages}
        self.attempts: Dict[Tuple[int, int], int] = {}
        self.last_error: str = ""
        self.scheduled: Set[int] = set()
        # per-partition launches for pipelined (FORWARD-input) stages
        self.launched: Set[Tuple[int, int]] = set()
        # consumer tasks waiting for a producer re-run after a fetch failure
        self.pending: Set[Tuple[int, int]] = set()
        self.stage_rows: Dict[int, int] = {}
        # per-{stage, partition} operator metrics from the winning task
        # attempt: {"worker_id", "rows_out", "operators": [...]}
        self.task_metrics: Dict[Tuple[int, int], dict] = {}
        self.result_addr: Optional[str] = None


class DriverActor(Actor):
    HEARTBEAT_TIMEOUT_S = 10.0
    MAX_TASK_ATTEMPTS = 3

    def __init__(self, host: str = "127.0.0.1"):
        super().__init__()
        self.host = host
        self.driver_id = uuid.uuid4().hex[:8]
        self.workers: Dict[str, dict] = {}
        self.jobs: Dict[str, _Job] = {}
        self._server: Optional[grpc.Server] = None
        self.port = 0
        self._probe_stop = threading.Event()
        self.streams = _StreamStore()  # (unused for now; driver-run roots)
        # elastic pool (reference: driver/worker_pool/ scale between
        # initial and max counts with idle reaping)
        self.elastic: Optional[dict] = None
        self._starting = 0
        self._starting_ts: List[float] = []

    def set_elastic(self, manager, min_workers: int = 1,
                    max_workers: int = 4, idle_secs: float = 60.0):
        """Enable demand-driven scale-up (saturated slots → new worker)
        and idle reaping down to ``min_workers``."""
        self.elastic = {"manager": manager, "min": min_workers,
                        "max": max_workers, "idle": idle_secs}

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    # -- rpc service -----------------------------------------------------
    def _scan_tables_view(self):
        out = {}
        # snapshot: gRPC handler threads race the actor thread on self.jobs
        for job in list(self.jobs.values()):
            for sid, table in job.graph.scan_tables.items():
                out[(job.job_id, sid)] = table
        return out

    def _service(self):
        def register(request: pb.RegisterWorkerRequest, context):
            self.handle.send(("register", request))
            return pb.RegisterWorkerResponse(accepted=True,
                                             driver_id=self.driver_id)

        def heartbeat(request: pb.HeartbeatRequest, context):
            self.handle.send(("heartbeat", request))
            return pb.HeartbeatResponse(known=True)

        def report(request: pb.ReportTaskStatusRequest, context):
            self.handle.send(("task_status", request))
            return pb.ReportTaskStatusResponse()

        return grpc.method_handlers_generic_handler(_DRIVER_SERVICE, {
            "RegisterWorker": _unary(register, pb.RegisterWorkerRequest),
            "Heartbeat": _unary(heartbeat, pb.HeartbeatRequest),
            "ReportTaskStatus": _unary(report, pb.ReportTaskStatusRequest),
            "FetchStream": grpc.unary_stream_rpc_method_handler(
                _fetch_stream_handler(self.streams, self._scan_tables_view),
                request_deserializer=pb.FetchStreamRequest.FromString,
                response_serializer=lambda m: m.SerializeToString()),
        })

    def on_start(self):
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        self._server.add_generic_rpc_handlers((self._service(),))
        self.port = self._server.add_insecure_port(f"{self.host}:0")
        self._server.start()
        threading.Thread(target=self._probe_loop, daemon=True).start()

    def on_stop(self):
        self._probe_stop.set()
        if self._server is not None:
            self._server.stop(grace=0.5)

    def _probe_loop(self):
        while not self._probe_stop.wait(2.0):
            try:
                self.handle.send(("probe", None))
            except Exception:  # noqa: BLE001 — actor stopped
                return

    # -- actor -----------------------------------------------------------
    def receive(self, message):
        kind, payload = message
        if kind == "register":
            r: pb.RegisterWorkerRequest = payload
            from ..catalog.system import SYSTEM
            SYSTEM.record_worker(r.worker_id, f"{r.host}:{r.port}",
                                 r.task_slots, "alive")
            self.workers[r.worker_id] = {
                "addr": f"{r.host}:{r.port}", "slots": r.task_slots,
                "last_seen": time.time(),
                "channel": grpc.insecure_channel(f"{r.host}:{r.port}"),
                "tasks": set(),
                "idle_since": time.time(),
            }
            if self._starting_ts:
                self._starting_ts.pop(0)
            self._starting = len(self._starting_ts)
            _record_metric("cluster.worker_count", len(self.workers))
        elif kind == "heartbeat":
            w = self.workers.get(payload.worker_id)
            if w is not None:
                w["last_seen"] = time.time()
        elif kind == "probe":
            self._probe_workers()
        elif kind == "submit":
            job, reply = payload
            self.jobs[job.job_id] = job
            from ..catalog.system import SYSTEM
            SYSTEM.record_job(job.job_id, len(job.graph.stages), "running")
            self._schedule_ready_stages(job)
            if reply is not None:
                reply.set(job)
        elif kind == "task_status":
            self._on_task_status(payload)
        elif kind == "cleanup":
            self._cleanup_job(payload)

    def _maybe_scale_up(self):
        e = self.elastic
        # prune pending starts that never registered (crashed at startup)
        # so a failed spawn can't cap the pool below max forever
        now = time.time()
        self._starting_ts = [t for t in self._starting_ts
                             if now - t < 30.0]
        self._starting = len(self._starting_ts)
        if len(self.workers) + self._starting >= e["max"]:
            return
        try:
            e["manager"].start_worker()
            self._starting_ts.append(now)
            self._starting += 1
        except Exception:  # noqa: BLE001 — scale-up is best effort
            pass

    def _worker_hosts_live_output(self, addr: str) -> bool:
        for job in self.jobs.values():
            if job.done.is_set():
                continue
            for locs in job.locations.values():
                if any(a == addr for a in locs.values()):
                    return True
        return False

    def _reap_idle_workers(self, now: float):
        e = self.elastic
        owns = getattr(e["manager"], "owns", None)
        stop = getattr(e["manager"], "stop_worker_id", None)
        for wid in list(self.workers):
            if len(self.workers) <= e["min"]:
                return
            w = self.workers[wid]
            idle = w.get("idle_since")
            if w["tasks"] or idle is None or now - idle < e["idle"]:
                continue
            # never strand a worker the manager can't actually stop, and
            # never kill completed stage outputs an active job still needs
            if owns is not None and not owns(wid):
                continue
            if self._worker_hosts_live_output(w["addr"]):
                continue
            self.workers.pop(wid)
            _record_metric("cluster.worker_count", len(self.workers))
            from ..catalog.system import SYSTEM
            SYSTEM.record_worker(wid, w["addr"], w["slots"], "reaped")
            if stop is not None:
                try:
                    stop(wid)
                except Exception:  # noqa: BLE001
                    pass

    def _probe_workers(self):
        now = time.time()
        if self.elastic is not None:
            self._reap_idle_workers(now)
        lost = [wid for wid, w in self.workers.items()
                if now - w["last_seen"] > self.HEARTBEAT_TIMEOUT_S]
        if lost:
            _record_metric("cluster.worker_count",
                           len(self.workers) - len(lost))
        for wid in lost:
            w = self.workers.pop(wid)
            # re-run the lost worker's RUNNING tasks
            for (job_id, stage, partition) in list(w["tasks"]):
                job = self.jobs.get(job_id)
                if job is not None and not job.done.is_set():
                    att = self.attempt_of(job, stage, partition) + 1
                    self._launch_task(job, stage, partition, att)
            # its COMPLETED stream outputs are gone too: invalidate their
            # locations and re-run those producer partitions
            for job in list(self.jobs.values()):
                if job.done.is_set():
                    continue
                for stage_id, locs in job.locations.items():
                    dead = [p for p, a in locs.items() if a == w["addr"]]
                    for p in dead:
                        del locs[p]
                        # re-run whether the stage was launched whole
                        # (scheduled) or per-partition (pipelined)
                        if stage_id in job.scheduled or \
                                (stage_id, p) in job.launched:
                            att = self.attempt_of(job, stage_id, p) + 1
                            self._launch_task(job, stage_id, p, att)

    @staticmethod
    def attempt_of(job: _Job, stage: int, partition: int) -> int:
        return job.attempts.get((stage, partition), 0)

    # -- scheduling ------------------------------------------------------
    def _stage_complete(self, job: _Job, stage_id: int) -> bool:
        stage = job.graph.stages[stage_id]
        return len(job.locations[stage_id]) >= stage.num_partitions

    def _partition_ready(self, job: _Job, stage, partition: int) -> bool:
        """FORWARD inputs need only the matching upstream partition; all
        other modes need the whole upstream stage (reference: the
        reference's OutputMode::Pipelined + task regions — consumer tasks
        co-run with still-executing producer stages)."""
        for i in stage.inputs:
            if i.mode == jg.InputMode.FORWARD:
                if partition not in job.locations[i.stage_id]:
                    return False
            elif not self._stage_complete(job, i.stage_id):
                return False
        return True

    def _schedule_ready_stages(self, job: _Job):
        for stage in job.graph.stages:
            if stage.on_driver:
                continue
            pipelined = any(i.mode == jg.InputMode.FORWARD
                            for i in stage.inputs)
            if pipelined:
                for partition in range(stage.num_partitions):
                    key = (stage.stage_id, partition)
                    if key in job.launched:
                        continue
                    if self._partition_ready(job, stage, partition):
                        job.launched.add(key)
                        self._launch_task(job, stage.stage_id, partition, 0)
                continue
            if stage.stage_id in job.scheduled:
                continue
            if all(self._stage_complete(job, i.stage_id)
                   for i in stage.inputs):
                job.scheduled.add(stage.stage_id)
                for partition in range(stage.num_partitions):
                    self._launch_task(job, stage.stage_id, partition, 0)
        root = job.graph.root
        if root.on_driver and not job.done.is_set() and \
                all(self._stage_complete(job, i.stage_id)
                    for i in root.inputs):
            job.done.set()

    def _launch_task(self, job: _Job, stage_id: int, partition: int,
                     attempt: int):
        if attempt >= self.MAX_TASK_ATTEMPTS:
            job.failed = (f"stage {stage_id} task {partition} exceeded "
                          f"max attempts: {job.last_error}")
            job.done.set()
            return
        live = sorted(self.workers.items(),
                      key=lambda kv: len(kv[1]["tasks"]))
        if not live:
            job.failed = "no live workers"
            job.done.set()
            return
        wid, w = live[0]
        if self.elastic is not None and len(w["tasks"]) >= w["slots"]:
            self._maybe_scale_up()
        stage = job.graph.stages[stage_id]
        job.attempts[(stage_id, partition)] = attempt
        inputs = []
        for i in stage.inputs:
            up = job.graph.stages[i.stage_id]
            # pipelined FORWARD consumers launch before sibling upstream
            # partitions finish; only THIS task's partition must resolve
            addrs = [job.locations[i.stage_id].get(p, "")
                     for p in range(up.num_partitions)]
            if i.mode == jg.InputMode.FORWARD:
                if not addrs[partition]:
                    job.failed = (f"stage {stage_id} p{partition}: forward "
                                  f"input {i.stage_id} not located")
                    job.done.set()
                    return
            elif not all(addrs):
                job.failed = (f"stage {stage_id}: input stage {i.stage_id} "
                              f"incomplete at launch")
                job.done.set()
                return
            inputs.append(pb.StageInputLocations(
                stage_id=i.stage_id, mode=i.mode.value, worker_addrs=addrs))
        task = pb.TaskDefinition(
            job_id=job.job_id, stage=stage_id, partition=partition,
            attempt=attempt, plan=encode_cached(job, stage),
            num_partitions=stage.num_partitions, inputs=inputs,
            driver_addr=self.addr,
            runtime_filters_json=job.graph.stage_filters.get(stage_id, ""))
        if stage.shuffle_keys is not None and stage.num_channels > 1:
            task.shuffle_write.CopyFrom(pb.ShuffleWriteSpec(
                key_columns=list(stage.shuffle_keys),
                num_channels=stage.num_channels))
        w["tasks"].add((job.job_id, stage_id, partition))
        w["idle_since"] = None
        rpc = w["channel"].unary_unary(
            f"/{_WORKER_SERVICE}/RunTask",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.RunTaskResponse.FromString)
        try:
            with tr.span(f"driver:launch s{stage_id}p{partition}",
                         {"job_id": job.job_id, "worker": wid},
                         parent=job.trace_ctx) as ls:
                rpc(pb.RunTaskRequest(task=task), timeout=30,
                    metadata=[("traceparent",
                               f"00-{ls.trace_id}-{ls.span_id}-01")])
        except grpc.RpcError:
            # dispatch failure = dead worker: evict immediately and redo the
            # SAME attempt elsewhere (a launch failure is not a task failure)
            self.workers.pop(wid, None)
            self._launch_task(job, stage_id, partition, attempt)

    def _on_task_status(self, r: pb.ReportTaskStatusRequest):
        from ..catalog.system import SYSTEM
        SYSTEM.record_task(r.job_id, r.stage, r.partition, r.attempt,
                           r.state, r.worker_id, int(r.rows_out))
        job = self.jobs.get(r.job_id)
        if job is None or job.done.is_set():
            return
        w = self.workers.get(r.worker_id)
        if r.state in ("succeeded", "failed", "canceled") and w is not None:
            w["tasks"].discard((r.job_id, r.stage, r.partition))
            if not w["tasks"]:
                w["idle_since"] = time.time()
        if r.state == "succeeded":
            if w is None:
                # the worker was evicted before its success report arrived;
                # its streams died with it — run the task again elsewhere
                self._launch_task(job, r.stage, r.partition,
                                  self.attempt_of(job, r.stage,
                                                  r.partition) + 1)
                return
            if r.attempt == self.attempt_of(job, r.stage, r.partition):
                job.locations[r.stage][r.partition] = w["addr"]
                job.stage_rows[r.stage] = \
                    job.stage_rows.get(r.stage, 0) + int(r.rows_out)
                if r.metrics_json:
                    try:
                        import json as _json
                        job.task_metrics[(r.stage, r.partition)] = {
                            "worker_id": r.worker_id,
                            "rows_out": int(r.rows_out),
                            "operators": _json.loads(r.metrics_json)}
                    except ValueError:
                        pass  # malformed metrics never fail a task
                self._fire_pending(job)
                self._schedule_ready_stages(job)
        elif r.state == "failed":
            if r.error.startswith("FETCH_FAILED:"):
                _, s, p = r.error.split(":")
                up_stage, up_part = int(s), int(p)
                job.locations[up_stage].pop(up_part, None)
                if self.attempt_of(job, up_stage, up_part) + 1 < \
                        self.MAX_TASK_ATTEMPTS:
                    # not the consumer's fault: park it (same attempt) and
                    # re-run the producer partition
                    job.pending.add((r.stage, r.partition))
                    self._launch_task(job, up_stage, up_part,
                                      self.attempt_of(job, up_stage,
                                                      up_part) + 1)
                    return
            job.last_error = r.error
            self._launch_task(job, r.stage, r.partition, r.attempt + 1)

    def _fire_pending(self, job: _Job):
        ready = []
        for (stage_id, partition) in list(job.pending):
            stage = job.graph.stages[stage_id]
            if self._partition_ready(job, stage, partition):
                ready.append((stage_id, partition))
        for stage_id, partition in ready:
            job.pending.discard((stage_id, partition))
            self._launch_task(job, stage_id, partition,
                              self.attempt_of(job, stage_id, partition))

    def _cleanup_job(self, job_id: str):
        job = self.jobs.get(job_id)
        if job is not None:
            from ..catalog.system import SYSTEM
            SYSTEM.record_job(job_id, len(job.graph.stages),
                              "failed" if job.failed else "finished",
                              job.stage_rows)
        self.jobs.pop(job_id, None)
        for w in self.workers.values():
            rpc = w["channel"].unary_unary(
                f"/{_WORKER_SERVICE}/CleanUpJob",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=pb.CleanUpJobResponse.FromString)
            try:
                rpc(pb.CleanUpJobRequest(job_id=job_id), timeout=10)
            except grpc.RpcError:
                pass


_FRAGMENT_CACHE: Dict[Tuple[str, int], bytes] = {}


def encode_cached(job: _Job, stage: jg.Stage) -> bytes:
    key = (job.job_id, stage.stage_id)
    blob = _FRAGMENT_CACHE.get(key)
    if blob is None:
        blob = jg.encode_fragment(stage.plan)
        _FRAGMENT_CACHE[key] = blob
        while len(_FRAGMENT_CACHE) > 256:
            _FRAGMENT_CACHE.pop(next(iter(_FRAGMENT_CACHE)))
    return blob


# ---------------------------------------------------------------------------
# Local-cluster runner (the reference's local-cluster mode / test vehicle)
# ---------------------------------------------------------------------------

class LocalCluster:
    def __init__(self, num_workers: int = 2, task_slots: int = 2,
                 elastic: Optional[dict] = None):
        """``elastic``: {"max": int, "min": int, "idle_secs": float} —
        workers beyond ``num_workers`` are started on demand by the driver
        through a ThreadWorkerManager and idle-reaped (reference:
        driver/worker_pool/ elastic scaling)."""
        self.driver = DriverActor()
        self.driver.start("driver")
        deadline = time.time() + 10
        while self.driver.port == 0 and time.time() < deadline:
            time.sleep(0.01)
        self.manager = None
        if elastic is not None:
            from .worker_manager import ThreadWorkerManager
            self.manager = ThreadWorkerManager(self.driver.addr, task_slots)
            self.driver.set_elastic(
                self.manager,
                min_workers=elastic.get("min", num_workers),
                max_workers=elastic.get("max", num_workers),
                idle_secs=elastic.get("idle_secs", 60.0))
        self.workers: List[WorkerActor] = []
        for i in range(num_workers):
            w = WorkerActor(f"worker-{i}", self.driver.addr,
                            task_slots)
            w.start(f"worker-{i}")
            self.workers.append(w)
        deadline = time.time() + 10
        while len(self.driver.workers) < num_workers and time.time() < deadline:
            time.sleep(0.02)
        self.last_job: Optional[_Job] = None

    def run_job(self, plan, num_partitions: Optional[int] = None, timeout=120):
        """Distribute a plan; returns the result pyarrow Table."""
        import pyarrow as pa
        from .local import LocalExecutor
        from .. import profiler

        nparts = num_partitions or max(1, len(self.workers))
        graph = jg.split_job(plan, nparts)
        if graph is None:
            return LocalExecutor().execute(plan)
        with tr.span("cluster:job") as root_span:
            job = _Job(uuid.uuid4().hex[:12], graph,
                       trace_ctx=tr.SpanContext(root_span.trace_id,
                                                root_span.span_id))
            # joins the session's profile when the job runs inside one;
            # a standalone run_job still gets its own profile record.
            # Execute/fetch phases come from the root-stage executor —
            # total_ms additionally covers the distributed wait.
            with profiler.profile_query(f"cluster job {job.job_id}"):
                return self._run_submitted(job, timeout)

    def _run_submitted(self, job, timeout):
        import pyarrow as pa
        from .local import LocalExecutor

        graph = job.graph
        self.last_job = job
        self.driver.handle.ask(lambda reply: ("submit", (job, reply)))
        try:
            if not job.done.wait(timeout):
                raise TimeoutError("cluster job timed out")
            if job.failed:
                raise RuntimeError(f"cluster job failed: {job.failed}")
            # the root stage runs on the driver over MERGE input fetched
            # from the workers via the data plane
            root = graph.root
            tables = {}
            for i in root.inputs:
                up = graph.stages[i.stage_id]
                parts = []
                for p in range(up.num_partitions):
                    addr = job.locations[i.stage_id][p]
                    buf = _fetch_from(addr, pb.FetchStreamRequest(
                        job_id=job.job_id, stage=i.stage_id, partition=p,
                        channel=-1), _WORKER_SERVICE)
                    parts.append(_ipc_to_table(buf))
                tables[i.stage_id] = pa.concat_tables(
                    parts, promote_options="permissive")
            root_plan = jg.attach_stage_inputs(root.plan, tables)
            # memory scans that stayed in the driver-run root plan read the
            # driver's own table map directly
            root_plan = _reattach_local_scans(root_plan, graph.scan_tables)
            result = LocalExecutor().execute(root_plan)
            # merge the workers' per-task operator metrics into the
            # driver's query profile per {stage, partition}
            from .. import profiler
            prof = profiler.current_profile()
            if prof is not None:
                for (stage, part), m in sorted(job.task_metrics.items()):
                    prof.add_task(stage, part, m.get("worker_id", ""),
                                  m.get("operators") or [],
                                  m.get("rows_out", 0))
            return result
        finally:
            self.driver.handle.send(("cleanup", job.job_id))

    def stage_rows(self) -> Dict[int, int]:
        """Rows produced per stage of the last job (operator metrics)."""
        return dict(self.last_job.stage_rows) if self.last_job else {}

    def task_metrics(self) -> Dict[Tuple[int, int], dict]:
        """Per-{stage, partition} operator metrics of the last job."""
        return dict(self.last_job.task_metrics) if self.last_job else {}

    def stop(self):
        for w in self.workers:
            w.stop()
        if self.manager is not None:
            self.manager.stop_all()
        self.driver.stop()
