"""Driver/worker cluster runtime over gRPC with a peer stream data plane.

Reference role: sail-execution's DriverActor/WorkerActor, worker pool with
heartbeats, stage scheduler with retry, the WorkerService/DriverService
RPCs, and the task-stream data plane
(crates/sail-execution/src/driver/, src/worker/, src/stream_service/ —
SURVEY.md §2.5/§3.3). Shape:

- the driver schedules stages in dependency order; tasks are assigned to
  the least-loaded live workers; per-task attempts with retry; heartbeat
  timeout eviction reschedules a lost worker's tasks.
- workers execute plan fragments on the local (jax) executor, hash-route
  shuffle outputs into channels, and serve them to PEERS over a
  FetchStream RPC (Arrow IPC) — results no longer ride task reports.
- memory-table scans are served by the DRIVER's stream service and sliced
  per task, so a stage ships the table at most once per consuming task's
  slice (not whole-table × partitions).
- local-cluster mode (the reference's test vehicle) runs driver + workers
  as threads speaking real gRPC over localhost.
"""

from __future__ import annotations

import itertools
import json
import os
import random
import statistics
import threading
import time
import uuid
from collections import OrderedDict
from concurrent import futures
from typing import Dict, List, Optional, Set, Tuple

import grpc

from .proto import control_plane_pb2 as pb

from .actor import Actor
from . import continuous as cont
from . import job_graph as jg
from . import shuffle as sh
from .. import events
from .. import faults
from .. import tracing as tr
from ..events import EventType
from ..io.prefetch import MultiPrefetcher
from ..metrics import record as _record_metric


def _fleet():
    from .. import metrics as _m
    return _m.FLEET


_DRIVER_SERVICE = "sail_tpu.control.DriverService"
_WORKER_SERVICE = "sail_tpu.control.WorkerService"


def _unary(fn, req_cls):
    return grpc.unary_unary_rpc_method_handler(
        fn, request_deserializer=req_cls.FromString,
        response_serializer=lambda m: m.SerializeToString())


# ---------------------------------------------------------------------------
# RPC retry: exponential backoff with FULL jitter (AWS architecture-blog
# shape: sleep = uniform(0, min(cap, base * 2^attempt))) applied to every
# driver<->worker unary RPC and stream fetch. Retries count in
# rpc.retry_count{method}; a NOT_FOUND (stream genuinely gone) is never
# retried — the fetch-failed producer-re-run path owns that case.
# ---------------------------------------------------------------------------

_RETRY_CONF_TTL_S = 5.0
_retry_conf_cache: Tuple[float, Tuple[int, float, float]] = (0.0, (4, 0.05, 2.0))


def _retry_conf() -> Tuple[int, float, float]:
    # config reads re-flatten the YAML tree and scan the environment;
    # this runs on every RPC attempt, so cache with a short TTL
    global _retry_conf_cache
    now = time.time()
    ts, cached = _retry_conf_cache
    if now - ts < _RETRY_CONF_TTL_S:
        return cached
    from ..config import get as config_get
    try:
        attempts = int(config_get("cluster.rpc_retry.max_attempts", 4))
        base = float(config_get("cluster.rpc_retry.base_ms", 50)) / 1000.0
        cap = float(config_get("cluster.rpc_retry.cap_ms", 2000)) / 1000.0
    except (TypeError, ValueError):
        attempts, base, cap = 4, 0.05, 2.0
    conf = (max(1, attempts), max(0.0, base), max(0.0, cap))
    _retry_conf_cache = (now, conf)
    return conf


def _conf_int(value, default: int) -> int:
    try:
        return int(value)
    except (TypeError, ValueError):
        return default


def _is_not_found(e: Exception) -> bool:
    if isinstance(e, faults.FaultInjectedError):
        return e.code == "not_found"
    code = getattr(e, "code", None)
    if code is None:
        return False
    try:
        return code() == grpc.StatusCode.NOT_FOUND
    except Exception:  # noqa: BLE001 — non-standard RpcError shapes
        return False


def _call_with_retry(fn, *, site: str, key: str, method: str,
                     attempts: Optional[int] = None):
    """Run ``fn`` under the retry budget; transient gRPC errors and
    injected faults back off with full jitter between attempts. An
    injected WorkerCrash always propagates (the caller is "dead"), and
    NOT_FOUND propagates immediately (retrying cannot resurrect a
    cleaned-up stream)."""
    max_attempts, base, cap = _retry_conf()
    if attempts is not None:
        max_attempts = max(1, attempts)
    last: Optional[Exception] = None
    for i in range(max_attempts):
        if i:
            time.sleep(random.uniform(0.0, min(cap, base * (2 ** (i - 1)))))
            _record_metric("rpc.retry_count", 1, method=method)
        try:
            faults.inject(site, key=key)
            return fn()
        except faults.WorkerCrash:
            raise
        except (grpc.RpcError, faults.FaultInjectedError) as e:
            if _is_not_found(e):
                raise
            last = e
    raise last


class _StreamStore:
    """Task output channels served over FetchStream, with disk spill.

    Reference role: the stream storage behind TaskStreamFlightServer
    (src/stream_manager/) + TaskWriteLocation::Local{Memory|Disk}
    (src/stream/writer.rs:11-29): channels stay in memory up to a cap;
    beyond it they spill to a per-store temp directory and are served
    from disk."""

    def __init__(self, memory_cap_bytes: Optional[int] = None):
        from ..config import get as config_get
        if memory_cap_bytes is None:
            memory_cap_bytes = int(config_get(
                "cluster.shuffle_memory_cap_mb", 256)) << 20
        self._cap = memory_cap_bytes
        self._mem_bytes = 0
        # epoch-tagged channels: streaming triggers publish each epoch's
        # output under its own key, so a crashed trigger's stale streams
        # can never satisfy the replay's fetches (epoch 0 = plain batch)
        self._streams: Dict[Tuple[str, int, int, int],
                            Dict[int, object]] = {}
        self._lock = threading.Lock()
        self._spill_dir: Optional[str] = None
        self.spill_count = 0
        self.epochs = sh.EpochLedger()

    def _spill_path(self, job_id: str, stage: int, partition: int,
                    channel: int, epoch: int) -> str:
        import tempfile
        if self._spill_dir is None:
            self._spill_dir = tempfile.mkdtemp(prefix="sail_shuffle_")
        return os.path.join(
            self._spill_dir,
            f"{job_id}_e{epoch}_{stage}_{partition}_{channel}.ipc")

    def put(self, job_id: str, stage: int, partition: int,
            channels: Dict[int, bytes], epoch: int = 0):
        with self._lock:
            # a task retry can overwrite a previous attempt's entry:
            # release its memory/disk accounting first
            prev = self._streams.pop((job_id, epoch, stage, partition),
                                     None)
            if prev is not None:
                for entry in prev.values():
                    if isinstance(entry, tuple):
                        try:
                            os.unlink(entry[1])
                        except OSError:
                            pass
                    else:
                        self._mem_bytes -= len(entry)
            stored: Dict[int, object] = {}
            for c, buf in channels.items():
                if self._mem_bytes + len(buf) > self._cap:
                    path = self._spill_path(job_id, stage, partition, c,
                                            epoch)
                    with open(path, "wb") as f:
                        f.write(buf)
                    stored[c] = ("disk", path)
                    self.spill_count += 1
                    _record_metric("execution.spill_count", 1,
                                   kind="shuffle")
                    # the spill format IS the wire format (compressed
                    # IPC), so these are post-compression bytes
                    _record_metric(
                        "execution.shuffle.spill_bytes_compressed",
                        len(buf))
                else:
                    self._mem_bytes += len(buf)
                    stored[c] = buf
            self._streams[(job_id, epoch, stage, partition)] = stored
        # the seal commits OUTSIDE the entry mutation but before any
        # success report can race a consumer here: publish-then-seal is
        # the producer half of the epoch barrier
        self.epochs.seal(job_id, epoch, stage, partition)

    def open_chunks(self, job_id: str, stage: int, partition: int,
                    channel: int, epoch: int = 0):
        """Serve a channel as an iterator of bounded byte chunks: memory
        entries slice, spilled entries stream from disk WITHOUT
        rehydrating the whole file under the memory cap. None = channel
        not found (including a raced clean_job unlink — the fetch side's
        NOT_FOUND producer-re-run path owns that case — and any request
        whose epoch the producer has not SEALED: barrier alignment is
        enforced at the data plane, not just by scheduling order)."""
        if not self.epochs.is_sealed(job_id, epoch, stage, partition):
            return None
        with self._lock:
            chans = self._streams.get((job_id, epoch, stage, partition))
            entry = None if chans is None else chans.get(channel)
        if entry is None:
            return None
        if isinstance(entry, tuple):
            try:
                f = open(entry[1], "rb")
            except FileNotFoundError:
                return None
            return sh.iter_file_chunks(f)
        return sh.iter_buffer_chunks(entry)

    def open_all_chunks(self, job_id: str, stage: int, partition: int,
                        epoch: int = 0):
        """Serve EVERY channel of one task's output as one chunk
        sequence — the channels' complete IPC streams back to back in
        channel order (the fetch side's decoder re-opens at each
        stream boundary). One round trip replaces num_channels fetches
        for consumers that need the whole output of a shuffle-writing
        producer (adaptive broadcast conversion)."""
        if not self.epochs.is_sealed(job_id, epoch, stage, partition):
            return None
        with self._lock:
            chans = self._streams.get((job_id, epoch, stage, partition))
            channels = None if chans is None else sorted(chans)
        if channels is None:
            return None

        def gen():
            for c in channels:
                chunks = self.open_chunks(job_id, stage, partition, c,
                                          epoch)
                if chunks is None:
                    # raced clean_job mid-serve: abort rather than ship
                    # a silently truncated concatenation — the fetch
                    # side fails over to the producer-re-run path
                    raise FileNotFoundError(
                        f"channel {c} of s{stage}p{partition} vanished")
                for chunk in chunks:
                    if chunk:
                        yield chunk

        return gen()

    def get(self, job_id: str, stage: int, partition: int,
            channel: int, epoch: int = 0) -> Optional[bytes]:
        """Whole-channel bytes (tests/tools); the serve path streams
        through :meth:`open_chunks` instead."""
        chunks = self.open_chunks(job_id, stage, partition, channel,
                                  epoch)
        if chunks is None:
            return None
        return b"".join(chunks)

    def clean_job(self, job_id: str):
        """Wipe a job's channels across every epoch. A streaming query
        keeps one stable job id across triggers but each trigger's
        ``run_job`` cleans up in its finally, so there is never more
        than one live epoch to wipe — stale epochs of a crashed trigger
        are inert anyway (unsealed or seal moved on)."""
        with self._lock:
            for key in [k for k in self._streams
                        if k[0] == job_id]:
                for entry in self._streams[key].values():
                    if isinstance(entry, tuple):
                        try:
                            os.unlink(entry[1])
                        except OSError:
                            pass
                    else:
                        self._mem_bytes -= len(entry)
                del self._streams[key]
        self.epochs.unseal(job_id)


def _task_metrics_enabled() -> bool:
    """Workers collect per-operator metrics for every task unless
    ``cluster.task_metrics`` turns it off (the collection forces one
    device sync per operator)."""
    from ..config import truthy
    return truthy("cluster.task_metrics")


def _fetch_stream_handler(store: Optional[_StreamStore],
                          scan_tables=None):
    """Server-streaming fetch: the channel's (compressed) IPC bytes
    stream as bounded chunks — no gRPC message-size cap, no full-buffer
    single message on the wire, and a SPILLED channel streams straight
    from disk without rehydrating under the memory cap (reference:
    stream_service/server.rs record-batch streams). ``store`` may be
    None (the DRIVER's service): scan slices still serve, but channel
    fetches are NOT_FOUND — the driver participates in the continuous
    data plane through PushRecords inboxes, not a stream store."""

    def resolve(request: pb.FetchStreamRequest, context):
        if store is None and not request.scan_id:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          "driver serves scan slices only")
        if request.scan_id:
            tables = scan_tables() if scan_tables is not None else {}
            entry = tables.get((request.job_id, request.scan_id))
            if entry is None:
                context.abort(grpc.StatusCode.NOT_FOUND,
                              f"unknown scan {request.scan_id}")
            n = entry.num_rows
            nparts = max(request.num_partitions, 1)
            per = -(-n // nparts) if n else 0
            part = entry.slice(request.partition * per, per) if per \
                else entry.slice(0, 0)
            chunks = sh.iter_buffer_chunks(sh.encode_table(part))
        elif request.channel == -2:
            # adaptive all-channels fetch: every channel of the task's
            # output as back-to-back IPC streams in one round trip
            chunks = store.open_all_chunks(request.job_id, request.stage,
                                           request.partition,
                                           epoch=request.epoch)
            if chunks is None:
                context.abort(
                    grpc.StatusCode.NOT_FOUND,
                    f"no streams for job={request.job_id} "
                    f"epoch={request.epoch} "
                    f"stage={request.stage} "
                    f"partition={request.partition}")
        else:
            chunks = store.open_chunks(request.job_id, request.stage,
                                       request.partition, request.channel,
                                       epoch=request.epoch)
            if chunks is None:
                context.abort(
                    grpc.StatusCode.NOT_FOUND,
                    f"no stream for job={request.job_id} "
                    f"epoch={request.epoch} "
                    f"stage={request.stage} "
                    f"partition={request.partition} "
                    f"channel={request.channel}")
        return chunks

    def fetch(request: pb.FetchStreamRequest, context):
        # the channel lookup runs under a server span parented on the
        # caller's traceparent (the span must not wrap the yields: gRPC
        # may resume the generator on another thread and the span stack
        # is thread-local)
        parent = tr.extract_context(context.invocation_metadata())
        with tr.span(f"serve:fetch s{request.stage}"
                     f"p{request.partition}",
                     {"job_id": request.job_id,
                      "channel": request.channel}, parent=parent):
            chunks = resolve(request, context)
        # one-chunk lookahead so the final data chunk carries last=True
        prev: Optional[bytes] = None
        for chunk in chunks:
            if prev is not None:
                yield pb.FetchChunk(data=prev, last=False)
            prev = chunk
        yield pb.FetchChunk(data=prev if prev is not None else b"",
                            last=True)

    return fetch


# fetch-side peer channel cache: gRPC channels are thread-safe and
# multiplexed, and adaptive fetch plans (a broadcast-converted build
# side reads every channel of every producer) multiply small fetches —
# a fresh channel per fetch made connection setup the dominant cost of
# tiny streams. Bounded; eviction closes the channel (in-flight calls
# on a closing channel fail like any transient error and retry/re-run).
_PEER_CHANNEL_CAP = 32
_peer_channels: "OrderedDict[str, grpc.Channel]" = OrderedDict()
_peer_channels_lock = threading.Lock()


def _peer_channel(addr: str) -> grpc.Channel:
    evicted = []
    with _peer_channels_lock:
        ch = _peer_channels.pop(addr, None)
        if ch is None:
            ch = grpc.insecure_channel(addr)
        _peer_channels[addr] = ch  # re-insert = move to MRU end
        while len(_peer_channels) > _PEER_CHANNEL_CAP:
            _addr, old = _peer_channels.popitem(last=False)  # LRU out
            evicted.append(old)
    for old in evicted:
        try:
            old.close()
        except Exception:  # noqa: BLE001
            pass
    return ch


def _drop_peer_channel(addr: str) -> None:
    """Evict a peer channel after a failed call: a cached channel sits
    in gRPC's reconnect backoff after a refused connection, so the
    single fetch retry must dial FRESH or a transient blip escalates
    into a producer re-run."""
    with _peer_channels_lock:
        ch = _peer_channels.pop(addr, None)
    if ch is not None:
        try:
            ch.close()
        except Exception:  # noqa: BLE001
            pass


def _fetch_table(addr: str, req: pb.FetchStreamRequest, service: str,
                 timeout: float = 120.0,
                 stats: Optional[sh.FetchStats] = None):
    """Fetch one stream and decode it INCREMENTALLY off the gRPC chunk
    stream (record batch by record batch — the bytes are never
    concatenated first). Returns a pyarrow Table."""
    key = (f"{addr}/scan:{req.scan_id}" if req.scan_id
           else f"{addr}/s{req.stage}p{req.partition}c{req.channel}")

    def once():
        channel = _peer_channel(addr)
        try:
            rpc = channel.unary_stream(
                f"/{service}/FetchStream",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=pb.FetchChunk.FromString)
            chunks = (c.data for c in
                      rpc(req, timeout=timeout,
                          metadata=tr.inject_context()))
            return sh.decode_stream(sh.ChunkReader(chunks), stats=stats)
        except grpc.RpcError as e:
            # evict only on connectivity-class failures — the channel is
            # SHARED by concurrent sibling fetches and close() cancels
            # their in-flight RPCs, so a semantic failure (NOT_FOUND
            # from a raced clean_job, a server-side error) must keep it
            code = getattr(e, "code", lambda: None)()
            if code in (grpc.StatusCode.UNAVAILABLE,
                        grpc.StatusCode.DEADLINE_EXCEEDED):
                _drop_peer_channel(addr)
            raise

    # one retry only: each attempt can legitimately take the full
    # stream timeout, so a blackholed peer must fail over to the
    # producer-re-run path after at most two, not multiply the stall
    return _call_with_retry(once, site="shuffle.fetch", key=key,
                            method="FetchStream", attempts=2)


def _fetch_channel_bytes(addr: str, req: pb.FetchStreamRequest,
                         service: str, timeout: float = 120.0) -> bytes:
    """Fetch one channel's RAW wire bytes (compressed IPC) without
    decoding. The drain handoff moves channels verbatim: the spill
    format IS the wire format, so a re-``put`` on the adopting store
    serves byte-identical streams to every later consumer."""
    key = f"{addr}/s{req.stage}p{req.partition}c{req.channel}/raw"

    def once():
        channel = _peer_channel(addr)
        try:
            rpc = channel.unary_stream(
                f"/{service}/FetchStream",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=pb.FetchChunk.FromString)
            return b"".join(c.data for c in
                            rpc(req, timeout=timeout,
                                metadata=tr.inject_context()))
        except grpc.RpcError as e:
            code = getattr(e, "code", lambda: None)()
            if code in (grpc.StatusCode.UNAVAILABLE,
                        grpc.StatusCode.DEADLINE_EXCEEDED):
                _drop_peer_channel(addr)
            raise

    # same budget and fault site as a consumer fetch: a dropped handoff
    # fetch retries once, then the drain tick retries the whole
    # partition (and the drain timeout bounds a black hole)
    return _call_with_retry(once, site="shuffle.fetch", key=key,
                            method="FetchStream", attempts=2)


# ---------------------------------------------------------------------------
# Worker
# ---------------------------------------------------------------------------

class WorkerActor(Actor):
    def __init__(self, worker_id: str, driver_addr: str, task_slots: int = 2,
                 host: str = "127.0.0.1", advertise_host: Optional[str] = None):
        super().__init__()
        self.worker_id = worker_id
        self.driver_addr = driver_addr
        self.task_slots = task_slots
        self.host = host
        # the address peers/driver dial; differs from the bind address when
        # binding 0.0.0.0 in a pod (reference kubernetes.rs: pod IP)
        self.advertise_host = advertise_host or host
        self.port = 0
        self._server: Optional[grpc.Server] = None
        self._driver_channel: Optional[grpc.Channel] = None
        # per-task cancel Events, one per execution currently queued or
        # running for that (job, stage, partition) on this worker;
        # mutated from the actor thread, pool threads, and gRPC handler
        # threads — every structural mutation holds _running_lock
        self._running: Dict[Tuple[str, int, int],
                            List[threading.Event]] = {}
        self._running_lock = threading.Lock()
        self._pool = futures.ThreadPoolExecutor(max_workers=task_slots)
        self._hb_stop = threading.Event()
        self._crashed = False
        self.streams = _StreamStore()
        # continuous streaming: resident (long-lived) stage tasks and
        # their sequenced, credit-bounded input channels
        self.continuous = cont.ContinuousWorker(self)
        # background-prewarm the persistent program store's working set
        # before first traffic (idempotent per process)
        from . import pcache
        pcache.start_prewarm()

    # -- rpc service -----------------------------------------------------
    def _service(self):
        def run_task(request: pb.RunTaskRequest, context):
            parent = tr.extract_context(context.invocation_metadata())
            self.handle.send(("run_task", (request.task, parent)))
            return pb.RunTaskResponse(accepted=True)

        def stop_task(request: pb.StopTaskRequest, context):
            key = (request.job_id, request.stage, request.partition)
            with self._running_lock:
                evs = list(self._running.get(key) or ())
            for ev in evs:
                ev.set()  # cooperative cancel: checked between pipeline steps
            return pb.StopTaskResponse(stopped=bool(evs))

        def clean_up_job(request: pb.CleanUpJobRequest, context):
            self.streams.clean_job(request.job_id)
            self.continuous.clean_job(request.job_id)
            with self._running_lock:
                evs = [ev for k, lst in self._running.items()
                       if k[0] == request.job_id for ev in lst]
            for ev in evs:
                ev.set()
            return pb.CleanUpJobResponse()

        def push_records(request: pb.PushRecordsRequest, context):
            return self.continuous.offer(request)

        def pull_channels(request: pb.PullChannelsRequest, context):
            # graceful drain: adopt a draining peer's sealed channels.
            # Pull each channel's raw wire bytes over the peer data
            # plane and re-put them locally — put() re-seals, so the
            # adopted output serves consumers exactly like our own.
            moved: Dict[int, bytes] = {}
            try:
                for c in request.channels:
                    moved[c] = _fetch_channel_bytes(
                        request.peer_addr,
                        pb.FetchStreamRequest(
                            job_id=request.job_id, stage=request.stage,
                            partition=request.partition, channel=c,
                            epoch=request.epoch),
                        _WORKER_SERVICE)
            except (grpc.RpcError, faults.FaultInjectedError) as e:
                # partial pulls import NOTHING: a half-adopted output
                # must never seal (consumers would fetch a truncated
                # channel set); the driver retries whole-partition
                return pb.PullChannelsResponse(
                    ok=False, error=f"{type(e).__name__}: {e}")
            self.streams.put(request.job_id, request.stage,
                             request.partition, moved,
                             epoch=request.epoch)
            return pb.PullChannelsResponse(
                ok=True, channels_moved=len(moved),
                bytes_moved=sum(len(b) for b in moved.values()))

        return grpc.method_handlers_generic_handler(_WORKER_SERVICE, {
            "RunTask": _unary(run_task, pb.RunTaskRequest),
            "StopTask": _unary(stop_task, pb.StopTaskRequest),
            "CleanUpJob": _unary(clean_up_job, pb.CleanUpJobRequest),
            "PushRecords": _unary(push_records, pb.PushRecordsRequest),
            "PullChannels": _unary(pull_channels, pb.PullChannelsRequest),
            "FetchStream": grpc.unary_stream_rpc_method_handler(
                _fetch_stream_handler(self.streams),
                request_deserializer=pb.FetchStreamRequest.FromString,
                response_serializer=lambda m: m.SerializeToString()),
        })

    def on_start(self):
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        self._server.add_generic_rpc_handlers((self._service(),))
        self.port = self._server.add_insecure_port(f"{self.host}:0")
        self._server.start()
        self._driver_channel = grpc.insecure_channel(self.driver_addr)
        resp = self._call_driver("RegisterWorker", pb.RegisterWorkerRequest(
            worker_id=self.worker_id, host=self.advertise_host,
            port=self.port,
            task_slots=self.task_slots), pb.RegisterWorkerResponse)
        if not resp.accepted:
            raise RuntimeError("driver rejected worker registration")
        threading.Thread(target=self._heartbeat_loop, daemon=True).start()

    def on_stop(self):
        self._hb_stop.set()
        self.continuous.stop_all()
        if self._server is not None:
            self._server.stop(grace=0.5)

    def _call_driver(self, method: str, msg, resp_cls, retry: bool = True):
        def once():
            rpc = self._driver_channel.unary_unary(
                f"/{_DRIVER_SERVICE}/{method}",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=resp_cls.FromString)
            return rpc(msg, timeout=30, metadata=tr.inject_context())

        return _call_with_retry(once, site="rpc.call", key=method,
                                method=method,
                                attempts=None if retry else 1)

    def _die(self):
        """Injected process-level crash: stop serving streams and
        heartbeats, report nothing — the driver must discover the loss
        through heartbeat eviction, exactly like a real dead process."""
        self._crashed = True
        self._hb_stop.set()
        self.continuous.stop_all()
        if self._server is not None:
            self._server.stop(grace=0)

    def _heartbeat_loop(self):
        from ..config import get as config_get
        try:
            interval = max(0.1, float(config_get(
                "cluster.worker_heartbeat_interval_secs", 1.0)))
        except (TypeError, ValueError):
            interval = 1.0
        # a delta the last heartbeat failed to deliver: folded into the
        # next cycle's increments instead of lost (the registry cursor
        # advances at take time, so delivery is this loop's problem)
        pending_delta = None
        while not self._hb_stop.wait(interval):
            try:
                faults.inject("worker.heartbeat", key=self.worker_id)
                # fleet telemetry piggyback: this process's metric
                # delta since the last heartbeat (counter increments +
                # histogram bucket increments); one cursor per process,
                # so a multi-worker loopback process ships each
                # increment exactly once
                try:
                    from .. import metrics as _m
                    pending_delta = _m.merge_heartbeat_deltas(
                        pending_delta,
                        _m.REGISTRY.take_heartbeat_delta())
                    delta_json = json.dumps(pending_delta) \
                        if pending_delta else ""
                except Exception:  # noqa: BLE001 — telemetry never
                    # blocks the heartbeat; ship nothing this cycle
                    # and KEEP any retained undelivered delta
                    delta_json = ""
                self._call_driver("Heartbeat", pb.HeartbeatRequest(
                    worker_id=self.worker_id,
                    running_tasks=len(self._running),
                    metrics_json=delta_json), pb.HeartbeatResponse,
                    retry=False)
                pending_delta = None  # delivered
            except faults.WorkerCrash:
                self._die()
                return
            except (grpc.RpcError, faults.FaultInjectedError):
                pass

    # -- actor -----------------------------------------------------------
    def receive(self, message):
        kind, payload = message
        if kind == "run_task":
            task, parent = payload
            key = (task.job_id, task.stage, task.partition)
            # one Event PER EXECUTION: a relaunched attempt landing on
            # this worker while an older one is still queued/running
            # must stay independently cancelable
            ev = threading.Event()
            with self._running_lock:
                self._running.setdefault(key, []).append(ev)
            if task.continuous_json:
                # long-lived resident stage task: runs on its own
                # thread (it never completes, so it must not occupy a
                # slot of the run-to-completion pool)
                try:
                    spec = json.loads(task.continuous_json)
                except ValueError:
                    spec = {}
                self.continuous.start_task(task, spec, ev)
            else:
                self._pool.submit(self._run_task, task, parent, ev)

    def _unregister_running(self, key,
                            ev: Optional[threading.Event] = None):
        with self._running_lock:
            evs = self._running.get(key)
            if evs is None:
                return
            if ev is not None:
                try:
                    evs.remove(ev)
                except ValueError:
                    pass
            else:
                del evs[:]
            if not evs:
                self._running.pop(key, None)

    # -- task execution --------------------------------------------------
    def _fetch_inputs(self, task: pb.TaskDefinition,
                      stats: Optional[sh.FetchStats] = None,
                      collector: Optional[
                          events.TaskEventCollector] = None,
                      parent: Optional[tr.SpanContext] = None):
        """Pull ALL upstream stage outputs over the peer data plane
        CONCURRENTLY: every (producer partition, channel) of every input
        streams through one bounded multi-producer prefetch pool
        (``shuffle.fetch_concurrency`` fetches in flight), overlapping
        network + decode across partitions instead of draining one fully
        materialized buffer at a time. Per-fetch fault semantics are
        unchanged: each fetch retries once at site ``shuffle.fetch`` and
        a NOT_FOUND surfaces as a per-input _FetchFailed (producer
        re-run)."""
        import pyarrow as pa

        # (input stage_id, position within the input, up_part, chan, addr)
        work: List[Tuple[int, int, int, int, str]] = []
        input_len: Dict[int, int] = {}
        for inp in task.inputs:
            addrs = list(inp.worker_addrs)
            if inp.fetch_parts:
                # adaptive fetch plan: explicit (producer partition,
                # channel) pairs — coalesced channel runs, skew-split
                # producer subsets, broadcast-converted build sides
                wanted = [(int(p), int(c)) for p, c in
                          zip(inp.fetch_parts, inp.fetch_channels)]
                addrs = [addrs[p] for p, _c in wanted]
            elif inp.mode == "shuffle":
                wanted = [(i, task.partition) for i in range(len(addrs))]
            elif inp.mode == "forward":
                wanted = [(task.partition, -1)]
                addrs = [addrs[task.partition]]
            else:  # merge | broadcast: everything from every producer
                wanted = [(i, -1) for i in range(len(addrs))]
            for pos, ((up_part, chan), addr) in enumerate(zip(wanted,
                                                              addrs)):
                work.append((inp.stage_id, pos, up_part, chan, addr))
            input_len[inp.stage_id] = len(wanted)

        def fetch_one(item):
            stage_id, _pos, up_part, chan, addr = item
            if collector is not None:
                collector.emit(EventType.FETCH_BEGIN,
                               job_id=task.job_id, stage=stage_id,
                               partition=up_part, channel=chan,
                               addr=addr, dst_stage=task.stage,
                               dst_partition=task.partition)
            t0 = time.perf_counter()
            ok = False
            nbytes = 0
            try:
                # the span opens ON the prefetch-pool thread with the
                # task span as explicit parent, so the fetch RPC's
                # traceparent (injected from this thread's stack inside
                # _fetch_table) chains worker:task → worker:fetch →
                # serve:fetch end to end
                with tr.span(f"worker:fetch s{stage_id}p{up_part}",
                             {"job_id": task.job_id, "channel": chan},
                             parent=parent):
                    table = _fetch_table(addr, pb.FetchStreamRequest(
                        job_id=task.job_id, stage=stage_id,
                        partition=up_part, channel=chan,
                        epoch=task.epoch), _WORKER_SERVICE,
                        stats=stats)
                ok = True
                nbytes = int(table.nbytes)
                return table
            except faults.WorkerCrash:
                raise
            except (grpc.RpcError, faults.FaultInjectedError) as e:
                raise _FetchFailed(stage_id, up_part) from e
            finally:
                if collector is not None:
                    collector.emit(
                        EventType.FETCH_END, job_id=task.job_id,
                        stage=stage_id, partition=up_part, channel=chan,
                        addr=addr, dst_stage=task.stage,
                        dst_partition=task.partition, bytes=nbytes,
                        ms=round((time.perf_counter() - t0) * 1000.0,
                                 3), ok=ok)

        parts: Dict[int, Dict[int, object]] = {}
        mp = MultiPrefetcher(work, fetch_one,
                             workers=sh.fetch_concurrency(),
                             kind="shuffle")
        try:
            for index, table in mp:
                stage_id, pos = work[index][0], work[index][1]
                parts.setdefault(stage_id, {})[pos] = table
        finally:
            mp.close()
            wait = mp.stats.consumer_wait_s
            _record_metric("execution.shuffle.fetch_wait_time", wait)
            if stats is not None:
                stats.add(wait_s=wait)
        tables: Dict[int, object] = {}
        for stage_id, n in input_len.items():
            ordered = [parts[stage_id][i] for i in range(n)]
            tables[stage_id] = pa.concat_tables(
                ordered, promote_options="permissive") if len(ordered) > 1 \
                else ordered[0]
        return tables

    def _run_task(self, task: pb.TaskDefinition, parent=None, ev=None):
        from .local import LocalExecutor
        key = (task.job_id, task.stage, task.partition)
        with tr.span(f"worker:task s{task.stage}p{task.partition}",
                     {"job_id": task.job_id, "stage": task.stage,
                      "partition": task.partition,
                      "worker": self.worker_id}, parent=parent):
            self._run_task_inner(task, key, ev)

    def _run_task_inner(self, task: pb.TaskDefinition, key, ev=None):
        from .local import LocalExecutor
        if self._crashed:
            return  # a "dead" process executes nothing and reports nothing
        # the Event registered for THIS execution (receive() created it
        # before submit): cancel checks and the final removal go through
        # it, so an old attempt finishing late can neither miss a cancel
        # nor unregister a relaunched attempt
        if ev is None:
            ev = threading.Event()
        fetch_stats = sh.FetchStats()
        # per-task flight-recorder buffer: execution + fetch threads
        # emit here; the TERMINAL status report ships the drained
        # buffer to the driver's cluster-wide event log
        recorder = events.TaskEventCollector()
        try:
            faults.inject("worker.task_exec",
                          key=f"{self.worker_id}:s{task.stage}"
                              f"p{task.partition}")
            self._report(task, "running")
            recorder.emit(EventType.TASK_START, job_id=task.job_id,
                          stage=task.stage, partition=task.partition,
                          attempt=task.attempt, worker=self.worker_id,
                          tenant=task.tenant)
            span_ctx = tr._current()
            plan = jg.decode_fragment(task.plan, task.partition,
                                      max(task.num_partitions, 1))
            plan = _resolve_driver_scans(plan, task, fetch_stats)
            if task.runtime_filters_json:
                # driver-derived runtime join filters: prune this task's
                # scan before upload/shuffle (applied before stage inputs
                # attach so scan ordinals match the driver's counting)
                plan = jg.apply_task_runtime_filters(
                    plan, task.runtime_filters_json)
            if task.inputs:
                plan = jg.attach_stage_inputs(
                    plan, self._fetch_inputs(task, fetch_stats,
                                             collector=recorder,
                                             parent=span_ctx))
            if ev.is_set():
                self._report(task, "canceled", recorder=recorder)
                return
            metrics_json = ""
            if _task_metrics_enabled():
                # per-operator metrics ride the success report so the
                # driver's query profile sees below the stage boundary
                import json as _json

                from .. import telemetry as tel
                with tel.collect_metrics() as collector, \
                        events.collecting(recorder):
                    table = LocalExecutor().execute(plan)
                try:
                    metrics_json = _json.dumps(
                        [m.to_dict() for m in collector])
                except (TypeError, ValueError):
                    metrics_json = ""
            else:
                with events.collecting(recorder):
                    table = LocalExecutor().execute(plan)
            if ev.is_set():
                # canceled while executing (job cancel / speculation
                # loser): do not publish partial shuffle outputs
                self._report(task, "canceled", recorder=recorder)
                return
            if task.HasField("shuffle_write") and \
                    task.shuffle_write.num_channels > 1:
                # shuffle consumers only ever fetch hash channels — do not
                # retain a second full copy of the output
                sw = task.shuffle_write
                parts = jg.hash_partition_table(
                    table, list(sw.key_columns), sw.num_channels)
                channels: Dict[int, bytes] = {
                    c: sh.encode_table(part)
                    for c, part in enumerate(parts)}
            else:
                channels = {-1: sh.encode_table(table)}
            self.streams.put(task.job_id, task.stage, task.partition,
                             channels, epoch=task.epoch)
            # channel-size metadata rides the success report: the driver's
            # memory governor projects consumer footprints from it
            channel_bytes = [len(channels[c]) for c in sorted(channels)]
            self._report(task, "succeeded", rows=table.num_rows,
                         metrics_json=metrics_json,
                         channel_bytes=channel_bytes,
                         raw_bytes=int(table.nbytes),
                         fetch_stats=fetch_stats, recorder=recorder)
        except faults.WorkerCrash:
            # injected process death: no failure report, no cleanup — the
            # driver's heartbeat eviction path must pick up the pieces
            self._die()
        except _FetchFailed as e:
            # a producer's streams are gone (dead peer): the driver re-runs
            # the producer and re-schedules this task, not as our failure
            self._report(task, "failed",
                         error=f"FETCH_FAILED:{e.stage_id}:{e.partition}",
                         recorder=recorder)
        except Exception as e:  # noqa: BLE001 — full cause goes to the driver
            self._report(task, "failed", error=f"{type(e).__name__}: {e}",
                         recorder=recorder)
        finally:
            with self._running_lock:
                evs = self._running.get(key)
                if evs is not None:
                    try:
                        evs.remove(ev)
                    except ValueError:
                        pass
                    if not evs:
                        self._running.pop(key, None)

    def _report(self, task: pb.TaskDefinition, state: str, error: str = "",
                rows: int = 0, metrics_json: str = "",
                channel_bytes: Optional[List[int]] = None,
                raw_bytes: int = 0,
                fetch_stats: Optional[sh.FetchStats] = None,
                recorder: Optional[events.TaskEventCollector] = None,
                report_seq: int = 0):
        """Report task status with backoff retries: a worker that cannot
        reach the driver for one transient blip must not lose a finished
        task's result until heartbeat eviction re-runs it from scratch."""
        if self._crashed:
            return
        events_json: List[str] = []
        if recorder is not None and (
                state in ("succeeded", "failed", "canceled")
                or report_seq):
            # worker events piggyback on TERMINAL reports — plus a
            # resident task's numbered periodic flushes (report_seq):
            # the driver dedupes both (at-least-once delivery), so the
            # shipped buffer merges exactly once. Without the flushes a
            # long-lived task would only surface its marker_align/
            # backpressure events at pipeline death (and its bounded
            # collector would drop the rest).
            try:
                events_json = [json.dumps(e, default=str)
                               for e in recorder.drain()]
            except (TypeError, ValueError):
                events_json = []
        try:
            self._call_driver("ReportTaskStatus", pb.ReportTaskStatusRequest(
                worker_id=self.worker_id, job_id=task.job_id,
                stage=task.stage, partition=task.partition,
                attempt=task.attempt, state=state, error=error,
                rows_out=rows, metrics_json=metrics_json,
                channel_bytes=channel_bytes or [],
                raw_bytes=int(raw_bytes),
                fetch_wait_s=fetch_stats.wait_s if fetch_stats else 0.0,
                decode_s=fetch_stats.decode_s if fetch_stats else 0.0,
                events_json=events_json,
                report_seq=int(report_seq)),
                pb.ReportTaskStatusResponse)
        except faults.WorkerCrash:
            self._die()
        except (grpc.RpcError, faults.FaultInjectedError):
            pass  # retries exhausted: heartbeat eviction will re-run


def _reattach_local_scans(plan, scan_tables):
    import dataclasses as dc
    from ..plan import nodes as pn

    def repl(p):
        if isinstance(p, pn.ScanExec) and p.format == "__driver__":
            return dc.replace(p, source=scan_tables[p.table_name],
                              format="memory", table_name="")
        if isinstance(p, pn.JoinExec):
            return dc.replace(p, left=repl(p.left), right=repl(p.right))
        if isinstance(p, pn.UnionExec):
            return dc.replace(p, inputs=tuple(repl(c) for c in p.inputs))
        if hasattr(p, "input") and p.input is not None:
            return dc.replace(p, input=repl(p.input))
        return p

    return repl(plan)


class _FetchFailed(Exception):
    def __init__(self, stage_id: int, partition: int):
        super().__init__(f"stage {stage_id} partition {partition}")
        self.stage_id = stage_id
        self.partition = partition


def _resolve_driver_scans(plan, task: pb.TaskDefinition,
                          stats: Optional[sh.FetchStats] = None):
    """Fetch this task's slice of driver-hosted memory tables."""
    import dataclasses as dc
    from ..plan import nodes as pn

    def repl(p):
        if isinstance(p, pn.ScanExec) and p.format == "__driver__":
            table = _fetch_table(task.driver_addr, pb.FetchStreamRequest(
                job_id=task.job_id, scan_id=p.table_name,
                partition=task.partition,
                num_partitions=max(task.num_partitions, 1)),
                _DRIVER_SERVICE, stats=stats)
            return dc.replace(p, source=table, format="memory",
                              table_name="")
        if isinstance(p, pn.JoinExec):
            return dc.replace(p, left=repl(p.left), right=repl(p.right))
        if isinstance(p, pn.UnionExec):
            return dc.replace(p, inputs=tuple(repl(c) for c in p.inputs))
        if hasattr(p, "input") and p.input is not None:
            return dc.replace(p, input=repl(p.input))
        return p

    return repl(plan)


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------

_JOB_SEQ = itertools.count()


class _Job:
    def __init__(self, job_id: str, graph: jg.JobGraph,
                 trace_ctx=None, epoch: int = 0,
                 tenant: str = "default"):
        self.job_id = job_id
        self.graph = graph
        # multi-tenant admission control: the owning tenant, the DRR
        # cost (stage-launch opportunities, stamped at offer), whether
        # the fair queue admitted the job yet, an optional absolute
        # deadline, and the typed failure kind ("shed" | "deadline")
        # run_job maps to ResourceExhausted / DeadlineExceeded
        self.tenant = tenant or "default"
        self.adm_cost = 1
        self.queued_ts = 0.0
        self.admitted = False
        self.deadline_ts: Optional[float] = None
        self.deadline_ms = 0.0
        self.error_kind = ""
        # flight-recorder envelope: the owning query's profile id,
        # stamped before submit so every driver/worker event of this
        # job carries it (empty for bare run_job calls until the
        # profile opens)
        self.query_id = ""
        # stages whose STAGE_SUBMIT event already fired (a pipelined
        # stage launches per partition but submits once)
        self.stage_submitted: Set[int] = set()
        # fragment-cache namespace: unique per SUBMISSION, never reused.
        # job_id+epoch is not enough — a streaming trigger may dispatch
        # several different job graphs under one (job_id, epoch) (e.g.
        # the incremental delta plan and the residual plan), and their
        # stage ids both start at 0
        self.seq = next(_JOB_SEQ)
        self.trace_ctx = trace_ctx
        # streaming epoch this job executes (0 for plain batch): stamped
        # on every task and stream fetch, so a restarted trigger's
        # replay can only ever address its own epoch's channels
        self.epoch = int(epoch)
        self.failed: Optional[str] = None
        self.done = threading.Event()
        # per stage: partition → worker addr (set on success)
        self.locations: Dict[int, Dict[int, str]] = {
            s.stage_id: {} for s in graph.stages}
        self.attempts: Dict[Tuple[int, int], int] = {}
        self.last_error: str = ""
        self.scheduled: Set[int] = set()
        # per-partition launches for pipelined (FORWARD-input) stages
        self.launched: Set[Tuple[int, int]] = set()
        # consumer tasks waiting for a producer re-run after a fetch failure
        self.pending: Set[Tuple[int, int]] = set()
        # rows per (stage, partition) from the winning attempt — keyed
        # (not accumulated) so a producer RE-RUN after worker loss
        # overwrites idempotently: stage totals stay bit-identical
        # across fault recovery, which the adaptive reorder and the
        # observed-cardinality feedback depend on
        self.partition_rows: Dict[Tuple[int, int], int] = {}
        self.stage_rows: Dict[int, int] = {}
        # attempt fencing: per (stage, partition), the attempts currently
        # IN FLIGHT and the worker running each — the first live attempt
        # to report success wins; stale/duplicate attempts are ignored
        self.live: Dict[Tuple[int, int], Dict[int, str]] = {}
        # dispatch wall-clock per (stage, partition, attempt) + accepted
        # task durations per stage (drives straggler detection)
        self.started: Dict[Tuple[int, int, int], float] = {}
        self.durations: Dict[int, List[float]] = {}
        # speculation: partitions already duplicated, which attempt
        # number is the speculative copy, and how many extra attempt ids
        # speculation consumed (they must not eat the failure budget)
        self.speculated: Set[Tuple[int, int]] = set()
        self.spec_attempt: Dict[Tuple[int, int], int] = {}
        self.attempt_allowance: Dict[Tuple[int, int], int] = {}
        # terminal task reports already processed (workers retry reports
        # under backoff, so delivery is at-least-once)
        self.seen_reports: Set[Tuple[int, int, int, str, str]] = set()
        # fault-tolerance accounting surfaced through the query profile
        self.retry_count = 0
        self.spec_launched = 0
        self.spec_won = 0
        self.canceled = False
        # data-movement accounting learned from task reports: per
        # (stage, partition) → (compressed bytes per channel, raw bytes)
        # — the memory governor projects consumer-task footprints from
        # these — plus job-level wire/fetch/decode totals for the profile
        self.channel_bytes: Dict[Tuple[int, int],
                                 Tuple[List[int], int]] = {}
        self.wire_raw = 0
        self.wire_comp = 0
        self.fetch_wait_s = 0.0
        self.decode_s = 0.0
        # memory governor: tasks deferred because no worker could admit
        # their projected input footprint — (stage, partition, attempt,
        # exclude) relaunched as capacity frees
        self.deferred: List[Tuple[int, int, int,
                                  Optional[frozenset]]] = []
        self.governor_deferred = 0
        # per-{stage, partition} operator metrics from the winning task
        # attempt: {"worker_id", "rows_out", "operators": [...]}
        self.task_metrics: Dict[Tuple[int, int], dict] = {}
        self.result_addr: Optional[str] = None
        # adaptive execution: decision log, skew telemetry, and the
        # stage-completion transitions already processed
        from . import adaptive as _aqe
        self.adaptive = _aqe.AdaptiveState()
        self.adaptive.job_id = job_id


def _jtrace(job: "_Job") -> Optional[str]:
    """The trace id every event of a job carries (None for bare jobs)."""
    return job.trace_ctx.trace_id if job.trace_ctx is not None else None


def _note_stage_submit(job: "_Job", stage, pipelined: bool) -> None:
    """STAGE_SUBMIT fires once per stage even when a pipelined stage
    launches per partition. Module-level (not a DriverActor method):
    scheduling-logic tests drive ``_schedule_ready_stages`` against
    minimal driver stubs."""
    if stage.stage_id in job.stage_submitted:
        return
    job.stage_submitted.add(stage.stage_id)
    events.emit(EventType.STAGE_SUBMIT, query_id=job.query_id,
                trace_id=_jtrace(job), job_id=job.job_id,
                stage=stage.stage_id,
                partitions=stage.num_partitions,
                pipelined=pipelined)


class DriverActor(Actor):
    HEARTBEAT_TIMEOUT_S = 10.0
    MAX_TASK_ATTEMPTS = 3

    def __init__(self, host: str = "127.0.0.1"):
        super().__init__()
        from ..config import get as config_get
        from ..config import truthy as _on

        def _num(key, default, cast=float):
            try:
                return cast(config_get(key, default))
            except (TypeError, ValueError):
                return default

        self.host = host
        self.driver_id = uuid.uuid4().hex[:8]
        self.workers: Dict[str, dict] = {}
        self.jobs: Dict[str, _Job] = {}
        self._server: Optional[grpc.Server] = None
        self.port = 0
        self._probe_stop = threading.Event()
        # continuous streaming: registration records of the live
        # long-lived pipelines (job_id → _DriverContinuousJob). The
        # driver participates in the continuous data plane through the
        # runners' PushRecords inboxes — the dead driver-side
        # _StreamStore this replaced is gone. Stopped pipelines linger
        # in the drain map briefly so resident tasks' terminal reports
        # (which carry their buffered flight-recorder events —
        # marker_align, backpressure) still merge into the log.
        self.continuous: Dict[str, "cont._DriverContinuousJob"] = {}
        self._continuous_drain: Dict[str, Tuple[object, float]] = {}
        # elastic pool (reference: driver/worker_pool/ scale between
        # initial and max counts with idle reaping)
        self.elastic: Optional[dict] = None
        self._starting = 0
        self._starting_ts: List[float] = []
        # high-water mark of (live + starting) workers: scale-up is
        # observable after the fact even once idle reaping shrinks the
        # pool back down (reading the live count races the reaper)
        self.pool_peak = 0
        self.HEARTBEAT_TIMEOUT_S = _num(
            "cluster.worker_heartbeat_timeout_secs", 10.0)
        self.MAX_TASK_ATTEMPTS = _num("cluster.task_max_attempts", 3, int)
        # memory-footprint task governor: admit tasks per worker by
        # projected input bytes (decoded, learned from producer channel
        # sizes) against this budget instead of pure slot count; 0
        # disables. An idle worker always admits one task, so the
        # governor can throttle but never deadlock a job.
        self.memory_budget_bytes = max(
            0, _num("cluster.memory_budget_mb", 512, int)) << 20
        # worker quarantine: N reported task failures inside a sliding
        # window blacklist the worker for a cool-off period
        self.quarantine = {
            "enabled": _on("cluster.quarantine.enabled"),
            "max_failures": _num("cluster.quarantine.max_failures", 5, int),
            "window_s": _num("cluster.quarantine.window_secs", 30.0),
            "duration_s": _num("cluster.quarantine.duration_secs", 60.0),
        }
        self.quarantined: Dict[str, float] = {}  # worker_id -> expiry ts
        # registration info of evicted workers: workers register only
        # once, so readmission (a transiently-evicted or cooled-off
        # worker that is still heartbeating) rebuilds the pool entry
        # from this
        self._readmit_info: Dict[str, dict] = {}
        # speculative execution: once a stage is mostly complete,
        # duplicate its slowest still-running tasks on other workers
        self.speculation = {
            "enabled": _on("cluster.speculation.enabled"),
            "fraction": _num("cluster.speculation.stage_fraction", 0.75),
            "multiplier": _num(
                "cluster.speculation.latency_multiplier", 1.5),
            "min_runtime_s": _num(
                "cluster.speculation.min_runtime_ms", 500.0) / 1000.0,
        }
        # multi-tenant admission control: the cross-job fair queue
        # (weighted DRR over stage-launch opportunities, per-tenant
        # concurrency + memory quotas, bounded queues with shedding)
        from . import admission as _adm
        self.admission = _adm.JobAdmissionQueue()
        # elastic autoscaler (exec/autoscaler.py): a pure policy over
        # recorded signals ticks from the probe loop; scale-down goes
        # through the graceful DRAINING lifecycle (channel handoff +
        # resident relaunch) instead of eviction
        from . import autoscaler as _asc
        self.autoscaler_cfg = _asc.AutoscalerConfig.load()
        self.autoscaler_state = _asc.PolicyState()
        # last N decisions (holds included) for /debug/autoscaler
        from collections import deque as _deque
        self.autoscaler_log: "_deque" = _deque(maxlen=64)
        self._as_next_tick = 0.0
        self._as_last_reason: Optional[str] = None
        # delta cursors for the tick's rate signals
        self._as_shed_seen: Dict[str, int] = {}
        self._as_stall_seen = 0.0
        # workers mid-drain: wid -> {"started", "addr", "reason",
        # "channels", "bytes"}; the scheduler, governor, speculation,
        # and continuous placement all skip these
        self.draining: Dict[str, dict] = {}

    def set_elastic(self, manager, min_workers: int = 1,
                    max_workers: int = 4, idle_secs: float = 60.0):
        """Enable demand-driven scale-up (saturated slots → new worker)
        and idle reaping down to ``min_workers``."""
        self.elastic = {"manager": manager, "min": min_workers,
                        "max": max_workers, "idle": idle_secs}

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    # -- rpc service -----------------------------------------------------
    def _scan_tables_view(self):
        out = {}
        # snapshot: gRPC handler threads race the actor thread on self.jobs
        for job in list(self.jobs.values()):
            for sid, table in job.graph.scan_tables.items():
                out[(job.job_id, sid)] = table
        # continuous pipelines' static tables (dimension/build sides):
        # resident tasks fetch them once at startup
        for cj in list(self.continuous.values()):
            for sid, table in cj.graph.scan_tables.items():
                out[(cj.job_id, sid)] = table
        return out

    def _service(self):
        def register(request: pb.RegisterWorkerRequest, context):
            self.handle.send(("register", request))
            return pb.RegisterWorkerResponse(accepted=True,
                                             driver_id=self.driver_id)

        def heartbeat(request: pb.HeartbeatRequest, context):
            self.handle.send(("heartbeat", request))
            return pb.HeartbeatResponse(known=True)

        def report(request: pb.ReportTaskStatusRequest, context):
            self.handle.send(("task_status", request))
            return pb.ReportTaskStatusResponse()

        def cancel_job(request: pb.CancelJobRequest, context):
            self.handle.send(("cancel", (request.job_id,
                                         request.reason or "client abort")))
            return pb.CancelJobResponse(canceled=True)

        def push_records(request: pb.PushRecordsRequest, context):
            # continuous root collection: top-stage resident tasks push
            # the pipeline's output here (the driver IS a data-plane
            # participant in continuous mode)
            cj = self.continuous.get(request.job_id)
            if cj is None:
                return cont.offer_response("unready")
            return cj.runner.root_offer(request)

        return grpc.method_handlers_generic_handler(_DRIVER_SERVICE, {
            "RegisterWorker": _unary(register, pb.RegisterWorkerRequest),
            "Heartbeat": _unary(heartbeat, pb.HeartbeatRequest),
            "ReportTaskStatus": _unary(report, pb.ReportTaskStatusRequest),
            "CancelJob": _unary(cancel_job, pb.CancelJobRequest),
            "PushRecords": _unary(push_records, pb.PushRecordsRequest),
            "FetchStream": grpc.unary_stream_rpc_method_handler(
                _fetch_stream_handler(None, self._scan_tables_view),
                request_deserializer=pb.FetchStreamRequest.FromString,
                response_serializer=lambda m: m.SerializeToString()),
        })

    def on_start(self):
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        self._server.add_generic_rpc_handlers((self._service(),))
        self.port = self._server.add_insecure_port(f"{self.host}:0")
        self._server.start()
        threading.Thread(target=self._probe_loop, daemon=True).start()

    def on_stop(self):
        self._probe_stop.set()
        if self._server is not None:
            self._server.stop(grace=0.5)

    def _probe_loop(self):
        while not self._probe_stop.wait(2.0):
            try:
                self.handle.send(("probe", None))
            except Exception:  # noqa: BLE001 — actor stopped
                return

    # -- actor -----------------------------------------------------------
    def receive(self, message):
        kind, payload = message
        if kind == "register":
            r: pb.RegisterWorkerRequest = payload
            if self.quarantined.get(r.worker_id, 0.0) > time.time():
                # a blacklisted worker re-registering inside its cool-off
                # window stays out of the pool for now; keep its info so
                # its heartbeats readmit it once the cool-off expires
                self._readmit_info[r.worker_id] = {
                    "addr": f"{r.host}:{r.port}", "slots": r.task_slots,
                    "ts": time.time()}
                return
            from ..catalog.system import SYSTEM
            SYSTEM.record_worker(r.worker_id, f"{r.host}:{r.port}",
                                 r.task_slots, "alive")
            self.workers[r.worker_id] = {
                "addr": f"{r.host}:{r.port}", "slots": r.task_slots,
                "last_seen": time.time(),
                "channel": grpc.insecure_channel(f"{r.host}:{r.port}"),
                "tasks": set(),
                "idle_since": time.time(),
                "projected": 0,
                "task_proj": {},
            }
            if self._starting_ts:
                self._starting_ts.pop(0)
            self._starting = len(self._starting_ts)
            self.pool_peak = max(self.pool_peak,
                                 len(self.workers) + self._starting)
            _record_metric("cluster.worker_count", len(self.workers))
        elif kind == "heartbeat":
            w = self.workers.get(payload.worker_id)
            if w is not None:
                w["last_seen"] = time.time()
            else:
                self._maybe_readmit(payload.worker_id)
            self._merge_heartbeat_metrics(payload)
        elif kind == "probe":
            self._probe_workers()
        elif kind == "submit":
            job, reply = payload
            self.jobs[job.job_id] = job
            from ..catalog.system import SYSTEM
            SYSTEM.record_job(job.job_id, len(job.graph.stages), "queued")
            # jobs pass through the cross-job fair queue: a shed job is
            # failed+done before the client's wait even starts (typed,
            # never a hang), an admitted one schedules immediately, the
            # rest wait for capacity under DRR
            self.admission.offer(job)
            self._drain_admission()
            if reply is not None:
                reply.set(job)
        elif kind == "task_status":
            self._on_task_status(payload)
            job = self.jobs.get(payload.job_id)
            if job is not None and not job.done.is_set():
                # a terminal report may have freed governor capacity
                self._drain_deferred(job)
            if job is not None:
                # ...or per-tenant quota headroom: quota-parked tasks
                # of the tenant's SIBLING jobs must not wait for the
                # 2s probe tick when this job's credit freed capacity
                for other in list(self.jobs.values()):
                    if other is not job and not other.done.is_set() \
                            and other.tenant == job.tenant \
                            and other.deferred:
                        self._drain_deferred(other)
            # a stage report is also the earliest deadline-check and
            # job-admission opportunity
            self._check_deadlines(time.time())
            self._drain_admission()
        elif kind == "cancel":
            job_id, reason = payload
            self._cancel_job(job_id, reason)
        elif kind == "cleanup":
            self._cleanup_job(payload)
        elif kind == "continuous_start":
            cj, reply = payload
            self._continuous_start(cj, reply)
        elif kind == "continuous_stop":
            self._continuous_stop(payload)
        elif kind == "call":
            # tests/tools: run a closure ON the actor thread — driver
            # state is single-threaded by construction, so out-of-band
            # inspection or drain/fault setup must ride the mailbox
            # like every other mutation
            fn, reply = payload
            try:
                out = fn(self)
            except Exception as e:  # noqa: BLE001 — reply, keep the loop
                out = e
            if reply is not None:
                reply.set(out)
        elif kind == "continuous_sync":
            # FIFO barrier (ContinuousJobRunner.sync_reports): by the
            # time this reply fires, every report enqueued before the
            # ask — including resident-task event flushes — has been
            # ingested
            payload.set(True)

    # -- continuous streaming: resident task scheduling ------------------
    def _continuous_start(self, cj: "cont._DriverContinuousJob",
                          reply) -> None:
        """Dispatch every stage of a continuous pipeline as LONG-LIVED
        resident tasks in one shot (the run-to-completion scheduler
        never re-enters): assign least-loaded workers, wire the push
        topology into each task's ``continuous_json``, and register the
        job so PushRecords / task reports / eviction route to it."""
        g = cj.graph
        work = [(s, p) for s in g.stages if not s.on_driver
                for p in range(s.num_partitions)]
        pool = sorted(((wid, w) for wid, w in self.workers.items()
                       if wid not in self.draining),
                      key=lambda kv: (len(kv[1]["tasks"]), kv[0]))
        if not pool:
            cj.runner.fail("no live workers")
            reply.set(None)
            return
        # a continuous pipeline occupies a concurrency slot like any
        # running job: a tenant at its max_concurrent_jobs cap (or a
        # full global cap) is shed with a typed retryable error — it
        # must not grab every worker with resident tasks the batch
        # admission path would have refused
        if not self.admission.admit_resident(cj.job_id, cj.tenant):
            cj.runner.fail(f"admission shed: tenant {cj.tenant!r} is "
                           f"at its concurrent-job cap")
            reply.set(None)
            return
        assign = {key: pool[i % len(pool)]
                  for i, key in enumerate(((s.stage_id, p)
                                           for s, p in work))}
        addr_of = {key: w["addr"] for key, (_wid, w) in assign.items()}
        consumers: Dict[int, List[Tuple[object, object]]] = {}
        for s in g.stages:
            for i in s.inputs:
                consumers.setdefault(i.stage_id, []).append((s, i.mode))
        rconf = cj.runner.conf
        self.continuous[cj.job_id] = cj
        for s, p in work:
            sid = s.stage_id
            outputs = []
            for c, mode in consumers.get(sid, ()):
                if c.on_driver:
                    outputs.append({"stage": c.stage_id, "mode": "merge",
                                    "addrs": [self.addr],
                                    "driver": True})
                    continue
                outputs.append({
                    "stage": c.stage_id, "mode": mode.value,
                    "addrs": [addr_of[(c.stage_id, cp)]
                              for cp in range(c.num_partitions)]})
            inputs = [{"stage": cont.SOURCE_STAGE, "mode": "source",
                       "parts": [0]}] if not s.inputs else []
            for i in s.inputs:
                up = g.stages[i.stage_id]
                if i.mode == jg.InputMode.FORWARD:
                    parts = [p % max(up.num_partitions, 1)]
                elif i.mode == jg.InputMode.BROADCAST:
                    parts = [0]
                else:  # shuffle | merge: every producer partition
                    parts = list(range(up.num_partitions))
                inputs.append({"stage": i.stage_id,
                               "mode": i.mode.value, "parts": parts})
            spec = {"generation": cj.generation, "inputs": inputs,
                    "outputs": outputs,
                    "credit_bytes": rconf["credit_bytes"],
                    "align_buffer_bytes": rconf["align_buffer_bytes"]}
            task = pb.TaskDefinition(
                job_id=cj.job_id, stage=sid, partition=p,
                attempt=cj.generation, plan=jg.encode_fragment(s.plan),
                num_partitions=s.num_partitions, driver_addr=self.addr,
                epoch=0, tenant=cj.tenant,
                runtime_filters_json=g.stage_filters.get(sid, ""),
                continuous_json=json.dumps(spec))
            if s.shuffle_keys is not None and s.num_channels > 1:
                task.shuffle_write.CopyFrom(pb.ShuffleWriteSpec(
                    key_columns=list(s.shuffle_keys),
                    num_channels=s.num_channels))
            wid, w = assign[(sid, p)]
            rpc = w["channel"].unary_unary(
                f"/{_WORKER_SERVICE}/RunTask",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=pb.RunTaskResponse.FromString)
            try:
                _call_with_retry(
                    lambda: rpc(pb.RunTaskRequest(task=task),
                                timeout=10),
                    site="rpc.call", key="RunTask", method="RunTask",
                    attempts=2)
            except (grpc.RpcError, faults.FaultInjectedError) as e:
                cj.runner.fail(f"resident dispatch s{sid}p{p} to "
                               f"{wid} failed: {e}")
                self._continuous_stop(cj.job_id)
                reply.set(None)
                return
            w["tasks"].add((cj.job_id, sid, p))
            w["idle_since"] = None
            cj.task_workers[(sid, p)] = wid
            events.emit(EventType.TASK_RESIDENT, query_id=cj.query_id,
                        job_id=cj.job_id, stage=sid, partition=p,
                        attempt=cj.generation, worker=wid)
        # admission accounting: a continuous job occupies its workers
        # indefinitely — register it for periodic DRR re-charging so it
        # cannot starve batch tenants (see JobAdmissionQueue.recharge)
        self.admission.note_resident(cj.job_id, cj.tenant,
                                     cost=max(1, len(work)))
        reply.set(dict(addr_of))

    def _continuous_stop(self, job_id: str) -> None:
        cj = self.continuous.pop(job_id, None)
        self.admission.release_resident(job_id)
        if cj is None:
            return
        self._continuous_drain[job_id] = (cj, time.time())
        for (sid, p), wid in list(cj.task_workers.items()):
            self._stop_task_on(wid, job_id, sid, p, "cleanup")
            w = self.workers.get(wid)
            if w is not None:
                self._release_task(w, (job_id, sid, p))
                if not w["tasks"]:
                    w["idle_since"] = time.time()
        for w in self.workers.values():
            rpc = w["channel"].unary_unary(
                f"/{_WORKER_SERVICE}/CleanUpJob",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=pb.CleanUpJobResponse.FromString)
            try:
                rpc(pb.CleanUpJobRequest(job_id=job_id), timeout=10)
            except grpc.RpcError:
                pass

    def _on_continuous_status(self, cj: "cont._DriverContinuousJob",
                              r: pb.ReportTaskStatusRequest) -> None:
        """Task reports of a continuous pipeline: readiness tracking,
        event-log merge (exactly-once via the same terminal-report
        dedupe as batch jobs), and failure propagation — a failed
        resident task fails the pipeline, which relaunches every stage
        from the last sealed marker under a NEW generation (zombie
        pushes are fenced by attempt/sequence checks)."""
        task_label = f"{r.job_id}/s{r.stage}p{r.partition}a{r.attempt}"
        if r.state == "running":
            if r.attempt == cj.generation:
                cj.running.add((r.stage, r.partition))
                if len(cj.running) >= len(cj.task_workers) and \
                        cj.task_workers:
                    cj.ready.set()
            if r.events_json and r.report_seq:
                # a resident task's periodic event flush: dedupe on the
                # flush sequence so at-least-once delivery merges each
                # drained buffer exactly once
                fk = (r.stage, r.partition, r.attempt, "flush",
                      int(r.report_seq))
                if fk not in cj.seen_reports:
                    cj.seen_reports.add(fk)
                    for blob in r.events_json:
                        try:
                            record = json.loads(blob)
                        except ValueError:
                            continue
                        events.EVENT_LOG.ingest(record,
                                                query_id=cj.query_id,
                                                task=task_label)
            return
        rk = (r.stage, r.partition, r.attempt, r.state, r.worker_id)
        if rk in cj.seen_reports:
            return
        cj.seen_reports.add(rk)
        for blob in r.events_json:
            try:
                record = json.loads(blob)
            except ValueError:
                continue
            events.EVENT_LOG.ingest(record, query_id=cj.query_id,
                                    task=task_label)
        w = self.workers.get(r.worker_id)
        if w is not None:
            self._release_task(w, (r.job_id, r.stage, r.partition))
            if not w["tasks"]:
                w["idle_since"] = time.time()
        if r.state == "failed" and r.attempt == cj.generation:
            cj.runner.fail(f"resident task s{r.stage}p{r.partition}: "
                           f"{r.error}")

    def _maybe_scale_up(self):
        e = self.elastic
        # prune pending starts that never registered (crashed at startup)
        # so a failed spawn can't cap the pool below max forever
        now = time.time()
        self._starting_ts = [t for t in self._starting_ts
                             if now - t < 30.0]
        self._starting = len(self._starting_ts)
        if len(self.workers) + self._starting >= e["max"]:
            return
        try:
            e["manager"].start_worker()
            self._starting_ts.append(now)
            self._starting += 1
            self.pool_peak = max(self.pool_peak,
                                 len(self.workers) + self._starting)
        except Exception:  # noqa: BLE001 — scale-up is best effort
            pass

    def _worker_hosts_live_output(self, addr: str) -> bool:
        for job in self.jobs.values():
            if job.done.is_set():
                continue
            for locs in job.locations.values():
                if any(a == addr for a in locs.values()):
                    return True
        return False

    def _reap_idle_workers(self, now: float):
        """Idle shrink. Default path: route the victim through the
        graceful DRAINING lifecycle — completed shuffle channels hand
        off to survivors instead of vanishing into producer re-runs.
        ``cluster.autoscaler.hard_reap`` restores the legacy hard-stop
        (the A/B control: reap kills live output, consumers re-run)."""
        e = self.elastic
        owns = getattr(e["manager"], "owns", None)
        stop = getattr(e["manager"], "stop_worker_id", None)
        hard = self.autoscaler_cfg.hard_reap
        for wid in list(self.workers):
            live = len(self.workers) - len(self.draining)
            if live <= e["min"]:
                return
            if wid in self.draining:
                continue
            w = self.workers[wid]
            idle = w.get("idle_since")
            if w["tasks"] or idle is None or now - idle < e["idle"]:
                continue
            # never strand a worker the manager can't actually stop
            if owns is not None and not owns(wid):
                continue
            if not hard:
                # one drain at a time: handoff must finish before the
                # next victim (the drain tick enforces ordering anyway,
                # but a burst of drains would race the survivors' load)
                if self.draining:
                    return
                self._begin_drain(wid, "idle_reap")
                return
            # legacy hard-reap: never kill completed stage outputs an
            # active job still needs
            if self._worker_hosts_live_output(w["addr"]):
                continue
            self.workers.pop(wid)
            _record_metric("cluster.worker_count", len(self.workers))
            from ..catalog.system import SYSTEM
            SYSTEM.record_worker(wid, w["addr"], w["slots"], "reaped")
            if stop is not None:
                try:
                    stop(wid)
                except Exception:  # noqa: BLE001
                    pass

    # -- elastic autoscaler + graceful drain -----------------------------
    def _autoscaler_signals(self, now: float):
        """One tick's observations as plain data (the policy input —
        and, embedded in the decision detail, the replay input)."""
        from . import autoscaler as _asc
        e = self.elastic or {}
        manager = e.get("manager")
        owns = getattr(manager, "owns", None)
        resident_on: Set[str] = set()
        for cj in self.continuous.values():
            resident_on.update(cj.task_workers.values())
        workers = []
        for wid, w in self.workers.items():
            if wid in self.draining:
                continue
            idle = w.get("idle_since")
            workers.append(_asc.WorkerSignals(
                worker_id=wid, tasks=len(w["tasks"]),
                slots=int(w["slots"]),
                idle_secs=0.0 if (w["tasks"] or idle is None)
                else max(0.0, now - idle),
                resident=wid in resident_on,
                live_output=self._worker_hosts_live_output(w["addr"]),
                stoppable=bool(owns is None or owns(wid))))
        queued = self.admission.queued_depths()
        shed_tot = dict(self.admission.shed_totals)
        shed = {}
        for t, n in shed_tot.items():
            d = n - self._as_shed_seen.get(t, 0)
            if d > 0:
                shed[t] = d
        self._as_shed_seen = shed_tot
        from .. import metrics as _m
        stall_tot = _m.REGISTRY.histogram_sum(
            "streaming.continuous.credit_stall_time")
        stall = max(0.0, stall_tot - self._as_stall_seen)
        self._as_stall_seen = stall_tot
        tenants = set(queued) | set(shed)
        weights = {t: float(self.admission.conf.policy(t).weight)
                   for t in tenants}
        return _asc.FleetSignals(
            pool=len(workers), draining=len(self.draining),
            pending_starts=self._starting,
            min_workers=int(e.get("min", len(workers))),
            max_workers=int(e.get("max", len(workers))),
            queued=queued, shed=shed, weights=weights,
            stall_secs=stall, workers=tuple(workers))

    def _autoscaler_tick(self, now: float):
        """Periodic policy evaluation (probe cadence, self-throttled to
        ``tick_secs``). Non-hold decisions and hold-reason EDGES emit
        replayable ``autoscaler_decision`` events; every decision lands
        in the /debug/autoscaler ring."""
        from . import autoscaler as _asc
        cfg = self.autoscaler_cfg
        if self.elastic is None or not cfg.enabled:
            return
        if now < self._as_next_tick:
            return
        self._as_next_tick = now + cfg.tick_secs
        signals = self._autoscaler_signals(now)
        decision, self.autoscaler_state = _asc.evaluate(
            cfg, self.autoscaler_state, signals)
        self.autoscaler_log.append({
            "ts": now, "action": decision.action,
            "worker": decision.worker, "reason": decision.reason,
            "pool": signals.pool, "draining": signals.draining})
        if decision.action != _asc.HOLD \
                or decision.reason != self._as_last_reason:
            events.emit(EventType.AUTOSCALER_DECISION, query_id="",
                        action=decision.action, worker=decision.worker,
                        reason=decision.reason, pool=signals.pool,
                        detail=decision.detail_json())
        self._as_last_reason = decision.reason
        if decision.action == _asc.SCALE_UP:
            _record_metric("cluster.autoscaler.scale_up_count", 1,
                           reason=decision.reason)
            self._maybe_scale_up()
        elif decision.action == _asc.SCALE_DOWN:
            _record_metric("cluster.autoscaler.scale_down_count", 1,
                           reason=decision.reason)
            if cfg.hard_reap:
                self._hard_stop(decision.worker)
            else:
                self._begin_drain(decision.worker, decision.reason)

    def _hard_stop(self, wid: str):
        """The A/B control (``cluster.autoscaler.hard_reap``): execute a
        policy scale-down as the legacy hard stop. Completed shuffle
        channels die with the worker and every consumer pays a producer
        re-run — exactly the cost the drain lifecycle exists to avoid."""
        if wid not in self.workers:
            return
        e = self.elastic or {}
        stop = getattr(e.get("manager"), "stop_worker_id", None)
        self._evict_worker(wid, "hard_reap")
        # a deliberate retirement is not a transient blip: no readmission
        self._readmit_info.pop(wid, None)
        if stop is not None:
            try:
                stop(wid)
            except Exception:  # noqa: BLE001 — manager stop is best-effort
                pass

    def _begin_drain(self, wid: str, reason: str):
        """Enter the DRAINING state: stop assigning (every placement
        site skips draining workers), relaunch resident continuous
        stages on survivors now, and let the drain tick hand off sealed
        channels before retirement. The worker stays registered and
        heartbeating throughout — drain is scheduling state, not
        eviction."""
        w = self.workers.get(wid)
        if w is None or wid in self.draining:
            return
        self.draining[wid] = {"started": time.time(), "addr": w["addr"],
                              "reason": reason, "channels": 0,
                              "bytes": 0}
        _record_metric("cluster.worker.draining_count",
                       len(self.draining))
        events.emit(EventType.WORKER_DRAIN, query_id="", worker=wid,
                    phase="begin", channels=0, bytes=0, ms=0.0)
        from ..catalog.system import SYSTEM
        SYSTEM.record_worker(wid, w["addr"], w["slots"], "draining")
        # a resident continuous stage cannot move mid-interval: fail the
        # pipeline so the streaming query relaunches EVERY stage from
        # the last sealed marker under a new generation (PR 15), placed
        # on the surviving pool (the placement site skips us)
        for cj in list(self.continuous.values()):
            if any(tw == wid for tw in cj.task_workers.values()):
                cj.runner.fail(f"worker {wid} draining")

    def _advance_drains(self, now: float):
        """Drive every in-flight drain one step: wait for running tasks
        to finish (nothing new lands on a draining worker), hand off
        sealed channels, then retire via the owning manager. A drain
        that exceeds its timeout falls back to the eviction path —
        producer re-run recovers whatever did not move."""
        for wid in list(self.draining):
            st = self.draining[wid]
            w = self.workers.get(wid)
            if w is None:
                # crashed/evicted mid-drain: _evict_worker already
                # repaired the jobs (and closed the drain record when
                # it went through the eviction hook)
                self._finish_drain(wid, "abort")
                continue
            if now - st["started"] > \
                    self.autoscaler_cfg.drain_timeout_secs:
                self._finish_drain(wid, "abort")
                self._evict_worker(wid, "drain-timeout")
                self._retire_worker_process(wid)
                continue
            if w["tasks"]:
                continue
            if not self._drain_handoff(wid, w, st):
                continue  # transient handoff failure: retry next tick
            self._finish_drain(wid, "done")
            self._retire_drained(wid, w)

    def _drain_handoff(self, wid: str, w: dict, st: dict) -> bool:
        """Move every completed shuffle output a live job still needs
        from the draining worker to survivors (PullChannels: the
        survivor pulls raw channel bytes over the data plane and
        re-seals them locally), then repoint ``job.locations`` so
        consumers fetch from the new owner. True = nothing left."""
        addr = w["addr"]
        done = True
        for job in list(self.jobs.values()):
            if job.done.is_set():
                continue
            for stage_id, locs in list(job.locations.items()):
                mine = [p for p, a in locs.items() if a == addr]
                if not mine:
                    continue
                stage = job.graph.stages[stage_id]
                if stage.shuffle_keys is not None \
                        and stage.num_channels > 1:
                    channels = list(range(stage.num_channels))
                else:
                    channels = [-1]
                for p in mine:
                    survivors = sorted(
                        ((swid, sw)
                         for swid, sw in self.workers.items()
                         if swid != wid
                         and swid not in self.draining),
                        key=lambda kv: (len(kv[1]["tasks"]), kv[0]))
                    if not survivors:
                        return False  # nowhere to move yet
                    moved = False
                    for swid, sw in survivors:
                        resp = self._pull_channels_rpc(
                            sw, addr, job, stage_id, p, channels)
                        if resp is not None and resp.ok:
                            locs[p] = sw["addr"]
                            st["channels"] += int(resp.channels_moved)
                            st["bytes"] += int(resp.bytes_moved)
                            _record_metric(
                                "cluster.autoscaler.handoff_bytes",
                                int(resp.bytes_moved))
                            events.emit(
                                EventType.WORKER_DRAIN, query_id="",
                                worker=wid, phase="handoff",
                                channels=st["channels"],
                                bytes=st["bytes"],
                                ms=round((time.time() - st["started"])
                                         * 1000.0, 3))
                            moved = True
                            break
                    if not moved:
                        done = False
        return done

    def _pull_channels_rpc(self, sw: dict, peer_addr: str, job: "_Job",
                           stage_id: int, partition: int,
                           channels: List[int]):
        rpc = sw["channel"].unary_unary(
            f"/{_WORKER_SERVICE}/PullChannels",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.PullChannelsResponse.FromString)
        try:
            return _call_with_retry(
                lambda: rpc(pb.PullChannelsRequest(
                    peer_addr=peer_addr, job_id=job.job_id,
                    stage=stage_id, partition=partition,
                    epoch=job.epoch, channels=channels), timeout=30),
                site="rpc.call", key="PullChannels",
                method="PullChannels", attempts=2)
        except (grpc.RpcError, faults.FaultInjectedError):
            return None

    def _finish_drain(self, wid: str, phase: str):
        st = self.draining.pop(wid, None)
        _record_metric("cluster.worker.draining_count",
                       len(self.draining))
        if st is None:
            return
        dur = time.time() - st["started"]
        _record_metric("cluster.autoscaler.drain_duration", dur)
        events.emit(EventType.WORKER_DRAIN, query_id="", worker=wid,
                    phase=phase, channels=st["channels"],
                    bytes=st["bytes"], ms=round(dur * 1000.0, 3))

    def _retire_drained(self, wid: str, w: dict):
        """Retire a fully-drained worker via the owning manager — NOT
        eviction: its outputs moved, so no job repair, no location
        invalidation, no producer re-runs."""
        self.workers.pop(wid, None)
        _record_metric("cluster.worker_count", len(self.workers))
        try:
            _fleet().drop_worker_gauges(wid)
        except Exception:  # noqa: BLE001 — telemetry never blocks
            pass
        try:
            w["channel"].close()
        except Exception:  # noqa: BLE001
            pass
        from ..catalog.system import SYSTEM
        SYSTEM.record_worker(wid, w["addr"], w["slots"], "drained")
        self._retire_worker_process(wid)

    def _retire_worker_process(self, wid: str):
        e = self.elastic or {}
        manager = e.get("manager")
        stop = getattr(manager, "stop_worker_id", None)
        owns = getattr(manager, "owns", None)
        if stop is None or (owns is not None and not owns(wid)):
            return
        try:
            stop(wid)
        except Exception:  # noqa: BLE001 — retirement is best effort
            pass

    def _probe_workers(self):
        now = time.time()
        self.quarantined = {wid: t for wid, t in self.quarantined.items()
                            if t > now}
        # readmission info only matters while the worker still
        # heartbeats; prune entries for workers that stayed silent well
        # past any cool-off (dead-worker churn must not grow the dict)
        ttl = self.quarantine["duration_s"] + 600.0
        self._readmit_info = {
            wid: info for wid, info in self._readmit_info.items()
            if now - info.get("ts", now) < ttl}
        # stopped continuous pipelines stay drainable for late terminal
        # reports (buffered worker events) for one short window only
        self._continuous_drain = {
            jid: (cj, ts) for jid, (cj, ts)
            in self._continuous_drain.items() if now - ts < 30.0}
        # drains advance BEFORE reaping/policy so a finished handoff
        # frees its slot in the one-drain-at-a-time pipeline this tick
        self._advance_drains(now)
        if self.elastic is not None:
            if self.autoscaler_cfg.enabled:
                # the policy owns scale-down (occupancy + idle with
                # hysteresis); running the legacy idle reaper too would
                # double-drive the drain pipeline
                self._autoscaler_tick(now)
            else:
                self._reap_idle_workers(now)
        lost = [wid for wid, w in self.workers.items()
                if now - w["last_seen"] > self.HEARTBEAT_TIMEOUT_S]
        for wid in lost:
            self._evict_worker(wid, "lost")
        self._maybe_speculate(now)
        # governor backstop: deferred tasks retry every probe even when
        # no terminal report fires (e.g. capacity freed by eviction)
        for job in list(self.jobs.values()):
            if not job.done.is_set():
                self._drain_deferred(job)
        # admission backstop: expire queued jobs past their queue budget
        # or deadline, cancel running jobs past their deadline, and
        # admit whatever the fair queue can now run; long-lived
        # (continuous) jobs re-charge their resident-task occupancy so
        # they keep paying DRR cost instead of riding a one-time debit
        self._check_deadlines(now)
        self.admission.recharge(now)
        self.admission.poll(now)
        self._drain_admission(now)

    def _drain_admission(self, now: Optional[float] = None):
        for job in self.admission.drain(now):
            if job.done.is_set():
                continue
            from ..catalog.system import SYSTEM
            SYSTEM.record_job(job.job_id, len(job.graph.stages),
                              "running")
            self._schedule_ready_stages(job)
        # jobs still queued after a drain pass mean the pool is the
        # bottleneck RIGHT NOW — start a worker here instead of waiting
        # out the autoscaler's hysteresis (the policy still owns
        # scale-down, and _maybe_scale_up enforces the max/pending cap)
        if self.elastic is not None and self.admission.total_queued():
            self._maybe_scale_up()

    def _check_deadlines(self, now: float):
        """Per-query deadlines cancel through the existing CancelJob
        path: cooperative worker-side stop, then the client-driven
        cleanup wipes partial shuffle outputs via CleanUpJob. Queued
        (not yet admitted) jobs are shed by ``admission.poll`` instead,
        so the shed/cancel event streams stay disjoint."""
        for job in list(self.jobs.values()):
            if job.done.is_set() or job.deadline_ts is None or \
                    not job.admitted or now < job.deadline_ts:
                continue
            overrun = round((now - job.deadline_ts) * 1000.0, 3)
            _record_metric("cluster.admission.deadline_cancel_count", 1,
                           tenant=job.tenant)
            _record_metric("cluster.admission.deadline_overrun_time",
                           overrun / 1000.0, tenant=job.tenant)
            events.emit(EventType.DEADLINE_CANCEL,
                        query_id=job.query_id, trace_id=_jtrace(job),
                        job_id=job.job_id, tenant=job.tenant,
                        deadline_ms=job.deadline_ms, overrun_ms=overrun)
            job.error_kind = "deadline"
            self._cancel_job(job.job_id,
                             f"deadline ({job.deadline_ms:.0f}ms) "
                             f"exceeded")

    def _evict_worker(self, wid: str, reason: str):
        """Remove a dead/blacklisted worker and repair every live job:
        its RUNNING tasks re-launch elsewhere (all of them, not just the
        one that exposed the failure) and its COMPLETED stream outputs
        are invalidated so their producer partitions re-run."""
        w = self.workers.pop(wid, None)
        if w is None:
            return
        if wid in self.draining:
            # crash/failure mid-drain: close the drain record — the
            # repair below (location invalidation + producer re-run)
            # recovers whatever the handoff had not moved yet
            self._finish_drain(wid, "abort")
        _record_metric("cluster.worker_count", len(self.workers))
        # the fleet view stops serving the dead worker's stale gauges
        # (counter/histogram history stays: it is still true)
        try:
            _fleet().drop_worker_gauges(wid)
        except Exception:  # noqa: BLE001 — telemetry never blocks eviction
            pass
        events.emit(EventType.WORKER_EVICT, query_id="", worker=wid,
                    reason=reason)
        try:
            w["channel"].close()
        except Exception:  # noqa: BLE001 — eviction must not fail
            pass
        # a live worker evicted for a transient blip (dispatch failure,
        # missed heartbeats under load) keeps heartbeating: remember its
        # registration so _maybe_readmit can restore it instead of
        # halving a static pool forever
        self._readmit_info[wid] = {"addr": w["addr"], "slots": w["slots"],
                                   "ts": time.time()}
        from ..catalog.system import SYSTEM
        SYSTEM.record_worker(wid, w["addr"], w["slots"], reason)
        relaunch: List[Tuple[_Job, int, int]] = []
        for (job_id, stage, partition) in list(w["tasks"]):
            job = self.jobs.get(job_id)
            if job is not None and not job.done.is_set():
                relaunch.append((job, stage, partition))
        w["tasks"].clear()
        for job in list(self.jobs.values()):
            if job.done.is_set():
                continue
            for stage_id, locs in job.locations.items():
                dead = [p for p, a in locs.items() if a == w["addr"]]
                for p in dead:
                    del locs[p]
                    # re-run whether the stage was launched whole
                    # (scheduled) or per-partition (pipelined)
                    if stage_id in job.scheduled or \
                            (stage_id, p) in job.launched:
                        relaunch.append((job, stage_id, p))
        # a continuous pipeline cannot survive losing a resident task's
        # worker mid-interval (the in-flight records between markers
        # died with it): fail the pipeline — the streaming query
        # relaunches EVERY stage from the last sealed marker under a
        # new generation, and this zombie's late pushes are fenced
        for cj in list(self.continuous.values()):
            if any(tw == wid for tw in cj.task_workers.values()):
                cj.runner.fail(f"worker {wid} lost")
        seen: Set[Tuple[str, int, int]] = set()
        for job, stage, partition in relaunch:
            if (job.job_id, stage, partition) in seen:
                continue
            seen.add((job.job_id, stage, partition))
            # drop the dead worker's in-flight attempts; if a twin attempt
            # survives on another worker it covers this partition
            live = job.live.get((stage, partition), {})
            for att in [a for a, lw in live.items() if lw == wid]:
                live.pop(att)
            if live:
                continue
            # the dead worker may have held BOTH a consumer task and its
            # producer's sealed output: the producer must re-run before
            # the consumer can resolve inputs, so park the consumer (the
            # producer's completion report fires _fire_pending) instead
            # of letting _launch_task fail the job on incomplete inputs
            if not self._partition_ready(job, job.graph.stages[stage],
                                         partition):
                job.pending.add((stage, partition))
                continue
            self._launch_task(job, stage, partition,
                              self.attempt_of(job, stage, partition) + 1,
                              reason="evicted")

    @staticmethod
    def attempt_of(job: _Job, stage: int, partition: int) -> int:
        return job.attempts.get((stage, partition), 0)

    def _attempt_cap(self, job: _Job, stage: int, partition: int) -> int:
        """Attempt-id budget for one task: the configured maximum plus
        one per attempt id a speculative twin consumed — speculation
        must not reduce how many real failures the task can survive."""
        return self.MAX_TASK_ATTEMPTS + \
            job.attempt_allowance.get((stage, partition), 0)

    # -- memory-footprint task governor ---------------------------------
    def _projected_task_bytes(self, job: _Job, stage_id: int,
                              partition: int) -> Optional[int]:
        """Project one pending task's decoded input footprint from the
        per-channel byte sizes its producers reported: shuffle inputs
        take their hash channel from every producer partition, forward
        inputs the matching partition, merge/broadcast everything. Wire
        bytes scale by each producer's raw/compressed ratio so the
        budget compares decoded (in-memory) bytes. None = some producer
        size is still unknown → fall back to slot scheduling."""
        stage = job.graph.stages[stage_id]
        if not stage.inputs:
            return None  # leaf scans: no learned sizes to project from
        total = 0
        for i in stage.inputs:
            up = job.graph.stages[i.stage_id]
            if i.fetch_plan is not None:
                # adaptive rewrite: project exactly the pairs this task
                # fetches (recomputed footprint after coalesce/split)
                from . import adaptive as _aqe
                pairs = i.fetch_plan[partition] \
                    if partition < len(i.fetch_plan) else ()
                decoded = {}  # per-partition memo: pairs share producers
                for p, c in pairs:
                    got = decoded.get(p)
                    if got is None:
                        got = _aqe._decoded_entry(job, i.stage_id, p)
                        if got is None:
                            return None
                        decoded[p] = got
                    dec, raw = got
                    if c < 0:  # -1 whole unsplit output | -2 all channels
                        total += int(raw)
                    else:
                        total += int(dec[c]) if c < len(dec) else 0
                continue
            if i.mode == jg.InputMode.FORWARD:
                # a pipelined FORWARD consumer reads ONLY its matching
                # producer partition — and launches while sibling
                # partitions are still running, so requiring every
                # producer size here would disable the governor for
                # pipelined stages entirely
                entry = job.channel_bytes.get((i.stage_id, partition))
                if entry is None:
                    return None
                chans, raw = entry
                comp_total = sum(chans)
                scale = (raw / comp_total) if comp_total else 1.0
                total += int(sum(chans) * scale)
                continue
            for p in range(up.num_partitions):
                entry = job.channel_bytes.get((i.stage_id, p))
                if entry is None:
                    return None
                chans, raw = entry
                comp_total = sum(chans)
                scale = (raw / comp_total) if comp_total else 1.0
                if i.mode == jg.InputMode.SHUFFLE:
                    wire = chans[partition] if partition < len(chans) \
                        else 0
                else:  # merge | broadcast
                    wire = sum(chans)
                total += int(wire * scale)
        return total

    def _release_task(self, w: dict, key: Tuple[str, int, int]) -> None:
        """Unregister a task from a worker AND release its admitted
        footprint from the governor's per-worker projection and the
        owning tenant's quota ledger."""
        w["tasks"].discard(key)
        proj = w.get("task_proj", {}).pop(key, 0)
        if proj:
            w["projected"] = max(0, w.get("projected", 0) - proj)
        self.admission.credit(key[0], key[1], key[2])

    def _drain_deferred(self, job: _Job) -> None:
        """Relaunch governor-deferred tasks now that capacity may have
        freed; a task that still does not fit simply re-defers."""
        if job.done.is_set():
            job.deferred = []
            return
        if not job.deferred:
            return
        pending, job.deferred = job.deferred, []
        for entry in pending:
            stage_id, partition, attempt, exclude = entry
            if partition in job.locations[stage_id] or \
                    job.live.get((stage_id, partition)):
                continue  # covered by another path in the meantime
            # an input producer may have been EVICTED between deferral
            # and drain: launching now would fail the whole job on the
            # incomplete-input guard, so stay parked until the producer
            # re-run restores the location (probe ticks retry)
            if not self._partition_ready(job, job.graph.stages[stage_id],
                                         partition):
                job.deferred.append(entry)
                continue
            self._launch_task(job, stage_id, partition, attempt,
                              exclude=set(exclude) if exclude else None)

    # -- scheduling ------------------------------------------------------
    def _stage_complete(self, job: _Job, stage_id: int) -> bool:
        stage = job.graph.stages[stage_id]
        return len(job.locations[stage_id]) >= stage.num_partitions

    def _partition_ready(self, job: _Job, stage, partition: int) -> bool:
        """FORWARD inputs need only the matching upstream partition; all
        other modes need the whole upstream stage (reference: the
        reference's OutputMode::Pipelined + task regions — consumer tasks
        co-run with still-executing producer stages)."""
        for i in stage.inputs:
            if i.mode == jg.InputMode.FORWARD:
                if partition not in job.locations[i.stage_id]:
                    return False
            elif not self._stage_complete(job, i.stage_id):
                return False
        return True

    def _schedule_ready_stages(self, job: _Job):
        for stage in job.graph.stages:
            if stage.on_driver:
                continue
            if not all(self._stage_complete(job, b)
                       for b in getattr(stage, "launch_after", ())):
                # adaptive scheduling barrier: the broadcast-conversion
                # decision window — cleared by the barrier stage
                # completing, which re-enters this scheduler
                continue
            pipelined = any(i.mode == jg.InputMode.FORWARD
                            for i in stage.inputs)
            if pipelined:
                for partition in range(stage.num_partitions):
                    key = (stage.stage_id, partition)
                    if key in job.launched:
                        continue
                    if self._partition_ready(job, stage, partition):
                        job.launched.add(key)
                        _note_stage_submit(job, stage, True)
                        self._launch_task(job, stage.stage_id, partition, 0)
                continue
            if stage.stage_id in job.scheduled:
                continue
            if all(self._stage_complete(job, i.stage_id)
                   for i in stage.inputs):
                job.scheduled.add(stage.stage_id)
                _note_stage_submit(job, stage, False)
                for partition in range(stage.num_partitions):
                    self._launch_task(job, stage.stage_id, partition, 0)
        root = job.graph.root
        if root.on_driver and not job.done.is_set() and \
                all(self._stage_complete(job, i.stage_id)
                    for i in root.inputs):
            job.done.set()

    def _launch_task(self, job: _Job, stage_id: int, partition: int,
                     attempt: int, reason: str = "",
                     exclude: Optional[Set[str]] = None,
                     speculative: bool = False) -> bool:
        """Dispatch one task attempt; True when a worker accepted it."""
        if job.done.is_set():
            return False
        if attempt >= self._attempt_cap(job, stage_id, partition):
            if speculative:
                return False  # speculation must never fail a healthy job
            job.failed = (f"stage {stage_id} task {partition} exceeded "
                          f"max attempts: {job.last_error}")
            job.done.set()
            return False
        if reason:
            job.retry_count += 1
            _record_metric("cluster.task.retry_count", 1, reason=reason)
        stage = job.graph.stages[stage_id]
        inputs = []
        for i in stage.inputs:
            up = job.graph.stages[i.stage_id]
            # pipelined FORWARD consumers launch before sibling upstream
            # partitions finish; only THIS task's partition must resolve
            addrs = [job.locations[i.stage_id].get(p, "")
                     for p in range(up.num_partitions)]
            if i.mode == jg.InputMode.FORWARD:
                missing = [] if addrs[partition] else [partition]
            else:
                missing = [p for p in range(up.num_partitions)
                           if not addrs[p]]
            if missing:
                # a recovery race, not a scheduling bug: scheduling only
                # launches once inputs are complete, so a hole here means
                # a producer's sealed output vanished (hard stop, crash)
                # after this consumer was dispatched or queued for retry.
                # Park the consumer and make sure every missing producer
                # partition is re-running — its completion report fires
                # _fire_pending and the consumer launches then.
                if speculative:
                    return False  # never park a duplicate
                job.pending.add((stage_id, partition))
                for p in missing:
                    if not job.live.get((i.stage_id, p)):
                        self._launch_task(
                            job, i.stage_id, p,
                            self.attempt_of(job, i.stage_id, p) + 1,
                            reason="input_lost")
                return False
            loc = pb.StageInputLocations(
                stage_id=i.stage_id, mode=i.mode.value, worker_addrs=addrs)
            if i.fetch_plan is not None and \
                    partition < len(i.fetch_plan):
                # adaptive fetch assignment for THIS task
                pairs = i.fetch_plan[partition]
                loc.fetch_parts.extend(p for p, _c in pairs)
                loc.fetch_channels.extend(c for _p, c in pairs)
            inputs.append(loc)
        task = pb.TaskDefinition(
            job_id=job.job_id, stage=stage_id, partition=partition,
            attempt=attempt, plan=encode_cached(job, stage),
            num_partitions=stage.num_partitions, inputs=inputs,
            driver_addr=self.addr, epoch=job.epoch, tenant=job.tenant,
            runtime_filters_json=job.graph.stage_filters.get(stage_id, ""))
        if stage.shuffle_keys is not None and stage.num_channels > 1:
            task.shuffle_write.CopyFrom(pb.ShuffleWriteSpec(
                key_columns=list(stage.shuffle_keys),
                num_channels=stage.num_channels))
        # memory governor + tenant quota: project this task's input
        # footprint once (observed producer channel sizes); the worker
        # admission check runs against each candidate below, the tenant
        # quota check here — a tenant over its projected-bytes quota
        # parks the task until its own tasks release capacity (a tenant
        # with nothing debited always admits: throttle, never deadlock)
        quota = self.admission.tenant_quota(job.tenant)
        proj = self._projected_task_bytes(job, stage_id, partition) \
            if (self.memory_budget_bytes > 0 or quota > 0) else None
        if quota > 0 and proj is not None and \
                not self.admission.quota_admit(job.tenant, proj):
            if speculative:
                return False  # never park a duplicate
            job.deferred.append((
                stage_id, partition, attempt,
                frozenset(exclude) if exclude else None))
            _record_metric("cluster.quota.deferred_count", 1,
                           tenant=job.tenant)
            events.emit(EventType.ADMISSION_DEFER,
                        query_id=job.query_id, trace_id=_jtrace(job),
                        job_id=job.job_id, tenant=job.tenant,
                        reason="quota", stage=stage_id,
                        partition=partition)
            return True  # parked: _drain_deferred relaunches
        # the per-worker governor filter below only runs when the worker
        # memory budget is configured; a quota-only projection must not
        # engage it
        if self.memory_budget_bytes <= 0:
            gproj = None
        else:
            gproj = proj
        # dispatch loop (NOT recursion): a flapping pool can no longer
        # blow the stack, and each failed dispatch evicts its worker and
        # reschedules ALL of that worker's running tasks, not just this
        # one. The budget bounds a pathological pool where every worker
        # rejects the dispatch.
        budget = max(4, 2 * len(self.workers))
        while not job.done.is_set():
            candidates = sorted(
                ((wid, w) for wid, w in self.workers.items()
                 if (not exclude or wid not in exclude)
                 and wid not in self.draining),
                key=lambda kv: len(kv[1]["tasks"]))
            if not candidates:
                if speculative:
                    return False  # nowhere to duplicate: keep the original
                if exclude:
                    # exclusion is a preference (avoid the worker that
                    # just failed), not a constraint: fall back to the
                    # full pool rather than failing the job
                    exclude = None
                    continue
                job.failed = "no live workers"
                job.done.set()
                return False
            if gproj is not None:
                # admit by projected bytes against the budget; a worker
                # with no admitted tasks always admits one (progress
                # guarantee), so the governor throttles wide shuffles
                # without ever deadlocking a job
                admissible = [
                    (wid, w) for wid, w in candidates
                    if not w["tasks"] or
                    w.get("projected", 0) + gproj <=
                    self.memory_budget_bytes]
                if not admissible:
                    if speculative:
                        return False  # never park a duplicate
                    job.deferred.append((
                        stage_id, partition, attempt,
                        frozenset(exclude) if exclude else None))
                    job.governor_deferred += 1
                    _record_metric("cluster.governor.deferred_count", 1)
                    events.emit(EventType.GOVERNOR_DEFER,
                                query_id=job.query_id,
                                trace_id=_jtrace(job),
                                job_id=job.job_id, stage=stage_id,
                                partition=partition, attempt=attempt)
                    return True  # parked: _drain_deferred relaunches
                candidates = admissible
            wid, w = candidates[0]
            if self.elastic is not None and len(w["tasks"]) >= w["slots"]:
                self._maybe_scale_up()
            w["tasks"].add((job.job_id, stage_id, partition))
            w["idle_since"] = None
            if gproj is not None:
                w.setdefault("task_proj", {})[
                    (job.job_id, stage_id, partition)] = gproj
                w["projected"] = w.get("projected", 0) + gproj
                _record_metric("cluster.governor.admitted_count", 1)
                _record_metric("cluster.governor.projected_bytes",
                               w["projected"])
                events.emit(EventType.GOVERNOR_ADMIT,
                            query_id=job.query_id,
                            trace_id=_jtrace(job), job_id=job.job_id,
                            stage=stage_id, partition=partition,
                            worker=wid, projected_bytes=int(gproj))
            if quota > 0 and proj is not None:
                # tenant-quota ledger: debit the observed-size
                # projection now; _release_task credits it back on any
                # terminal report or dispatch failure
                self.admission.debit(job, stage_id, partition, proj)
            rpc = w["channel"].unary_unary(
                f"/{_WORKER_SERVICE}/RunTask",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=pb.RunTaskResponse.FromString)
            try:
                with tr.span(f"driver:launch s{stage_id}p{partition}",
                             {"job_id": job.job_id, "worker": wid},
                             parent=job.trace_ctx) as ls:
                    # RunTask only enqueues on the worker actor, so a
                    # short deadline and a small retry budget keep the
                    # single-threaded driver's worst-case stall on a
                    # wedged worker well under the old 30s, not above it
                    _call_with_retry(
                        lambda: rpc(
                            pb.RunTaskRequest(task=task), timeout=10,
                            metadata=[("traceparent",
                                       f"00-{ls.trace_id}-{ls.span_id}-01")]),
                        site="rpc.call", key="RunTask", method="RunTask",
                        attempts=2)
                # the attempt number is committed only now: a launch
                # that never dispatched (e.g. a failed speculative twin)
                # must not burn one of the task's attempts
                job.attempts[(stage_id, partition)] = max(
                    attempt, job.attempts.get((stage_id, partition), 0))
                job.live.setdefault((stage_id, partition), {})[attempt] = wid
                job.started[(stage_id, partition, attempt)] = time.time()
                # a parked consumer relaunches at the SAME attempt
                # number: drop that attempt's terminal records so the
                # report dedupe only swallows retransmissions, never the
                # fresh execution's genuine outcome
                job.seen_reports = {
                    rk for rk in job.seen_reports
                    if rk[:3] != (stage_id, partition, attempt)}
                events.emit(
                    EventType.TASK_DISPATCH, query_id=job.query_id,
                    trace_id=_jtrace(job), job_id=job.job_id,
                    stage=stage_id, partition=partition,
                    attempt=attempt, worker=wid,
                    reason=reason or ("speculative" if speculative
                                      else ""))
                return True
            except (grpc.RpcError, faults.FaultInjectedError):
                # dispatch failure = dead worker: evict it (rescheduling
                # its OTHER tasks) and redo the SAME attempt elsewhere (a
                # launch failure is not a task failure)
                self._release_task(w, (job.job_id, stage_id, partition))
                self._evict_worker(wid, "dispatch-failure")
                _record_metric("cluster.task.retry_count", 1,
                               reason="dispatch")
                budget -= 1
                if budget <= 0:
                    if speculative:
                        return False
                    job.failed = (f"stage {stage_id} task {partition}: "
                                  f"dispatch retry budget exhausted")
                    job.done.set()
                    return False
        return False

    def _on_task_status(self, r: pb.ReportTaskStatusRequest):
        from ..catalog.system import SYSTEM
        SYSTEM.record_task(r.job_id, r.stage, r.partition, r.attempt,
                           r.state, r.worker_id, int(r.rows_out))
        cj = self.continuous.get(r.job_id)
        if cj is None:
            drained = self._continuous_drain.get(r.job_id)
            if drained is not None:
                cj = drained[0]
        if cj is not None:
            self._on_continuous_status(cj, r)
            return
        job = self.jobs.get(r.job_id)
        if job is None or job.done.is_set():
            return
        w = self.workers.get(r.worker_id)
        key = (r.stage, r.partition)
        live = job.live.get(key, {})
        if r.state in ("succeeded", "failed", "canceled"):
            # workers retry status reports (at-least-once delivery): a
            # duplicate terminal report must not re-trigger ANY side
            # effect — not the FETCH_FAILED teardown below, and not the
            # w["tasks"] discard either (the same task may have been
            # relaunched onto this worker in the meantime; unregistering
            # it would let the idle reaper take a busy worker)
            rk = (r.stage, r.partition, r.attempt, r.state, r.worker_id)
            if rk in job.seen_reports:
                return
            job.seen_reports.add(rk)
            # merge the worker's shipped task events into the cluster-
            # wide log, stamped with the owning query's envelope (the
            # dedupe above makes the merge exactly-once despite
            # at-least-once report delivery)
            task_label = f"{r.job_id}/s{r.stage}p{r.partition}" \
                         f"a{r.attempt}"
            for blob in r.events_json:
                try:
                    record = json.loads(blob)
                except ValueError:
                    continue
                events.EVENT_LOG.ingest(record, query_id=job.query_id,
                                        trace_id=_jtrace(job),
                                        task=task_label)
            if w is not None:
                self._release_task(w, (r.job_id, r.stage, r.partition))
                if not w["tasks"]:
                    w["idle_since"] = time.time()
        if r.state == "succeeded":
            if r.partition in job.locations[r.stage]:
                return  # a twin attempt already won — late duplicate
            if w is None:
                # the worker was evicted before its success report arrived;
                # its streams died with it. A surviving twin attempt will
                # cover the partition; otherwise run the task again.
                if not live:
                    self._launch_task(job, r.stage, r.partition,
                                      self.attempt_of(job, r.stage,
                                                      r.partition) + 1,
                                      reason="evicted")
                return
            if live and r.attempt not in live:
                return  # fenced out: a stale attempt may not publish
            started = job.started.get((r.stage, r.partition, r.attempt))
            if started is not None:
                job.durations.setdefault(r.stage, []).append(
                    time.time() - started)
            # first live attempt wins; losers are canceled on their workers
            for att, lw in live.items():
                if att != r.attempt:
                    self._stop_task_on(lw, r.job_id, r.stage, r.partition,
                                       "speculation_loser")
            job.live.pop(key, None)
            if key in job.speculated and \
                    r.attempt == job.spec_attempt.get(key):
                job.spec_won += 1
                _record_metric("cluster.task.speculative_won", 1)
                events.emit(EventType.SPECULATION_WIN,
                            query_id=job.query_id,
                            trace_id=_jtrace(job), job_id=job.job_id,
                            stage=r.stage, partition=r.partition,
                            attempt=r.attempt)
            # data-movement metadata from the winning attempt: feeds the
            # governor's projections and the profile's shuffle line
            if r.channel_bytes:
                job.channel_bytes[key] = (list(r.channel_bytes),
                                          int(r.raw_bytes))
                job.wire_comp += sum(r.channel_bytes)
            job.wire_raw += int(r.raw_bytes)
            job.fetch_wait_s += float(r.fetch_wait_s)
            job.decode_s += float(r.decode_s)
            job.locations[r.stage][r.partition] = w["addr"]
            events.emit(EventType.TASK_FINISH, query_id=job.query_id,
                        trace_id=_jtrace(job), job_id=job.job_id,
                        stage=r.stage, partition=r.partition,
                        attempt=r.attempt, worker=r.worker_id,
                        state="succeeded", rows=int(r.rows_out),
                        fetch_wait_ms=round(
                            float(r.fetch_wait_s) * 1000.0, 3),
                        error="")
            # delta update keeps the per-(stage,partition) idempotent
            # overwrite (a producer re-run replaces, never double-counts)
            # without rescanning every stage's rows per report
            prev_rows = job.partition_rows.get((r.stage, r.partition), 0)
            job.partition_rows[(r.stage, r.partition)] = int(r.rows_out)
            job.stage_rows[r.stage] = job.stage_rows.get(r.stage, 0) \
                - prev_rows + int(r.rows_out)
            if r.metrics_json:
                try:
                    import json as _json
                    job.task_metrics[(r.stage, r.partition)] = {
                        "worker_id": r.worker_id,
                        "rows_out": int(r.rows_out),
                        "operators": _json.loads(r.metrics_json)}
                except ValueError:
                    pass  # malformed metrics never fail a task
            self._maybe_adapt(job, r.stage)
            self._fire_pending(job)
            self._schedule_ready_stages(job)
        elif r.state == "failed":
            live.pop(r.attempt, None)
            events.emit(EventType.TASK_FINISH, query_id=job.query_id,
                        trace_id=_jtrace(job), job_id=job.job_id,
                        stage=r.stage, partition=r.partition,
                        attempt=r.attempt, worker=r.worker_id,
                        state="failed", rows=0,
                        fetch_wait_ms=round(
                            float(r.fetch_wait_s) * 1000.0, 3),
                        error=r.error[:200])
            if r.error.startswith("FETCH_FAILED:"):
                _, s, p = r.error.split(":")
                up_stage, up_part = int(s), int(p)
                job.locations[up_stage].pop(up_part, None)
                if self.attempt_of(job, up_stage, up_part) + 1 < \
                        self._attempt_cap(job, up_stage, up_part):
                    # not the consumer's fault: park it (same attempt) and
                    # re-run the producer partition — unless a producer
                    # re-run is already in flight (several consumers can
                    # hit the same dead producer; one re-run serves all)
                    job.pending.add((r.stage, r.partition))
                    if not job.live.get((up_stage, up_part)):
                        self._launch_task(job, up_stage, up_part,
                                          self.attempt_of(job, up_stage,
                                                          up_part) + 1,
                                          reason="fetch_failed")
                    return
            else:
                # a fetch failure is the PRODUCER's loss, never a strike
                # against the consumer's worker — quarantining healthy
                # consumers would shrink the pool exactly when degraded
                self._note_worker_failure(r.worker_id)
            job.last_error = r.error
            if job.live.get(key):
                return  # a twin attempt still runs — let it finish
            # prefer a DIFFERENT worker for the retry: with the default
            # budgets a node-local fault would otherwise burn every
            # attempt on the same least-loaded (just-freed) worker
            # before quarantine can engage
            self._launch_task(job, r.stage, r.partition,
                              max(r.attempt,
                                  self.attempt_of(job, r.stage,
                                                  r.partition)) + 1,
                              reason="failure", exclude={r.worker_id})
        elif r.state == "canceled":
            live.pop(r.attempt, None)
            events.emit(EventType.TASK_FINISH, query_id=job.query_id,
                        trace_id=_jtrace(job), job_id=job.job_id,
                        stage=r.stage, partition=r.partition,
                        attempt=r.attempt, worker=r.worker_id,
                        state="canceled", rows=0, fetch_wait_ms=0.0,
                        error="")

    def _maybe_adapt(self, job: _Job, stage_id: int):
        """Stage-boundary replanning hook: fires EXACTLY ONCE per stage
        completion (re-completions after fault recovery re-produce
        bit-identical outputs, so the first completion's statistics are
        canonical), BEFORE any newly-unblocked consumer schedules."""
        if job.done.is_set():
            return
        if not self._stage_complete(job, stage_id):
            return
        if stage_id in job.adaptive.stages_done:
            return
        job.adaptive.stages_done.add(stage_id)
        events.emit(EventType.STAGE_COMPLETE, query_id=job.query_id,
                    trace_id=_jtrace(job), job_id=job.job_id,
                    stage=stage_id,
                    rows=int(job.stage_rows.get(stage_id, 0)))
        try:
            from . import adaptive as aqe
            aqe.on_stage_complete(self, job, stage_id)
        except Exception:  # noqa: BLE001 — adaptivity is advisory
            pass

    def _stop_task_on(self, wid: str, job_id: str, stage: int,
                      partition: int, reason: str):
        """Best-effort cooperative cancel of a task on one worker."""
        w = self.workers.get(wid)
        if w is None:
            return
        job = self.jobs.get(job_id)
        rpc = w["channel"].unary_unary(
            f"/{_WORKER_SERVICE}/StopTask",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.StopTaskResponse.FromString)
        try:
            # fire-and-forget: a blackholed worker must not stall the
            # single-threaded driver actor for the full RPC deadline
            fut = rpc.future(
                pb.StopTaskRequest(job_id=job_id, stage=stage,
                                   partition=partition, reason=reason),
                timeout=10,
                metadata=tr.inject_context(
                    job.trace_ctx if job is not None else None))
            fut.add_done_callback(lambda f: f.cancelled() or f.exception())
        except (grpc.RpcError, faults.FaultInjectedError):
            pass

    def _note_worker_failure(self, wid: str):
        """Quarantine accounting: N reported task failures inside the
        sliding window blacklist the worker for the cool-off period and
        (under an elastic pool) trigger a replacement scale-up."""
        q = self.quarantine
        if not q["enabled"]:
            return
        w = self.workers.get(wid)
        if w is None:
            return
        now = time.time()
        fails = [t for t in w.get("failures", [])
                 if now - t <= q["window_s"]]
        fails.append(now)
        w["failures"] = fails
        if len(fails) < q["max_failures"]:
            return
        # pool floor: a deterministically failing QUERY produces strikes
        # on every worker — never quarantine the last live worker, or
        # one bad job blacks out the whole cluster for the cool-off
        # (an elastic pool refills AFTER eviction, so the floor applies
        # there too: scale-up is asynchronous)
        if len(self.workers) <= 1:
            w["failures"] = []
            return
        self.quarantined[wid] = now + q["duration_s"]
        _record_metric("cluster.worker.quarantined_count", 1)
        events.emit(EventType.WORKER_QUARANTINE, query_id="",
                    worker=wid, failures=len(fails))
        self._evict_worker(wid, "quarantined")
        if self.elastic is not None:
            self._maybe_scale_up()

    def _merge_heartbeat_metrics(self, hb: "pb.HeartbeatRequest"):
        """Fold a heartbeat's piggybacked metric delta into the fleet
        view. A delta from THIS process (loopback thread workers share
        the driver's registry) is dropped — its increments are already
        in the local view and merging them would double-count fleet
        totals."""
        raw = getattr(hb, "metrics_json", "")
        if not raw:
            return
        try:
            delta = json.loads(raw)
        except ValueError:
            return
        if not isinstance(delta, dict):
            return
        from .. import metrics as _m
        src = delta.get("src")
        if src is not None:
            if src == _m.PROCESS_TOKEN:
                return
        elif int(delta.get("pid", 0) or 0) == os.getpid():
            return  # version-skewed worker without a token: pid check
        try:
            _fleet().merge(hb.worker_id, delta)
        except Exception:  # noqa: BLE001 — telemetry never fails the plane
            pass

    def readiness(self) -> dict:
        """Cluster readiness for the ops endpoint's ``/readyz``: every
        registered worker heartbeating inside the timeout, no evicted
        worker pending readmission (capacity we expect back is still
        missing), and no wedged admission queue (a queued job sitting
        past twice its shed budget means the scheduling loop is stuck).
        Called from the HTTP thread — reads are snapshots and a torn
        read degrades to not-ready, never an exception upstream."""
        now = time.time()
        for _ in range(3):
            try:
                workers = dict(self.workers)
                readmit = list(self._readmit_info)
                quarantined = sorted(dict(self.quarantined))
                break
            except RuntimeError:  # actor thread resized mid-copy
                continue
        else:
            # the actor is visibly busy mutating pool state — that is
            # not "unready", and flapping /readyz on it would be worse
            return {"ready": True, "driver_id": self.driver_id,
                    "racing": True}
        stale = sorted(
            wid for wid, w in workers.items()
            if now - float(w.get("last_seen", 0.0))
            > self.HEARTBEAT_TIMEOUT_S)
        pending = sorted(wid for wid in readmit
                         if wid not in workers)
        wedged = self.admission.wedged(now)
        ready = bool(workers) and not stale and not pending \
            and not wedged
        return {"ready": ready, "driver_id": self.driver_id,
                "workers": len(workers), "stale_heartbeats": stale,
                "pending_readmission": pending,
                "quarantined": quarantined,
                "admission_wedged": wedged}

    def _maybe_readmit(self, wid: str):
        """An evicted worker is still alive and heartbeating (transient
        dispatch failure, heartbeat blip, or an expired quarantine):
        rebuild its pool entry from the registration info saved at
        eviction (workers register only once, so without this evicting
        a live worker would be permanent capacity loss)."""
        info = self._readmit_info.get(wid)
        if info is None or self.quarantined.get(wid, 0.0) > time.time():
            return
        self._readmit_info.pop(wid, None)
        self.quarantined.pop(wid, None)
        from ..catalog.system import SYSTEM
        SYSTEM.record_worker(wid, info["addr"], info["slots"], "alive")
        self.workers[wid] = {
            "addr": info["addr"], "slots": info["slots"],
            "last_seen": time.time(),
            "channel": grpc.insecure_channel(info["addr"]),
            "tasks": set(),
            "idle_since": time.time(),
            "projected": 0,
            "task_proj": {},
        }
        _record_metric("cluster.worker_count", len(self.workers))

    def _maybe_speculate(self, now: float):
        """Straggler mitigation: when a stage is mostly complete,
        duplicate its slowest still-running tasks on OTHER workers. The
        first attempt to succeed wins (attempt fencing in
        _on_task_status); the loser is canceled."""
        sp = self.speculation
        if not sp["enabled"]:
            return
        for job in list(self.jobs.values()):
            if job.done.is_set():
                continue
            for stage in job.graph.stages:
                if stage.on_driver or stage.num_partitions < 2:
                    continue
                sid = stage.stage_id
                done = len(job.locations[sid])
                if done >= stage.num_partitions or \
                        done / stage.num_partitions < sp["fraction"]:
                    continue
                durs = job.durations.get(sid)
                if not durs:
                    continue
                threshold = max(sp["min_runtime_s"],
                                sp["multiplier"] * statistics.median(durs))
                for (s, p), live in list(job.live.items()):
                    if s != sid or not live or (s, p) in job.speculated \
                            or p in job.locations[sid]:
                        continue
                    att = max(live)
                    started = job.started.get((s, p, att))
                    if started is None or now - started < threshold:
                        continue
                    new_att = self.attempt_of(job, s, p) + 1
                    # mark BEFORE dispatch so the twin's instant success
                    # report (same actor thread, but belt and braces)
                    # sees the speculative attempt id; roll back if no
                    # worker accepted the duplicate so the partition can
                    # be speculated once capacity appears
                    job.speculated.add((s, p))
                    job.spec_attempt[(s, p)] = new_att
                    # the twin's attempt id is granted back to the
                    # failure budget up front (BEFORE the cap check in
                    # _launch_task) and revoked if nothing dispatched
                    job.attempt_allowance[(s, p)] = \
                        job.attempt_allowance.get((s, p), 0) + 1
                    if self._launch_task(job, s, p, new_att,
                                         exclude={live[att]},
                                         speculative=True):
                        job.spec_launched += 1
                        _record_metric("cluster.task.speculative_launched",
                                       1)
                        # ``worker`` is the STRAGGLER being raced; the
                        # twin's worker rides its task_dispatch event
                        events.emit(EventType.SPECULATION_LAUNCH,
                                    query_id=job.query_id,
                                    trace_id=_jtrace(job),
                                    job_id=job.job_id, stage=s,
                                    partition=p, attempt=new_att,
                                    worker=live[att])
                    else:
                        job.attempt_allowance[(s, p)] -= 1
                        job.speculated.discard((s, p))
                        job.spec_attempt.pop((s, p), None)

    def _cancel_job(self, job_id: str, reason: str):
        """Deadline/client cancellation: mark the job failed, stop its
        worker-side tasks cooperatively, and let the cleanup path wipe
        the partial shuffle outputs instead of leaking them."""
        job = self.jobs.get(job_id)
        if job is None or job.done.is_set():
            return
        job.canceled = True
        job.failed = f"canceled: {reason}"
        job.done.set()
        for wid, w in list(self.workers.items()):
            for (j, s, p) in [t for t in w["tasks"] if t[0] == job_id]:
                self._stop_task_on(wid, job_id, s, p, "cancel")
                self._release_task(w, (j, s, p))
            if not w["tasks"] and w.get("idle_since") is None:
                w["idle_since"] = time.time()

    def _fire_pending(self, job: _Job):
        ready = []
        for (stage_id, partition) in list(job.pending):
            stage = job.graph.stages[stage_id]
            if self._partition_ready(job, stage, partition):
                ready.append((stage_id, partition))
        for stage_id, partition in ready:
            job.pending.discard((stage_id, partition))
            self._launch_task(job, stage_id, partition,
                              self.attempt_of(job, stage_id, partition))

    def _cleanup_job(self, job_id: str):
        job = self.jobs.get(job_id)
        trace_ctx = job.trace_ctx if job is not None else None
        if job is not None:
            from ..catalog.system import SYSTEM
            SYSTEM.record_job(job_id, len(job.graph.stages),
                              "failed" if job.failed else "finished",
                              job.stage_rows)
            # free the tenant's concurrency slot + any residual quota
            # debits, then let the fair queue admit the next job and
            # un-park any same-tenant tasks the released quota frees
            self.admission.release(job)
            for other in list(self.jobs.values()):
                if other is not job and not other.done.is_set() \
                        and other.tenant == job.tenant and other.deferred:
                    self._drain_deferred(other)
        self.jobs.pop(job_id, None)
        self._drain_admission()
        for w in self.workers.values():
            rpc = w["channel"].unary_unary(
                f"/{_WORKER_SERVICE}/CleanUpJob",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=pb.CleanUpJobResponse.FromString)
            try:
                rpc(pb.CleanUpJobRequest(job_id=job_id), timeout=10,
                    metadata=tr.inject_context(trace_ctx))
            except grpc.RpcError:
                pass


_FRAGMENT_CACHE: Dict[Tuple[int, int], bytes] = {}


def encode_cached(job: _Job, stage: jg.Stage) -> bytes:
    # keyed by the job's unique submission seq: the memo is only valid
    # WITHIN one submission anyway (each epoch's plan embeds that
    # epoch's batch slice, and one streaming trigger may dispatch
    # several different graphs under the same job_id+epoch) — a
    # job_id-based key served one graph's fragment to another's stages
    key = (job.seq, stage.stage_id)
    blob = _FRAGMENT_CACHE.get(key)
    if blob is None:
        blob = jg.encode_fragment(stage.plan)
        _FRAGMENT_CACHE[key] = blob
        while len(_FRAGMENT_CACHE) > 256:
            _FRAGMENT_CACHE.pop(next(iter(_FRAGMENT_CACHE)))
    return blob


# ---------------------------------------------------------------------------
# Local-cluster runner (the reference's local-cluster mode / test vehicle)
# ---------------------------------------------------------------------------

class LocalCluster:
    def __init__(self, num_workers: Optional[int] = None,
                 task_slots: Optional[int] = None,
                 elastic: Optional[dict] = None):
        """``elastic``: {"max": int, "min": int, "idle_secs": float} —
        workers beyond ``num_workers`` are started on demand by the driver
        through a ThreadWorkerManager and idle-reaped (reference:
        driver/worker_pool/ elastic scaling). ``num_workers`` and
        ``task_slots`` default from ``cluster.worker_initial_count`` /
        ``cluster.worker_task_slots``."""
        faults.reload()  # pick up SAIL_FAULTS set after module import
        # workers run LocalExecutor in-process, so re-reading
        # compile_cache.* here makes every worker share the store a
        # test/bench just configured through SAIL_COMPILE_CACHE__* env
        # (process workers inherit it through their environment)
        from . import pcache
        pcache.reload()
        from ..config import get as config_get
        if num_workers is None:
            num_workers = _conf_int(
                config_get("cluster.worker_initial_count", 2), 2)
        if task_slots is None:
            task_slots = _conf_int(
                config_get("cluster.worker_task_slots", 2), 2)
        self.driver = DriverActor()
        self.driver.start("driver")
        deadline = time.time() + 10
        while self.driver.port == 0 and time.time() < deadline:
            time.sleep(0.01)
        self.manager = None
        if elastic is not None:
            from .worker_manager import ThreadWorkerManager
            self.manager = ThreadWorkerManager(self.driver.addr, task_slots)
            self.driver.set_elastic(
                self.manager,
                min_workers=elastic.get("min", num_workers),
                max_workers=elastic.get("max", num_workers),
                idle_secs=elastic.get("idle_secs", 60.0))
        self.workers: List[WorkerActor] = []
        for i in range(num_workers):
            w = WorkerActor(f"worker-{i}", self.driver.addr,
                            task_slots)
            w.start(f"worker-{i}")
            self.workers.append(w)
        deadline = time.time() + 10
        while len(self.driver.workers) < num_workers and time.time() < deadline:
            time.sleep(0.02)
        self.last_job: Optional[_Job] = None
        # the driver joins the process ops surface: /readyz and the
        # debug endpoints report this cluster until stop()
        from .. import obs_server
        obs_server.register_cluster(self.driver)
        obs_server.ensure_started()

    def run_job(self, plan, num_partitions: Optional[int] = None,
                timeout=120, epoch: int = 0,
                job_id: Optional[str] = None,
                tenant: Optional[str] = None,
                deadline_ms: Optional[float] = None):
        """Distribute a plan; returns the result pyarrow Table.

        ``epoch``/``job_id`` serve the streaming runner: a streaming
        query keeps ONE stable job id across triggers and tags every
        trigger with its epoch, so its shuffle channels publish and
        fetch under (job_id, epoch) — barrier-aligned per epoch, with a
        failed trigger's channels wiped (discarded stage) and a
        restarted trigger re-running under the SAME epoch id.

        ``tenant``/``deadline_ms`` feed the driver's admission queue:
        jobs schedule under weighted-fair queuing with per-tenant
        quotas; a shed job raises a typed retryable
        :class:`~sail_tpu.exec.admission.ResourceExhausted`, a blown
        deadline cancels through CancelJob and raises
        :class:`~sail_tpu.exec.admission.DeadlineExceeded`. Defaults
        come from the ``admission.*`` config."""
        import pyarrow as pa
        from .local import LocalExecutor
        from .. import profiler

        if num_partitions:
            nparts = num_partitions
        else:
            from ..config import get as config_get
            conf_parts = _conf_int(
                config_get("cluster.shuffle_partitions", 0), 0)
            nparts = conf_parts if conf_parts > 0 \
                else max(1, len(self.workers))
        graph = jg.split_job(plan, nparts)
        if graph is None:
            return LocalExecutor().execute(plan)
        adm_conf = self.driver.admission.conf
        if tenant is None:
            tenant = adm_conf.default_tenant
        if deadline_ms is None and adm_conf.default_deadline_ms:
            deadline_ms = float(adm_conf.default_deadline_ms)
        with tr.span("cluster:job") as root_span:
            job = _Job(job_id or uuid.uuid4().hex[:12], graph,
                       trace_ctx=tr.SpanContext(root_span.trace_id,
                                                root_span.span_id),
                       epoch=epoch, tenant=tenant)
            if deadline_ms and deadline_ms > 0:
                job.deadline_ms = float(deadline_ms)
                job.deadline_ts = time.time() + deadline_ms / 1000.0
            # joins the session's profile when the job runs inside one;
            # a standalone run_job still gets its own profile record.
            # Execute/fetch phases come from the root-stage executor —
            # total_ms additionally covers the distributed wait.
            with profiler.profile_query(
                    f"cluster job {job.job_id}") as prof:
                # stamp the flight-recorder envelope BEFORE submit so
                # every driver/worker event of this job carries the
                # owning query's id and trace
                job.query_id = prof.query_id
                job.adaptive.query_id = prof.query_id
                job.adaptive.trace_id = _jtrace(job)
                return self._run_submitted(job, timeout)

    def _run_submitted(self, job, timeout):
        import pyarrow as pa
        from .local import LocalExecutor

        graph = job.graph
        self.last_job = job
        self.driver.handle.ask(lambda reply: ("submit", (job, reply)))
        try:
            if not job.done.wait(timeout):
                # cancel on the driver actor: stop worker-side execution
                # and release the tasks instead of leaving them running
                # against a dead _Job (the cleanup in finally then wipes
                # the partial shuffle outputs on every worker)
                self.cancel_job(job.job_id, "timeout")
                job.done.wait(5.0)
                raise TimeoutError("cluster job timed out")
            if job.failed:
                from . import admission as adm
                if job.error_kind == "shed":
                    raise adm.ResourceExhausted(
                        job.failed, tenant=job.tenant,
                        retry_after_ms=self.driver.admission.conf
                        .queue_timeout_ms or 1000)
                if job.error_kind == "deadline":
                    raise adm.DeadlineExceeded(job.failed,
                                               tenant=job.tenant)
                if job.canceled:
                    raise RuntimeError(f"cluster job {job.failed}")
                raise RuntimeError(f"cluster job failed: {job.failed}")
            # the root stage runs on the driver over MERGE input fetched
            # from the workers via the data plane — all partitions
            # stream concurrently through the bounded fetch pool
            root = graph.root
            stats = sh.FetchStats()
            work = [(i.stage_id, p, job.locations[i.stage_id][p])
                    for i in root.inputs
                    for p in range(
                        graph.stages[i.stage_id].num_partitions)]

            root_sid = root.stage_id

            def fetch_one(item):
                stage_id, p, addr = item
                events.emit(EventType.FETCH_BEGIN,
                            query_id=job.query_id,
                            trace_id=_jtrace(job), job_id=job.job_id,
                            stage=stage_id, partition=p, channel=-1,
                            addr=addr, dst_stage=root_sid,
                            dst_partition=-1)
                t0 = time.perf_counter()
                ok = False
                nbytes = 0
                try:
                    with tr.span(f"driver:fetch s{stage_id}p{p}",
                                 {"job_id": job.job_id},
                                 parent=job.trace_ctx):
                        table = _fetch_table(addr, pb.FetchStreamRequest(
                            job_id=job.job_id, stage=stage_id,
                            partition=p, channel=-1, epoch=job.epoch),
                            _WORKER_SERVICE, stats=stats)
                    ok = True
                    nbytes = int(table.nbytes)
                    return table
                finally:
                    events.emit(
                        EventType.FETCH_END, query_id=job.query_id,
                        trace_id=_jtrace(job), job_id=job.job_id,
                        stage=stage_id, partition=p, channel=-1,
                        addr=addr, dst_stage=root_sid, dst_partition=-1,
                        bytes=nbytes,
                        ms=round((time.perf_counter() - t0) * 1000.0,
                                 3), ok=ok)

            parts: Dict[int, Dict[int, object]] = {}
            mp = MultiPrefetcher(work, fetch_one,
                                 workers=sh.fetch_concurrency(),
                                 kind="shuffle")
            try:
                for index, table in mp:
                    stage_id, p = work[index][0], work[index][1]
                    parts.setdefault(stage_id, {})[p] = table
            finally:
                mp.close()
                _record_metric("execution.shuffle.fetch_wait_time",
                               mp.stats.consumer_wait_s)
                stats.add(wait_s=mp.stats.consumer_wait_s)
            tables = {
                sid: pa.concat_tables(
                    [by_part[p] for p in range(len(by_part))],
                    promote_options="permissive")
                for sid, by_part in parts.items()}
            root_plan = jg.attach_stage_inputs(root.plan, tables)
            # memory scans that stayed in the driver-run root plan read the
            # driver's own table map directly
            root_plan = _reattach_local_scans(root_plan, graph.scan_tables)
            result = LocalExecutor().execute(root_plan)
            # merge the workers' per-task operator metrics into the
            # driver's query profile per {stage, partition}
            from .. import profiler
            prof = profiler.current_profile()
            if prof is not None:
                for (stage, part), m in sorted(job.task_metrics.items()):
                    prof.add_task(stage, part, m.get("worker_id", ""),
                                  m.get("operators") or [],
                                  m.get("rows_out", 0))
                prof.note_fault_tolerance(
                    retries=job.retry_count,
                    speculative_launched=job.spec_launched,
                    speculative_won=job.spec_won)
                prof.note_shuffle(
                    wire_bytes=job.wire_raw,
                    wire_bytes_compressed=job.wire_comp,
                    fetch_wait_s=job.fetch_wait_s + stats.wait_s,
                    decode_s=job.decode_s + stats.decode_s,
                    governor_deferred=job.governor_deferred)
                ad = job.adaptive
                prof.note_adaptive(coalesced=ad.coalesced,
                                   split=ad.split,
                                   broadcast=ad.broadcast,
                                   reordered=ad.reordered,
                                   events=ad.events)
                prof.note_skew(ad.skew)
                prof.note_shuffle_channels(ad.channel_report)
                # critical-path attribution: walk the task/fetch
                # dependency edges this job's events recorded — the
                # same computation sail_timeline.py runs offline on the
                # durable log, so live and post-mortem views agree
                if events.enabled():
                    try:
                        from ..analysis import timeline as _tl
                        prof.critical_path = _tl.critical_path(
                            events.events(query_id=prof.query_id))
                    except Exception:  # noqa: BLE001 — attribution is advisory
                        pass
            # observed-cardinality feedback: leaf-stage output rows keyed
            # by the scan subtree feed join_reorder / runtime-filter
            # estimates on repeat queries (real cardinalities, not just
            # footer counts)
            try:
                from ..plan import join_reorder as jr
                for stage in graph.stages:
                    if stage.inputs or stage.on_driver:
                        continue
                    rows = job.stage_rows.get(stage.stage_id)
                    if rows is not None:
                        jr.note_observed_rows(stage.plan, rows,
                                              scan_tables=graph.scan_tables)
            except Exception:  # noqa: BLE001 — feedback is advisory
                pass
            return result
        finally:
            self.driver.handle.send(("cleanup", job.job_id))

    def cancel_job(self, job_id: Optional[str] = None,
                   reason: str = "client abort"):
        """Cancel a running job (client abort): stops worker-side task
        execution and fails the waiting run_job call. Also reachable
        over the driver's CancelJob RPC."""
        job_id = job_id or (self.last_job.job_id if self.last_job else None)
        if job_id is not None:
            self.driver.handle.send(("cancel", (job_id, reason)))

    def stage_rows(self) -> Dict[int, int]:
        """Rows produced per stage of the last job (operator metrics)."""
        return dict(self.last_job.stage_rows) if self.last_job else {}

    def task_metrics(self) -> Dict[Tuple[int, int], dict]:
        """Per-{stage, partition} operator metrics of the last job."""
        return dict(self.last_job.task_metrics) if self.last_job else {}

    def stop(self):
        from .. import obs_server
        obs_server.unregister_cluster(self.driver)
        for w in self.workers:
            w.stop()
        if self.manager is not None:
            self.manager.stop_all()
        self.driver.stop()
