"""Job graph: splitting a physical plan into distributable stages.

Reference role: JobGraph::try_new and the five-InputMode exchange vocabulary
(crates/sail-execution/src/job_graph/ — SURVEY.md §2.5). v0 splits at the
materialization operators (aggregate/join/sort/limit): everything below the
first such boundary over a partitionable scan becomes a per-partition leaf
stage (Forward input), and the remainder runs as the root stage over the
merged leaf outputs (Merge input). Hash-shuffled intermediate stages
(InputMode::Shuffle riding the all_to_all collectives in parallel/) plug in
at the same seam in a later round.
"""

from __future__ import annotations

import dataclasses
import enum
import pickle
from typing import List, Optional, Tuple

from ..plan import nodes as pn
from ..plan import rex as rx


class InputMode(enum.Enum):
    FORWARD = "forward"
    MERGE = "merge"
    SHUFFLE = "shuffle"
    BROADCAST = "broadcast"
    RESCALE = "rescale"


@dataclasses.dataclass
class Stage:
    stage_id: int
    plan: pn.PlanNode             # fragment; leaf stages scan a partition slice
    input_mode: InputMode
    inputs: Tuple[int, ...] = ()
    num_partitions: int = 1


@dataclasses.dataclass
class JobGraph:
    stages: List[Stage]

    @property
    def root(self) -> Stage:
        return self.stages[-1]


class _StageInput(pn.PlanNode):
    """Placeholder leaf standing for a stage's merged upstream output."""

    def __init__(self, stage_id: int, schema):
        object.__setattr__(self, "stage_id", stage_id)
        object.__setattr__(self, "_schema", schema)

    @property
    def schema(self):
        return self._schema


def _is_pipeline_op(p: pn.PlanNode) -> bool:
    return isinstance(p, (pn.FilterExec, pn.ProjectExec))


def _pipeline_over_scan(p: pn.PlanNode) -> bool:
    """True if ``p`` is a chain of Filter/Project ops ending at a scan."""
    seen_pipeline = False
    while _is_pipeline_op(p):
        seen_pipeline = True
        p = p.input
    return seen_pipeline and isinstance(p, pn.ScanExec)


def _find_leaf_pipeline(p: pn.PlanNode) -> Optional[pn.PlanNode]:
    """Topmost subtree that is a pipeline chain over a scan."""
    if _pipeline_over_scan(p):
        return p
    for c in p.children:
        r = _find_leaf_pipeline(c)
        if r is not None:
            return r
    return None


def split_job(plan: pn.PlanNode, num_partitions: int) -> Optional[JobGraph]:
    """Split into (leaf pipeline stage over scan partitions, root stage).
    Returns None when the plan has no distributable pipeline subtree (the
    local executor should run it directly)."""
    target = _find_leaf_pipeline(plan)
    if target is None or target is plan and not _is_pipeline_op(plan):
        return None
    leaf = Stage(0, target, InputMode.FORWARD, (), num_partitions)
    root_input = _StageInput(0, target.schema)
    root_plan = _replace_subtree(plan, target, root_input)
    root = Stage(1, root_plan, InputMode.MERGE, (0,), 1)
    return JobGraph([leaf, root])


def _replace_subtree(plan: pn.PlanNode, target: pn.PlanNode,
                     replacement: pn.PlanNode) -> pn.PlanNode:
    if plan is target:
        return replacement
    if isinstance(plan, pn.JoinExec):
        return dataclasses.replace(
            plan,
            left=_replace_subtree(plan.left, target, replacement),
            right=_replace_subtree(plan.right, target, replacement))
    if isinstance(plan, pn.UnionExec):
        return dataclasses.replace(plan, inputs=tuple(
            _replace_subtree(c, target, replacement) for c in plan.inputs))
    if hasattr(plan, "input") and plan.input is not None:
        return dataclasses.replace(
            plan, input=_replace_subtree(plan.input, target, replacement))
    return plan


# ---------------------------------------------------------------------------
# fragment codec (reference role: RemoteExecutionCodec, src/proto/codec.rs)
# ---------------------------------------------------------------------------

def encode_fragment(plan: pn.PlanNode) -> Tuple[bytes, Optional[bytes]]:
    """Serialize a plan fragment for shipping to a worker.

    Memory-table scans carry their data as Arrow IPC alongside the plan
    (v0; file scans ship only paths). Returns (plan_bytes, table_ipc|None).
    """
    import pyarrow as pa

    table_ipc = None

    def strip(p: pn.PlanNode) -> pn.PlanNode:
        nonlocal table_ipc
        if isinstance(p, pn.ScanExec) and p.source is not None:
            sink = pa.BufferOutputStream()
            src = p.source
            if p.projection is not None:
                src = src.select(list(p.projection))
            with pa.ipc.new_stream(sink, src.schema) as w:
                w.write_table(src)
            table_ipc = sink.getvalue().to_pybytes()
            return dataclasses.replace(p, source=None, format="__shipped__",
                                       projection=None)
        if isinstance(p, pn.JoinExec):
            return dataclasses.replace(p, left=strip(p.left), right=strip(p.right))
        if isinstance(p, pn.UnionExec):
            return dataclasses.replace(p, inputs=tuple(strip(c) for c in p.inputs))
        if hasattr(p, "input") and p.input is not None:
            return dataclasses.replace(p, input=strip(p.input))
        return p

    stripped = strip(plan)
    return pickle.dumps(stripped), table_ipc


def decode_fragment(plan_bytes: bytes, table_ipc: Optional[bytes],
                    partition: int, num_partitions: int) -> pn.PlanNode:
    """Deserialize a fragment, re-attaching shipped data sliced to this
    task's partition."""
    import pyarrow as pa

    plan = pickle.loads(plan_bytes)

    def attach(p: pn.PlanNode) -> pn.PlanNode:
        if isinstance(p, pn.ScanExec) and p.format == "__shipped__":
            table = pa.ipc.open_stream(table_ipc).read_all()
            n = table.num_rows
            per = -(-n // num_partitions)
            part = table.slice(partition * per, per)
            return dataclasses.replace(p, source=part, format="memory")
        if isinstance(p, pn.ScanExec) and p.paths:
            files = list(p.paths)
            mine = tuple(f for i, f in enumerate(sorted(files))
                         if i % num_partitions == partition)
            if not mine:
                # More partitions than files: this task reads nothing. An
                # empty memory table (projected schema) keeps the plan
                # executable without re-reading files[0] (which would
                # duplicate its rows in the job result).
                from ..columnar.arrow_interop import spec_type_to_arrow
                empty = pa.Table.from_arrays(
                    [pa.array([], type=spec_type_to_arrow(f.dtype))
                     for f in p.schema],
                    names=[f.name for f in p.schema])
                return dataclasses.replace(p, out_schema=p.schema,
                                           source=empty, paths=(),
                                           format="memory", projection=None)
            return dataclasses.replace(p, paths=mine)
        if isinstance(p, pn.JoinExec):
            return dataclasses.replace(p, left=attach(p.left), right=attach(p.right))
        if hasattr(p, "input") and p.input is not None:
            return dataclasses.replace(p, input=attach(p.input))
        return p

    return attach(plan)
