"""Job graph: splitting a physical plan into distributable stages.

Reference role: JobGraph::try_new and the five-InputMode exchange vocabulary
(crates/sail-execution/src/job_graph/mod.rs:134-151, planner.rs:42-61 —
SURVEY.md §2.5), plus the RemoteExecutionCodec (src/proto/codec.rs)
re-designed as a whitelist dataclass codec (no pickle: no arbitrary-code
deserialization, stable across engine versions).

The splitter builds a real multi-stage graph:

- pipeline-over-scan subtrees become FORWARD leaf stages, one task per
  scan partition;
- equi-joins of stage outputs become SHUFFLE stages: both producers
  hash-partition their output on the join keys into R channels, the join
  stage's task r fetches channel r from every producer partition;
- a small build side becomes a BROADCAST stage (single task, whole output
  fetched by every consumer);
- aggregations split into a partial aggregate FUSED into the producer
  stage (pre-shuffle reduction — the TPU-friendly two-phase plan) and a
  final merge aggregate in a SHUFFLE stage keyed on the group columns;
- whatever remains (sorts, limits, windows, …) runs in the root stage on
  the driver over MERGE input.
"""

from __future__ import annotations

import base64
import dataclasses
import datetime
import decimal
import enum
import json
from typing import Dict, List, Optional, Tuple

from ..plan import nodes as pn
from ..plan import rex as rx
from ..spec import data_type as dt
from ..spec.literal import Literal as LV


class InputMode(enum.Enum):
    FORWARD = "forward"
    MERGE = "merge"
    SHUFFLE = "shuffle"
    BROADCAST = "broadcast"
    RESCALE = "rescale"


@dataclasses.dataclass
class StageInput:
    stage_id: int
    mode: InputMode
    # adaptive execution: per consumer-task explicit fetch assignment —
    # fetch_plan[task_partition] is the ordered tuple of (producer
    # partition, channel) pairs that task pulls, replacing the mode's
    # default fetch set. None = default semantics. Set only by
    # exec/adaptive.py rewrites (coalesce, skew split, broadcast
    # conversion) before the consuming stage launches.
    fetch_plan: Optional[Tuple[Tuple[Tuple[int, int], ...], ...]] = None


@dataclasses.dataclass
class Stage:
    stage_id: int
    plan: pn.PlanNode
    inputs: Tuple[StageInput, ...] = ()
    num_partitions: int = 1
    # hash-route this stage's output into channels on these column indices
    shuffle_keys: Optional[Tuple[int, ...]] = None
    num_channels: int = 1
    on_driver: bool = False
    # adaptive execution: scheduling-only barrier — this stage may not
    # launch until these stages complete (the window in which a
    # broadcast-conversion decision is made from the build side's
    # observed output size). Cleared implicitly: barrier stages
    # completing is exactly the launch condition.
    launch_after: Tuple[int, ...] = ()
    # adaptive execution: (probe producer sid, build producer sid) of a
    # shuffle join eligible for broadcast conversion once the build
    # side's observed size is in; None after the decision is taken.
    bcast_candidate: Optional[Tuple[int, int]] = None


@dataclasses.dataclass
class JobGraph:
    stages: List[Stage]
    # memory tables stripped out of scan nodes, served by the driver
    scan_tables: Dict[str, object] = dataclasses.field(default_factory=dict)
    # runtime join filters the driver derived from broadcast-side tables
    # it hosts: stage_id → JSON entries shipped on that stage's tasks
    # (TaskDefinition.runtime_filters_json)
    stage_filters: Dict[int, str] = dataclasses.field(default_factory=dict)

    @property
    def root(self) -> Stage:
        return self.stages[-1]


@dataclasses.dataclass(frozen=True)
class StageInputExec(pn.PlanNode):
    """Leaf standing for an upstream stage's exchanged output."""

    out_schema: Tuple[pn.Field, ...] = ()
    stage_id: int = -1

    @property
    def schema(self):
        return self.out_schema

    @property
    def children(self):
        return ()


# ---------------------------------------------------------------------------
# Fragment codec (reference role: RemoteExecutionCodec, src/proto/codec.rs).
# Whitelist-tagged JSON: only registered dataclasses decode, so a hostile
# plan blob cannot execute code on a worker the way pickle would.
# ---------------------------------------------------------------------------

_CODEC_TYPES: Dict[str, type] = {}


def _register_codec_types():
    import sys
    if _CODEC_TYPES:
        return
    for mod in (pn, rx, dt):
        for name in dir(mod):
            obj = getattr(mod, name)
            if isinstance(obj, type) and dataclasses.is_dataclass(obj):
                _CODEC_TYPES[f"{mod.__name__.split('.')[-1]}.{name}"] = obj
    _CODEC_TYPES["literal.Literal"] = LV
    _CODEC_TYPES["job_graph.StageInputExec"] = StageInputExec


def _tag_of(obj) -> str:
    mod = type(obj).__module__.split(".")[-1]
    return f"{mod}.{type(obj).__name__}"


def _enc(obj):
    import pyarrow as pa

    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, bytes):
        return ["!b", base64.b64encode(obj).decode()]
    if isinstance(obj, tuple):
        return ["!t", [_enc(x) for x in obj]]
    if isinstance(obj, list):
        return ["!l", [_enc(x) for x in obj]]
    if isinstance(obj, decimal.Decimal):
        return ["!D", str(obj)]
    if isinstance(obj, datetime.datetime):
        return ["!ts", obj.isoformat()]
    if isinstance(obj, datetime.date):
        return ["!d", obj.isoformat()]
    if isinstance(obj, datetime.timedelta):
        return ["!td", obj.total_seconds()]
    if isinstance(obj, pa.Table):
        # plan-fragment embedding is control-plane traffic: uncompressed
        # (base64 JSON dominates anyway) and excluded from the wire-byte
        # counters the data plane reports
        from . import shuffle as sh
        return ["!table", base64.b64encode(
            sh.encode_table(obj, codec=None, record=False)).decode()]
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        tag = _tag_of(obj)
        if tag not in _CODEC_TYPES:
            raise TypeError(f"type not registered with the plan codec: {tag}")
        fields = {f.name: _enc(getattr(obj, f.name))
                  for f in dataclasses.fields(obj)}
        return ["!o", tag, fields]
    if isinstance(obj, enum.Enum):
        return ["!e", _tag_of(obj), obj.value]
    raise TypeError(f"cannot encode {type(obj)!r} in a plan fragment")


def _dec(v):
    import pyarrow as pa

    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    tag = v[0]
    if tag == "!b":
        return base64.b64decode(v[1])
    if tag == "!t":
        return tuple(_dec(x) for x in v[1])
    if tag == "!l":
        return [_dec(x) for x in v[1]]
    if tag == "!D":
        return decimal.Decimal(v[1])
    if tag == "!ts":
        return datetime.datetime.fromisoformat(v[1])
    if tag == "!d":
        return datetime.date.fromisoformat(v[1])
    if tag == "!td":
        return datetime.timedelta(seconds=v[1])
    if tag == "!table":
        return pa.ipc.open_stream(base64.b64decode(v[1])).read_all()
    if tag == "!o":
        cls = _CODEC_TYPES.get(v[1])
        if cls is None:
            raise ValueError(f"unknown plan codec type: {v[1]}")
        kwargs = {k: _dec(x) for k, x in v[2].items()}
        return cls(**kwargs)
    raise ValueError(f"bad plan codec tag: {tag!r}")


def encode_fragment(plan: pn.PlanNode) -> bytes:
    _register_codec_types()
    return json.dumps(_enc(plan)).encode()


def decode_fragment(plan_bytes: bytes, partition: int,
                    num_partitions: int) -> pn.PlanNode:
    """Deserialize a fragment, assigning this task its partition of every
    partitionable scan (files round-robin; memory tables row-sliced)."""
    import pyarrow as pa

    _register_codec_types()
    plan = _dec(json.loads(plan_bytes.decode()))

    def attach(p: pn.PlanNode) -> pn.PlanNode:
        if isinstance(p, pn.ScanExec) and p.source is not None \
                and num_partitions > 1:
            table = p.source
            n = table.num_rows
            per = -(-n // num_partitions)
            part = table.slice(partition * per, per)
            return dataclasses.replace(p, source=part)
        if isinstance(p, pn.ScanExec) and p.paths:
            files = list(p.paths)
            mine = tuple(f for i, f in enumerate(sorted(files))
                         if i % num_partitions == partition)
            if not mine:
                # More partitions than files: this task reads nothing. An
                # empty memory table (projected schema) keeps the plan
                # executable without re-reading files[0] (which would
                # duplicate its rows in the job result).
                from ..columnar.arrow_interop import spec_type_to_arrow
                empty = pa.Table.from_arrays(
                    [pa.array([], type=spec_type_to_arrow(f.dtype))
                     for f in p.schema],
                    names=[f.name for f in p.schema])
                return dataclasses.replace(p, out_schema=p.schema,
                                           source=empty, paths=(),
                                           format="memory", projection=None)
            return dataclasses.replace(p, paths=mine)
        if isinstance(p, (StageInputExec,)):
            return p
        if isinstance(p, pn.JoinExec):
            return dataclasses.replace(p, left=attach(p.left),
                                       right=attach(p.right))
        if isinstance(p, pn.UnionExec):
            return dataclasses.replace(
                p, inputs=tuple(attach(c) for c in p.inputs))
        if hasattr(p, "input") and p.input is not None:
            return dataclasses.replace(p, input=attach(p.input))
        return p

    return attach(plan)


# ---------------------------------------------------------------------------
# Stage building
# ---------------------------------------------------------------------------

_MERGEABLE_AGGS = {"sum": "sum", "count": "sum", "min": "min", "max": "max",
                   "first": "first", "last": "last",
                   "bool_and": "bool_and", "bool_or": "bool_or"}

# memory tables smaller than this broadcast instead of shuffling
BROADCAST_ROW_LIMIT = 100_000


def _is_pipeline_op(p: pn.PlanNode) -> bool:
    return isinstance(p, (pn.FilterExec, pn.ProjectExec))


class _Builder:
    def __init__(self, num_partitions: int):
        self.stages: List[Stage] = []
        self.scan_tables: Dict[str, object] = {}
        self.nparts = num_partitions

    def _add(self, stage: Stage) -> Stage:
        self.stages.append(stage)
        return stage

    def _strip_tables(self, p: pn.PlanNode) -> pn.PlanNode:
        """Move memory tables out of scan nodes into the driver-served
        table map, so tasks fetch only their slice over the data plane
        (instead of every task shipping the whole table)."""
        if isinstance(p, pn.ScanExec) and p.source is not None:
            src = p.source
            if p.projection is not None:
                src = src.select(list(p.projection))
            scan_id = f"scan{len(self.scan_tables)}"
            self.scan_tables[scan_id] = src
            return dataclasses.replace(p, out_schema=p.schema, source=None,
                                       format="__driver__", projection=None,
                                       table_name=scan_id)
        if isinstance(p, pn.JoinExec):
            return dataclasses.replace(p, left=self._strip_tables(p.left),
                                       right=self._strip_tables(p.right))
        if isinstance(p, pn.UnionExec):
            return dataclasses.replace(p, inputs=tuple(
                self._strip_tables(c) for c in p.inputs))
        if hasattr(p, "input") and p.input is not None:
            return dataclasses.replace(
                p, input=self._strip_tables(p.input))
        return p

    # -- recursive stage construction -----------------------------------
    def build(self, p: pn.PlanNode) -> Optional[Stage]:
        """Try to turn ``p`` into a distributed stage; None → not
        distributable (stays in the consumer's plan)."""
        if _is_pipeline_op(p):
            child = self.build(p.input)
            if child is None:
                return None
            # absorb the pipeline op into the producing stage
            child.plan = dataclasses.replace(p, input=child.plan) \
                if hasattr(p, "input") else p
            return child
        if isinstance(p, pn.ScanExec):
            return self._add(Stage(len(self.stages), p, (),
                                   self.nparts))
        if isinstance(p, pn.JoinExec):
            return self._build_join(p)
        if isinstance(p, pn.AggregateExec):
            return self._build_aggregate(p)
        return None

    def _estimated_small(self, stage: Stage) -> bool:
        p = stage.plan
        while _is_pipeline_op(p):
            p = p.input
        if isinstance(p, pn.ScanExec) and p.format == "__driver__":
            table = self.scan_tables.get(p.table_name)
            return table is not None and table.num_rows <= BROADCAST_ROW_LIMIT
        return False

    def _reshard(self, producer: Stage) -> Stage:
        """Identity re-shard stage: consumes an already-shuffled producer
        (a producer shuffle-writes at most once) and re-routes its rows
        under this stage's own shuffle keys. Reference role: the extra
        exchange DataFusion's EnforceDistribution inserts between
        incompatible hash distributions (job_graph/planner.rs:42-61)."""
        inp = StageInputExec(tuple(producer.plan.schema), producer.stage_id)
        return self._add(Stage(
            len(self.stages), inp,
            (StageInput(producer.stage_id, InputMode.SHUFFLE),),
            self.nparts))

    def _build_join(self, p: pn.JoinExec) -> Optional[Stage]:
        if p.join_type == "cross" or not p.left_keys or p.null_aware:
            return None
        lkeys = _plain_key_indices(p.left_keys)
        rkeys = _plain_key_indices(p.right_keys)
        if lkeys is None or rkeys is None:
            return None
        n_before = len(self.stages)
        left = self.build(p.left)
        if left is None:
            del self.stages[n_before:]
            return None
        right = self.build(p.right)
        if right is None:
            del self.stages[n_before:]
            return None
        if self._estimated_small(right) and p.join_type in (
                "inner", "left", "semi", "anti") and \
                right.shuffle_keys is None:
            # broadcast build side: one producer task, every probe task
            # fetches the whole build output
            l_in = StageInputExec(tuple(p.left.schema), left.stage_id)
            r_in = StageInputExec(tuple(p.right.schema), right.stage_id)
            join_plan = dataclasses.replace(p, left=l_in, right=r_in)
            right.num_partitions = 1
            return self._add(Stage(
                len(self.stages), join_plan,
                (StageInput(left.stage_id, InputMode.FORWARD),
                 StageInput(right.stage_id, InputMode.BROADCAST)),
                left.num_partitions))
        if left.shuffle_keys is not None:
            left = self._reshard(left)
        if right.shuffle_keys is not None:
            right = self._reshard(right)
        l_in = StageInputExec(tuple(p.left.schema), left.stage_id)
        r_in = StageInputExec(tuple(p.right.schema), right.stage_id)
        join_plan = dataclasses.replace(p, left=l_in, right=r_in)
        left.shuffle_keys = lkeys
        left.num_channels = self.nparts
        right.shuffle_keys = rkeys
        right.num_channels = self.nparts
        return self._add(Stage(
            len(self.stages), join_plan,
            (StageInput(left.stage_id, InputMode.SHUFFLE),
             StageInput(right.stage_id, InputMode.SHUFFLE)),
            self.nparts))

    def _build_aggregate(self, p: pn.AggregateExec) -> Optional[Stage]:
        if any(a.distinct for a in p.aggs):
            return self._build_distinct_aggregate(p)
        if any(a.fn not in _MERGEABLE_AGGS for a in p.aggs):
            return None
        child = self.build(p.input)
        if child is None:
            return None
        nk = len(p.group_indices)
        if child.shuffle_keys is not None:
            # producer already routes a join shuffle: the partial
            # aggregate becomes its OWN stage consuming that shuffle
            inp = StageInputExec(tuple(child.plan.schema), child.stage_id)
            partial = dataclasses.replace(p, input=inp)
            child = self._add(Stage(
                len(self.stages), partial,
                (StageInput(child.stage_id, InputMode.SHUFFLE),),
                self.nparts))
        else:
            # partial aggregate fused into the producer stage (pre-shuffle
            # reduction: the TPU two-phase aggregation plan)
            partial = dataclasses.replace(p, input=child.plan)
            child.plan = partial
        child.shuffle_keys = tuple(range(nk))
        child.num_channels = self.nparts
        # final merge aggregate over the shuffled partials
        f_in = StageInputExec(tuple(partial.schema), child.stage_id)
        final_aggs = []
        for j, a in enumerate(p.aggs):
            out_f = partial.schema[nk + j]
            final_aggs.append(pn.AggSpec(
                _MERGEABLE_AGGS[a.fn], nk + j, False, out_f.dtype,
                None, a.ignore_nulls))
        final = pn.AggregateExec(f_in, tuple(range(nk)), tuple(final_aggs),
                                 tuple(p.out_names), p.max_groups_hint)
        # a GLOBAL aggregate (no group keys) must merge on exactly one
        # final task: every partial routes to channel 0, and extra final
        # partitions would each synthesize a spurious empty-input row
        return self._add(Stage(
            len(self.stages), final,
            (StageInput(child.stage_id, InputMode.SHUFFLE),),
            self.nparts if nk else 1))

    def _build_distinct_aggregate(self, p: pn.AggregateExec
                                  ) -> Optional[Stage]:
        """Distributed DISTINCT via two-level dedup: partial GROUP BY
        (group keys, arg) per partition prunes duplicates, a shuffle on
        the group keys co-locates each group, and the original distinct
        aggregate runs exactly on each co-located group."""
        args = {a.arg for a in p.aggs if a.distinct}
        if len(args) != 1 or None in args or \
                not all(a.distinct for a in p.aggs) or \
                any(a.filter is not None for a in p.aggs):
            return None  # mixed / multi-argument DISTINCT stays local
        arg = args.pop()
        child = self.build(p.input)
        if child is None:
            return None
        if child.shuffle_keys is not None:
            child = self._reshard(child)
        nk = len(p.group_indices)
        dedup_indices = tuple(p.group_indices) + (arg,)
        dedup_names = tuple(f"d{i}" for i in range(len(dedup_indices)))
        partial = pn.AggregateExec(child.plan, dedup_indices, (),
                                   dedup_names, p.max_groups_hint)
        child.plan = partial
        child.shuffle_keys = tuple(range(nk))
        child.num_channels = self.nparts
        f_in = StageInputExec(tuple(partial.schema), child.stage_id)
        final_aggs = tuple(
            dataclasses.replace(a, arg=nk) for a in p.aggs)
        final = pn.AggregateExec(f_in, tuple(range(nk)), final_aggs,
                                 tuple(p.out_names), p.max_groups_hint)
        return self._add(Stage(
            len(self.stages), final,
            (StageInput(child.stage_id, InputMode.SHUFFLE),),
            self.nparts if nk else 1))


def _plain_key_indices(keys) -> Optional[Tuple[int, ...]]:
    out = []
    for k in keys:
        if isinstance(k, rx.BoundRef):
            out.append(k.index)
        else:
            return None
    return tuple(out)


def split_job(plan: pn.PlanNode, num_partitions: int) -> Optional[JobGraph]:
    """Split into a multi-stage graph; None → run locally."""
    b = _Builder(num_partitions)
    plan = b._strip_tables(plan)
    top = b.build(plan)
    if top is None:
        # try the largest distributable subtree instead
        sub = _find_distributable_subtree(b, plan)
        if sub is None:
            return None
        top, target = sub
        root_plan = _replace_subtree(
            plan, target, StageInputExec(tuple(target.schema), top.stage_id))
    else:
        root_plan = StageInputExec(tuple(plan.schema), top.stage_id)
    if not b.stages:
        return None
    root = Stage(len(b.stages), root_plan,
                 (StageInput(top.stage_id, InputMode.MERGE),), 1,
                 on_driver=True)
    b.stages.append(root)
    graph = JobGraph(b.stages, b.scan_tables)
    _maybe_validate_graph(graph)
    from ..config import truthy as _on

    # both the cluster gate AND the runtime-filter master switch must be
    # on (SAIL_JOIN__RUNTIME_FILTER__ENABLED=0 kills cluster shipping
    # along with every other filter site)
    if _on("cluster.runtime_filters") and _on("join.runtime_filter.enabled"):
        try:
            graph.stage_filters = compute_runtime_filters(graph)
        except Exception:  # noqa: BLE001 — filters are advisory
            graph.stage_filters = {}
    # adaptive execution: register broadcast-conversion candidates and
    # barrier their probe producers behind the build side so the
    # decision window exists when the build's observed size arrives
    try:
        from . import adaptive as aqe
        aqe.plan_graph(graph)
    except Exception:  # noqa: BLE001 — adaptivity is advisory
        pass
    return graph


def _maybe_validate_graph(graph: JobGraph) -> None:
    """Stage-boundary invariant check (shuffle channel counts, stage
    input schemas) before any task ships. Gated by the app-config
    ``analysis.validate_plans`` (split_job has no session context —
    like the other cluster gates, use SAIL_ANALYSIS__VALIDATE_PLANS to
    override); the walk rides the query profile's validated count."""
    from ..analysis.invariants import (VALIDATE_OFF, validate_job_graph,
                                       validate_stage_split,
                                       validation_mode)
    if validation_mode() == VALIDATE_OFF:
        return
    validate_job_graph(graph)
    # fused-stage invariant per cluster stage: every job-graph stage's
    # plan must split cleanly into pipelines (the worker's fused
    # executor maps them 1:1 onto compiled programs), so a stage whose
    # interior hides a breaker surfaces here — before any task ships
    from ..config import truthy as _on
    if _on("execution.fusion.enabled"):
        from ..plan.stages import split_stages
        for stage in graph.stages:
            validate_stage_split(stage.plan, split_stages(stage.plan))
    try:
        from .. import profiler
        profiler.note_plan_validated()
    except Exception:  # noqa: BLE001 — accounting never fails a job
        pass


# ---------------------------------------------------------------------------
# Cluster runtime join filters: the driver holds broadcast-side memory
# tables, so it can derive min/max (+ exact key lists) filters BEFORE any
# task launches and ship them with the probe-scan stage's tasks. Workers
# attach the entries as runtime_predicates on their scan fragment —
# parquet row groups skip on the conjuncts; driver-hosted scan slices
# filter host-side after fetch. Always sound: the driver table is the
# UNFILTERED build input, so its key set is a superset of the build keys.
# ---------------------------------------------------------------------------

def compute_runtime_filters(graph: JobGraph) -> Dict[int, str]:
    from ..config import get as config_get
    from ..plan import runtime_filters as rtfp

    try:
        cap = int(config_get("join.runtime_filter.in_list_max", 8192))
    except (TypeError, ValueError):
        cap = 8192
    stages_by_id = {s.stage_id: s for s in graph.stages}
    out: Dict[int, List[dict]] = {}
    for stage in graph.stages:
        for node in pn.walk_plan(stage.plan):
            if not (isinstance(node, pn.JoinExec)
                    and node.join_type in ("inner", "semi")
                    and node.left_keys and not node.null_aware):
                continue
            for lk, rk in zip(node.left_keys, node.right_keys):
                if not (isinstance(lk, rx.BoundRef)
                        and isinstance(rk, rx.BoundRef)):
                    continue
                col = _driver_build_column(node.right, rk.index,
                                           stages_by_id, graph)
                if col is None:
                    continue
                probe = _probe_scan_target(node.left, lk.index,
                                           stages_by_id,
                                           default_stage=stage.stage_id)
                if probe is None:
                    continue
                stage_id, scan_ord, col_idx, field = probe
                if not rtfp.supports_bounds(field.dtype):
                    continue
                entry = _filter_entry(col, field, scan_ord, col_idx, cap)
                if entry is not None:
                    out.setdefault(stage_id, []).append(entry)
    return {sid: json.dumps(entries) for sid, entries in out.items()}


def _driver_build_column(p: pn.PlanNode, idx: int, stages_by_id,
                         graph: JobGraph):
    """Resolve a build-side key column to a driver-hosted table column
    through Filter/simple-Project chains (the unfiltered column is a
    sound superset of the filtered build keys). Returns a pyarrow
    ChunkedArray or None."""
    while True:
        if isinstance(p, StageInputExec):
            stage = stages_by_id.get(p.stage_id)
            if stage is None:
                return None
            p = stage.plan
            continue
        if isinstance(p, pn.FilterExec):
            p = p.input
            continue
        if isinstance(p, pn.ProjectExec):
            if idx >= len(p.exprs):
                return None
            e = p.exprs[idx][1]
            if not isinstance(e, rx.BoundRef):
                return None
            idx = e.index
            p = p.input
            continue
        if isinstance(p, pn.ScanExec):
            if idx >= len(p.schema):
                return None
            name = p.schema[idx].name
            if p.format == "__driver__":
                table = graph.scan_tables.get(p.table_name)
            elif p.source is not None:
                table = p.source
            else:
                return None
            if table is None or table.num_rows > BROADCAST_ROW_LIMIT \
                    or name not in table.column_names:
                return None
            return table.column(name)
        return None


def _probe_scan_target(p: pn.PlanNode, idx: int, stages_by_id,
                       default_stage: int):
    """Trace a probe-side key column to a worker-scanned leaf through
    key-preserving operators, possibly crossing into a producer stage.
    Returns (stage_id, scan_ordinal, column_index, field) or None."""
    stage_id = default_stage
    while True:
        if isinstance(p, StageInputExec):
            stage = stages_by_id.get(p.stage_id)
            if stage is None:
                return None
            stage_id = stage.stage_id
            p = stage.plan
            continue
        if isinstance(p, pn.FilterExec):
            p = p.input
            continue
        if isinstance(p, pn.ProjectExec):
            if idx >= len(p.exprs):
                return None
            e = p.exprs[idx][1]
            if not isinstance(e, rx.BoundRef):
                return None
            idx = e.index
            p = p.input
            continue
        if isinstance(p, pn.ScanExec):
            if idx >= len(p.schema):
                return None
            if not (p.format in ("parquet", "__driver__")
                    or p.source is not None):
                return None
            stage = stages_by_id.get(stage_id)
            if stage is None:
                return None
            scans = [n for n in pn.walk_plan(stage.plan)
                     if isinstance(n, pn.ScanExec)]
            for ord_, s in enumerate(scans):
                if s is p:
                    return stage_id, ord_, idx, p.schema[idx]
            return None
        return None


def _filter_entry(col, field, scan_ord: int, col_idx: int,
                  cap: int):
    import pyarrow.compute as pc

    from ..spec import data_type as dt_

    def raw(v):
        if v is None:
            return None
        if isinstance(field.dtype, dt_.DateType):
            return (v - datetime.date(1970, 1, 1)).days
        return int(v)

    try:
        mm = pc.min_max(col)
        lo, hi = raw(mm["min"].as_py()), raw(mm["max"].as_py())
    except Exception:  # noqa: BLE001 — filters are advisory
        return None
    if lo is None or hi is None:
        lo, hi = 1, 0  # empty/all-null build: an always-false range
    entry = {"scan": scan_ord, "column": col_idx, "name": field.name,
             "min": lo, "max": hi}
    try:
        vals = pc.unique(col.combine_chunks()
                         if hasattr(col, "combine_chunks") else col)
        vals = vals.drop_null()
        if len(vals) <= cap:
            entry["values"] = [raw(v) for v in vals.to_pylist()]
    except Exception:  # noqa: BLE001
        pass
    return entry


def apply_task_runtime_filters(plan: pn.PlanNode,
                               filters_json: str) -> pn.PlanNode:
    """Worker side: attach driver-shipped runtime filters to this task's
    scan fragment (scans matched by walk-order ordinal, which the codec
    round-trip and per-partition slicing both preserve)."""
    from ..metrics import record as _record_metric
    from ..plan import runtime_filters as rtfp

    try:
        entries = json.loads(filters_json)
    except ValueError:
        return plan
    if not isinstance(entries, list):
        return plan
    for e in entries:
        scans = [n for n in pn.walk_plan(plan)
                 if isinstance(n, pn.ScanExec)]
        try:
            scan = scans[int(e["scan"])]
            idx = int(e["column"])
            field = scan.schema[idx]
            if field.name != e.get("name") or \
                    not rtfp.supports_bounds(field.dtype):
                continue
            vals = e.get("values")
            conjs = rtfp.bounds_conjuncts(
                idx, field, int(e["min"]), int(e["max"]),
                None if vals is None else [int(v) for v in vals])
        except (KeyError, IndexError, TypeError, ValueError):
            continue
        plan = _replace_subtree(
            plan, scan, dataclasses.replace(
                scan,
                runtime_predicates=scan.runtime_predicates + conjs))
        try:
            _record_metric("execution.runtime_filter.pushed_count", 1,
                           site="cluster")
        except Exception:  # noqa: BLE001
            pass
    return plan


def _find_distributable_subtree(b: "_Builder", plan: pn.PlanNode):
    """DFS for the topmost subtree the builder can distribute."""
    for node in _topdown(plan):
        if node is plan:
            continue
        n_before = len(b.stages)
        got = b.build(node)
        if got is not None:
            return got, node
        del b.stages[n_before:]
    return None


def _topdown(p: pn.PlanNode):
    yield p
    for c in p.children:
        yield from _topdown(c)


def _replace_subtree(plan: pn.PlanNode, target: pn.PlanNode,
                     replacement: pn.PlanNode) -> pn.PlanNode:
    if plan is target:
        return replacement
    if isinstance(plan, pn.JoinExec):
        return dataclasses.replace(
            plan,
            left=_replace_subtree(plan.left, target, replacement),
            right=_replace_subtree(plan.right, target, replacement))
    if isinstance(plan, pn.UnionExec):
        return dataclasses.replace(plan, inputs=tuple(
            _replace_subtree(c, target, replacement) for c in plan.inputs))
    if hasattr(plan, "input") and plan.input is not None:
        return dataclasses.replace(
            plan, input=_replace_subtree(plan.input, target, replacement))
    return plan


# ---------------------------------------------------------------------------
# Worker-side exchange helpers
# ---------------------------------------------------------------------------

def hash_partition_table(table, key_columns, num_channels: int):
    """Split an arrow table into hash channels on the key columns.

    Value-based (dictionary-safe) deterministic hashing so producers on
    different workers route equal keys to the same channel. ZERO key
    columns (a global aggregate's partial stage) route every row to
    channel 0: the single final task consumes exactly one channel."""
    import numpy as np
    import pandas as pd

    if table.num_rows == 0 or num_channels <= 1 or not key_columns:
        return [table] + [table.slice(0, 0)] * (num_channels - 1)
    keys = table.select(list(key_columns)).to_pandas()
    h = pd.util.hash_pandas_object(keys, index=False).values
    ch = (h % np.uint64(num_channels)).astype(np.int64)
    order = np.argsort(ch, kind="stable")
    taken = table.take(order)
    bounds = np.searchsorted(ch[order], np.arange(num_channels + 1))
    return [taken.slice(int(bounds[i]), int(bounds[i + 1] - bounds[i]))
            for i in range(num_channels)]


def attach_stage_inputs(plan: pn.PlanNode, tables: Dict[int, object]
                        ) -> pn.PlanNode:
    """Replace StageInputExec leaves with memory scans of fetched tables."""

    def repl(p):
        if isinstance(p, StageInputExec):
            return pn.ScanExec(tuple(p.schema), tables[p.stage_id], (),
                               "memory")
        if isinstance(p, pn.JoinExec):
            return dataclasses.replace(p, left=repl(p.left),
                                       right=repl(p.right))
        if isinstance(p, pn.UnionExec):
            return dataclasses.replace(p, inputs=tuple(repl(c)
                                                       for c in p.inputs))
        if hasattr(p, "input") and p.input is not None:
            return dataclasses.replace(p, input=repl(p.input))
        return p

    return repl(plan)
