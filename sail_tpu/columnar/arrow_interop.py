"""Arrow ⇄ device-columnar conversion.

Host boundary of the engine: pyarrow Tables (from Parquet/CSV/JSON scans or
client LocalRelations) become padded DeviceBatches, and query results come
back as Arrow for the protocol layer. Mirrors the role of the reference's
use of arrow-rs as the in-memory format (SURVEY.md §2.1 sail-common /
§2.6 sail-data-source), re-shaped for HBM residency:

- fixed-width types upload as padded device arrays
- decimal128(p≤18) uploads as the *unscaled* int64 (exact arithmetic on
  device; the low 64 bits of the two's-complement decimal128 value equal
  the int64 value whenever it fits)
- strings/binary dictionary-encode; codes upload, dictionary stays host-side
"""

from __future__ import annotations

import datetime
import decimal
from typing import Dict, Optional, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from ..spec import data_type as dt
from .batch import (Column, DeviceBatch, HostBatch, bucket_capacity,
                    make_batch)


def arrow_type_to_spec(t: pa.DataType) -> dt.DataType:
    if pa.types.is_boolean(t):
        return dt.BooleanType()
    if pa.types.is_int8(t):
        return dt.ByteType()
    if pa.types.is_int16(t):
        return dt.ShortType()
    if pa.types.is_int32(t):
        return dt.IntegerType()
    if pa.types.is_int64(t):
        return dt.LongType()
    if pa.types.is_uint8(t):
        return dt.ShortType()
    if pa.types.is_uint16(t):
        return dt.IntegerType()
    if pa.types.is_uint32(t) or pa.types.is_uint64(t):
        return dt.LongType()
    if pa.types.is_float32(t):
        return dt.FloatType()
    if pa.types.is_float64(t):
        return dt.DoubleType()
    if pa.types.is_decimal(t):
        return dt.DecimalType(t.precision, t.scale)
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return dt.StringType()
    if pa.types.is_binary(t) or pa.types.is_large_binary(t):
        return dt.BinaryType()
    if pa.types.is_date32(t):
        return dt.DateType()
    if pa.types.is_date64(t):
        return dt.DateType()
    if pa.types.is_timestamp(t):
        return dt.TimestampType(t.tz)
    if pa.types.is_time(t):
        return dt.TimeType()
    if pa.types.is_duration(t):
        return dt.DayTimeIntervalType()
    if pa.types.is_interval(t):
        return dt.YearMonthIntervalType()
    if pa.types.is_dictionary(t):
        return arrow_type_to_spec(t.value_type)
    if pa.types.is_null(t):
        return dt.NullType()
    if pa.types.is_list(t) or pa.types.is_large_list(t):
        return dt.ArrayType(arrow_type_to_spec(t.value_type))
    if pa.types.is_struct(t):
        return dt.StructType(tuple(
            dt.StructField(f.name, arrow_type_to_spec(f.type), f.nullable)
            for f in t))
    if pa.types.is_map(t):
        return dt.MapType(arrow_type_to_spec(t.key_type), arrow_type_to_spec(t.item_type))
    raise TypeError(f"unsupported arrow type {t}")


def spec_type_to_arrow(d: dt.DataType) -> pa.DataType:
    if isinstance(d, dt.BooleanType):
        return pa.bool_()
    if isinstance(d, dt.ByteType):
        return pa.int8()
    if isinstance(d, dt.ShortType):
        return pa.int16()
    if isinstance(d, dt.IntegerType):
        return pa.int32()
    if isinstance(d, dt.LongType):
        return pa.int64()
    if isinstance(d, dt.FloatType):
        return pa.float32()
    if isinstance(d, dt.DoubleType):
        return pa.float64()
    if isinstance(d, dt.DecimalType):
        return pa.decimal128(d.precision, d.scale)
    if isinstance(d, dt.StringType):
        return pa.string()
    if isinstance(d, dt.BinaryType):
        return pa.binary()
    if isinstance(d, dt.DateType):
        return pa.date32()
    if isinstance(d, dt.TimestampType):
        return pa.timestamp("us", tz=d.timezone)
    if isinstance(d, dt.TimeType):
        return pa.time64("us")
    if isinstance(d, dt.DayTimeIntervalType):
        return pa.duration("us")
    if isinstance(d, dt.YearMonthIntervalType):
        return pa.month_day_nano_interval()  # months carry the value
    if isinstance(d, dt.NullType):
        return pa.null()
    if isinstance(d, dt.ArrayType):
        return pa.list_(spec_type_to_arrow(d.element_type))
    if isinstance(d, dt.StructType):
        return pa.struct([pa.field(f.name, spec_type_to_arrow(f.data_type), f.nullable)
                          for f in d.fields])
    if isinstance(d, dt.MapType):
        return pa.map_(spec_type_to_arrow(d.key_type), spec_type_to_arrow(d.value_type))
    raise TypeError(f"unsupported spec type {d}")


def _decimal_to_unscaled_int64(arr: pa.Array, validity=None) -> np.ndarray:
    """Unscaled int64 values of a decimal128 array (zero-copy-ish).

    Validates that every value fits in int64 (high word must be the sign
    extension of the low word) — wide-decimal overflow is a loud error, not
    silent corruption."""
    arr = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
    buf = arr.buffers()[1]
    raw = np.frombuffer(buf, dtype=np.int64)
    # decimal128 is 16 bytes LE; low word at even indices (plus array offset)
    lo = raw[2 * arr.offset::2][: len(arr)]
    hi = raw[2 * arr.offset + 1::2][: len(arr)]
    ok = hi == (lo >> 63)
    if validity is not None:
        ok = ok | ~validity
    if len(lo) and not ok.all():
        raise TypeError(
            f"decimal values exceed the engine's int64 unscaled range "
            f"(type {arr.type}); reduce precision or cast to double")
    return lo.copy()


def _unscaled_int64_to_decimal(vals: np.ndarray, validity: Optional[np.ndarray],
                               d: dt.DecimalType) -> pa.Array:
    """Vectorized decimal128 construction from unscaled int64 values:
    low word = the value, high word = its sign extension."""
    n = len(vals)
    words = np.empty((n, 2), dtype=np.int64)
    words[:, 0] = vals
    words[:, 1] = vals >> 63  # arithmetic shift: 0 or -1
    data_buf = pa.py_buffer(words.tobytes())
    if validity is not None:
        null_buf = pa.py_buffer(np.packbits(validity.astype(np.uint8), bitorder="little").tobytes())
    else:
        null_buf = None
    return pa.Array.from_buffers(pa.decimal128(d.precision, d.scale), n,
                                 [null_buf, data_buf])


def from_arrow(table: pa.Table, capacity: Optional[int] = None,
               bucket_key=None) -> HostBatch:
    """Convert a pyarrow Table to a HostBatch (uploads to default device).

    ``bucket_key`` names the consuming program (structural cache key) so
    the pinned-bucket registry can hold the padded capacity stable
    across calls — see :func:`columnar.batch.bucket_capacity`."""
    n = table.num_rows
    cap = capacity if capacity is not None else \
        bucket_capacity(n, key=bucket_key)
    columns: Dict[str, Tuple[np.ndarray, Optional[np.ndarray], dt.DataType]] = {}
    dicts: Dict[str, pa.Array] = {}
    for name, col in zip(table.column_names, table.columns):
        spec_t = arrow_type_to_spec(col.type)
        arr = col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col
        validity = None
        if arr.null_count > 0:
            validity = np.asarray(pc.is_valid(arr))
        if pa.types.is_uint64(arr.type):
            mx = pc.max(arr).as_py()
            if mx is not None and mx >= 2**63:
                raise TypeError(
                    f"column {name!r}: uint64 values >= 2^63 cannot be represented "
                    f"on device (int64); cast to decimal or string first")
        if isinstance(spec_t, (dt.StringType, dt.BinaryType)):
            if pa.types.is_dictionary(arr.type):
                denc = arr
            else:
                denc = pc.dictionary_encode(arr)
            if isinstance(denc, pa.ChunkedArray):
                denc = denc.combine_chunks()
            codes = np.asarray(denc.indices.fill_null(0)).astype(np.int32)
            dicts[name] = denc.dictionary
            columns[name] = (codes, validity, spec_t)
        elif isinstance(spec_t, dt.DecimalType) and spec_t.physical_dtype == "int64":
            if pa.types.is_decimal256(arr.type):
                arr = arr.cast(pa.decimal128(spec_t.precision, spec_t.scale))
            vals = _decimal_to_unscaled_int64(arr, validity)
            columns[name] = (vals, validity, spec_t)
        elif isinstance(spec_t, dt.DecimalType):
            vals = np.asarray(arr.cast(pa.float64()).fill_null(0.0))
            columns[name] = (vals, validity, spec_t)
        elif isinstance(spec_t, dt.NullType):
            columns[name] = (np.zeros(n, dtype=np.int8), np.zeros(n, dtype=bool), spec_t)
        elif isinstance(spec_t, (dt.ArrayType, dt.StructType, dt.MapType)):
            # Nested types stay host-side in v0: dictionary-encode the whole
            # value so the device carries an opaque int32 handle.
            import pickle
            py = arr.to_pylist()
            uniq: Dict[bytes, int] = {}
            codes = np.empty(n, dtype=np.int32)
            values = []
            for i, v in enumerate(py):
                k = pickle.dumps(v)
                if k not in uniq:
                    uniq[k] = len(values)
                    values.append(v)
                codes[i] = uniq[k]
            dicts[name] = pa.array(values, type=arr.type)
            columns[name] = (codes, validity, spec_t)
        else:
            # Temporal types upload as their epoch integers.
            if isinstance(spec_t, dt.DateType):
                if pa.types.is_date64(arr.type):
                    arr = arr.cast(pa.date32())
                arr = arr.view(pa.int32())
            elif isinstance(spec_t, dt.TimestampType):
                arr = arr.cast(pa.timestamp("us", tz=arr.type.tz)).view(pa.int64())
            elif isinstance(spec_t, dt.DayTimeIntervalType):
                arr = arr.cast(pa.duration("us")).view(pa.int64())
            elif isinstance(spec_t, dt.TimeType):
                arr = arr.cast(pa.time64("us")).view(pa.int64())
            elif isinstance(spec_t, dt.YearMonthIntervalType) and \
                    pa.types.is_interval(arr.type):
                months = np.array(
                    [0 if v is None else v[0] for v in arr.to_pylist()],
                    dtype=np.int32)
                columns[name] = (months, validity, spec_t)
                continue
            fill = False if pa.types.is_boolean(arr.type) else 0
            np_vals = np.asarray(arr.fill_null(fill) if arr.null_count else arr)
            columns[name] = (np_vals, validity, spec_t)
    device = make_batch(columns, n, cap)
    return HostBatch(device, dicts)


def column_values_to_arrow(data, validity, d, dictionary=None) -> pa.Array:
    """Convert host numpy column data (physical encoding) to a pa.Array."""
    name_in_dicts = dictionary is not None
    return _column_to_arrow(data, validity, d, dictionary, name_in_dicts)


def to_arrow(batch: HostBatch) -> pa.Table:
    """Download a HostBatch to a pyarrow Table (live rows only, in order).

    All device arrays (sel + every column's data/validity) are fetched in
    ONE ``jax.device_get`` call: on a remote accelerator each blocking
    fetch pays a full round trip, so per-column ``np.asarray`` loops are
    O(columns) round trips while a batched get overlaps the transfers."""
    import jax

    dev = batch.device
    fetch = {"sel": dev.sel}
    for name, col in dev.columns.items():
        fetch[f"d:{name}"] = col.data
        if col.validity is not None:
            fetch[f"v:{name}"] = col.validity
    host = jax.device_get(fetch)
    sel = np.asarray(host["sel"])
    idx = np.nonzero(sel)[0]
    arrays = []
    fields = []
    for name, col in dev.columns.items():
        data = np.asarray(host[f"d:{name}"])[idx]
        validity = (np.asarray(host[f"v:{name}"])[idx]
                    if col.validity is not None else None)
        arr = _column_to_arrow(data, validity, col.dtype,
                               batch.dicts.get(name), name in batch.dicts)
        arrays.append(arr)
        fields.append(pa.field(name, arr.type, nullable=True))
    return pa.Table.from_arrays(arrays, schema=pa.schema(fields))


def _column_to_arrow(data, validity, d, dictionary, has_dict) -> pa.Array:
    if isinstance(d, (dt.StringType, dt.BinaryType)) and has_dict:
        codes = pa.array(data.astype(np.int32),
                         mask=None if validity is None else ~validity)
        arr = pa.DictionaryArray.from_arrays(codes, dictionary).cast(
            pa.string() if isinstance(d, dt.StringType) else pa.binary())
    elif isinstance(d, (dt.ArrayType, dt.StructType, dt.MapType)) and has_dict:
        # nested dictionaries can't cast; take() materializes (null index →
        # null value)
        codes = pa.array(data.astype(np.int64),
                         mask=None if validity is None else ~validity)
        arr = dictionary.take(codes)
    elif isinstance(d, dt.DecimalType) and d.physical_dtype == "int64":
        arr = _unscaled_int64_to_decimal(data, validity, d)
    elif isinstance(d, dt.DecimalType):
        arr = pa.array(data, mask=None if validity is None else ~validity)
        arr = arr.cast(pa.decimal128(d.precision, d.scale), safe=False)
    elif isinstance(d, dt.NullType):
        arr = pa.nulls(len(data))
    else:
        at = spec_type_to_arrow(d)
        if isinstance(d, dt.TimestampType):
            arr = pa.array(data.astype("datetime64[us]"),
                           mask=None if validity is None else ~validity).cast(at)
        elif isinstance(d, dt.DateType):
            arr = pa.array(data.astype(np.int32),
                           mask=None if validity is None else ~validity).cast(at)
        elif isinstance(d, dt.DayTimeIntervalType):
            arr = pa.array(data.astype("timedelta64[us]"),
                           mask=None if validity is None else ~validity)
        elif isinstance(d, dt.YearMonthIntervalType):
            vals = [None if (validity is not None and not validity[i])
                    else (int(data[i]), 0, 0) for i in range(len(data))]
            arr = pa.array(vals, type=pa.month_day_nano_interval())
        elif isinstance(d, dt.TimeType):
            arr = pa.array(data.astype(np.int64),
                           mask=None if validity is None else ~validity
                           ).cast(pa.time64("us"))
        else:
            arr = pa.array(data, mask=None if validity is None else ~validity)
            if arr.type != at:
                arr = arr.cast(at, safe=False)
    return arr


def unify_dictionaries(dict_a: pa.Array, dict_b: pa.Array) -> Tuple[pa.Array, np.ndarray, np.ndarray]:
    """Merge two dictionaries; returns (merged, remap_a, remap_b) where
    remap_x maps old codes → merged codes. Used before joins/unions on
    string columns so device-side code comparison is exact."""
    merged_tbl = pa.concat_arrays([dict_a.cast(pa.string()), dict_b.cast(pa.string())])
    enc = pc.dictionary_encode(merged_tbl)
    if isinstance(enc, pa.ChunkedArray):
        enc = enc.combine_chunks()
    codes = np.asarray(enc.indices)
    remap_a = codes[: len(dict_a)].astype(np.int32)
    remap_b = codes[len(dict_a):].astype(np.int32)
    return enc.dictionary, remap_a, remap_b


def dictionary_ranks(dictionary: pa.Array) -> np.ndarray:
    """Order-preserving rank per dictionary code (for ORDER BY / range
    comparisons on dictionary-encoded strings)."""
    order = pc.sort_indices(dictionary)
    ranks = np.empty(len(dictionary), dtype=np.int32)
    ranks[np.asarray(order)] = np.arange(len(dictionary), dtype=np.int32)
    return ranks
