"""Device-resident columnar batches.

This is the TPU-native replacement for the reference's Arrow RecordBatch
execution substrate (reference role: arrow-rs arrays flowing through
DataFusion operators). Design, driven by XLA's static-shape compilation
model:

- A ``Column`` is a fixed-capacity padded device array plus an optional
  validity (null) mask. Capacity is a *static* (compile-time) property;
  live row count is carried dynamically by the batch selection mask.
- A ``DeviceBatch`` holds named columns plus a boolean *selection* mask;
  filters never compact (compaction creates dynamic shapes) — they narrow
  the selection, and XLA fuses the mask arithmetic into downstream ops.
  Explicit ``compact`` reorders live rows to the front when an op (sort,
  join build, limit) benefits.
- Variable-width data (strings/binary) is dictionary-encoded: the device
  carries int32 codes; the dictionary (a pyarrow Array) stays host-side in
  the ``HostBatch`` wrapper and never enters jit.

Both Column and DeviceBatch are pytrees, so jitted kernels take and return
them directly.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..spec import data_type as dt


@jax.tree_util.register_pytree_node_class
class Column:
    """A padded device array + optional validity mask + logical type."""

    __slots__ = ("data", "validity", "dtype")

    def __init__(self, data, validity, dtype: dt.DataType):
        self.data = data
        self.validity = validity  # bool[capacity] or None (all valid)
        self.dtype = dtype

    @property
    def capacity(self) -> int:
        return self.data.shape[0]

    def valid_mask(self):
        if self.validity is None:
            return jnp.ones(self.data.shape[0], dtype=jnp.bool_)
        return self.validity

    def with_data(self, data, validity="__keep__") -> "Column":
        v = self.validity if isinstance(validity, str) else validity
        return Column(data, v, self.dtype)

    def tree_flatten(self):
        return (self.data, self.validity), (self.dtype,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        data, validity = children
        return cls(data, validity, aux[0])

    def __repr__(self):
        return f"Column({self.dtype.simple_string()}, cap={self.data.shape[0] if hasattr(self.data, 'shape') else '?'})"


@jax.tree_util.register_pytree_node_class
class DeviceBatch:
    """Named columns + selection mask. All arrays share one capacity."""

    __slots__ = ("columns", "sel")

    def __init__(self, columns: Dict[str, Column], sel):
        self.columns = columns
        self.sel = sel  # bool[capacity]

    @property
    def capacity(self) -> int:
        return self.sel.shape[0]

    @property
    def names(self) -> Tuple[str, ...]:
        return tuple(self.columns.keys())

    def column(self, name: str) -> Column:
        return self.columns[name]

    def num_rows(self):
        """Dynamic live row count (device scalar)."""
        return jnp.sum(self.sel.astype(jnp.int32))

    def select(self, names) -> "DeviceBatch":
        return DeviceBatch({n: self.columns[n] for n in names}, self.sel)

    def with_columns(self, new: Dict[str, Column]) -> "DeviceBatch":
        cols = dict(self.columns)
        cols.update(new)
        return DeviceBatch(cols, self.sel)

    def with_sel(self, sel) -> "DeviceBatch":
        return DeviceBatch(self.columns, sel)

    def tree_flatten(self):
        names = tuple(self.columns.keys())
        children = tuple(self.columns[n] for n in names) + (self.sel,)
        return children, names

    @classmethod
    def tree_unflatten(cls, names, children):
        cols = dict(zip(names, children[:-1]))
        return cls(cols, children[-1])

    def __repr__(self):
        return f"DeviceBatch({list(self.columns)}, cap={self.capacity})"


@dataclasses.dataclass
class HostBatch:
    """A DeviceBatch plus its host-side string dictionaries.

    Physical operators pass HostBatch between themselves; the jit boundary
    receives only the inner DeviceBatch pytree. ``dicts`` maps column name →
    pyarrow Array of dictionary values for String/Binary columns.
    """

    device: DeviceBatch
    dicts: Dict[str, object] = dataclasses.field(default_factory=dict)

    @property
    def capacity(self) -> int:
        return self.device.capacity

    @property
    def names(self) -> Tuple[str, ...]:
        return self.device.names

    def schema_types(self) -> Dict[str, dt.DataType]:
        return {n: c.dtype for n, c in self.device.columns.items()}

    def num_rows(self) -> int:
        return int(self.device.num_rows())


_CAPACITY_MIN: Optional[int] = None


def _capacity_min() -> int:
    """``execution.batch_capacity_min``, read once per process (this
    sits under every batch construction)."""
    global _CAPACITY_MIN
    if _CAPACITY_MIN is None:
        try:
            from ..config import get as config_get
            _CAPACITY_MIN = max(1, int(config_get(
                "execution.batch_capacity_min", 8)))
        except (TypeError, ValueError, ImportError):
            _CAPACITY_MIN = 8
    return _CAPACITY_MIN


def round_capacity(n: int, minimum: Optional[int] = None) -> int:
    """Round a row count up to the padded device capacity.

    Buckets to 1.25^k-ish steps on top of powers of two fragments so that
    repeated scans with similar sizes hit the jit cache instead of
    recompiling (XLA static shapes).
    """
    if minimum is None:
        minimum = _capacity_min()
    if n <= minimum:
        return minimum
    p = 1 << (int(n - 1).bit_length() - 1)  # largest pow2 <= n-1... p < n <= 2p
    for frac in (p + p // 4, p + p // 2, p + 3 * (p // 4), 2 * p):
        if n <= frac:
            return frac
    return 2 * p


def bucket_capacity(n: int, key=None,
                    minimum: Optional[int] = None) -> int:
    """THE capacity policy: every padded-capacity derivation in the
    engine routes through here (the capacity-policy lint fails direct
    ``round_capacity`` calls anywhere else).

    With a ``key`` (a structural program/stage cache key — the same
    vocabulary the retrace ledger fingerprints), delegates to the
    pinned grow-only bucket registry (``exec/capacity.py``): once a
    program is warmed its bucket only grows, and growth needs a
    sustained overflow streak, so oscillating input sizes stop crossing
    bucket boundaries (zero capacity-bucket retraces after warmup).
    Without a key — or with pinning disabled — this is plain
    ``round_capacity`` rounding.
    """
    if key is None:
        return round_capacity(n, minimum)
    try:
        from ..exec.capacity import bucket_for
    except ImportError:
        return round_capacity(n, minimum)
    return bucket_for(key, n, minimum)


def physical_jnp_dtype(d: dt.DataType):
    if isinstance(d, (dt.ArrayType, dt.MapType, dt.StructType)):
        return jnp.dtype("int32")  # dictionary code handle (values on host)
    name = d.physical_dtype
    if name is None:
        raise TypeError(f"type {d.simple_string()} has no device representation")
    return jnp.dtype(name)


def make_batch(columns: Dict[str, Tuple[np.ndarray, Optional[np.ndarray], dt.DataType]],
               num_rows: int, capacity: Optional[int] = None,
               bucket_key=None) -> DeviceBatch:
    import jax

    cap = capacity if capacity is not None else \
        bucket_capacity(num_rows, key=bucket_key)
    host = {}
    types = {}
    for name, (values, validity, dtype) in columns.items():
        n = len(values)
        data = np.zeros(cap, dtype=physical_jnp_dtype(dtype))
        data[:n] = values
        v = None
        if validity is not None:
            v = np.zeros(cap, dtype=bool)
            v[:n] = validity
        host[name] = (data, v)
        types[name] = dtype
    sel = np.zeros(cap, dtype=bool)
    sel[:num_rows] = True
    # ONE batched transfer for all columns (a per-column jnp.asarray costs
    # ~1 ms of dispatch each; the output of a small aggregate was paying
    # 10+ ms in uploads alone)
    from ..profiler import note_transfer_bytes
    note_transfer_bytes(sel.nbytes + sum(
        d.nbytes + (v.nbytes if v is not None else 0)
        for d, v in host.values()))
    dhost, dsel = jax.device_put((host, sel))
    cols = {name: Column(dhost[name][0], dhost[name][1], types[name])
            for name in host}
    return DeviceBatch(cols, dsel)


def empty_batch(types: Dict[str, dt.DataType], capacity: int = 8) -> DeviceBatch:
    cols = {}
    for name, d in types.items():
        jdt = physical_jnp_dtype(d)
        cols[name] = Column(jnp.zeros(capacity, dtype=jdt),
                            jnp.zeros(capacity, dtype=jnp.bool_), d)
    return DeviceBatch(cols, jnp.zeros(capacity, dtype=jnp.bool_))
