"""Sort / permutation kernels.

Sorting is the workhorse primitive of this engine: ORDER BY, group-by
(sort-based aggregation), and joins (sort-probe) all reduce to argsort +
gather, which XLA lowers to efficient parallel sorts — unlike scatter-heavy
hash tables, which serialize on TPU. Total order over null/dead rows is
obtained by mapping every key column to order-preserving uint64 bits
(IEEE-754 trick for floats, sign-bias for ints) with null and selection
flags folded in, so one stable argsort per key column suffices.

Reference role: SortExec / sort-merge machinery in DataFusion (SURVEY.md
§2.4-2.5), re-designed for XLA static shapes.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from ..columnar.batch import Column, DeviceBatch
from ..spec import data_type as dt


def _order_bits(data, d: dt.DataType) -> jnp.ndarray:
    """Map values to uint64 whose unsigned order equals the value order."""
    pd = d.physical_dtype
    if pd == "bool":
        return data.astype(jnp.uint64)
    if pd in ("int8", "int16", "int32", "int64"):
        x = data.astype(jnp.int64)
        return (x.astype(jnp.uint64)) ^ jnp.uint64(1 << 63)
    if pd == "float32":
        from .hash import _normalize_float
        b = jax.lax.bitcast_convert_type(_normalize_float(data.astype(jnp.float32)),
                                         jnp.uint32).astype(jnp.uint64)
        neg = (b >> jnp.uint64(31)) != 0
        return jnp.where(neg, ~b & jnp.uint64(0xFFFFFFFF), b | jnp.uint64(0x80000000))
    if pd == "float64":
        from .hash import _normalize_float
        b = jax.lax.bitcast_convert_type(_normalize_float(data.astype(jnp.float64)), jnp.uint64)
        neg = (b >> jnp.uint64(63)) != 0
        return jnp.where(neg, ~b, b | jnp.uint64(1 << 63))
    raise TypeError(pd)


def order_bits(data, d: dt.DataType, ascending: bool = True) -> jnp.ndarray:
    """Full-width uint64 order key (exact: distinct values stay distinct).
    Null placement is handled by a separate stable pass in lexsort_perm."""
    bits = _order_bits(data, d)
    return bits if ascending else ~bits


def lexsort_perm(keys, sel=None) -> jnp.ndarray:
    """Stable lexicographic sort permutation.

    ``keys``: sequence of (data, validity, dtype, ascending, nulls_first),
    most significant first. Spark null ordering (default nulls first when
    ascending, last when descending). Dead rows (sel == False) always sort
    last. Returns int32 permutation of row indices.
    """
    n = keys[0][0].shape[0] if keys else sel.shape[0]
    perm = jnp.arange(n, dtype=jnp.int32)
    for data, validity, d, asc, nf in reversed(list(keys)):
        bits = order_bits(data, d, asc)
        perm = perm[jnp.argsort(bits[perm], stable=True)]
        if validity is not None:
            nulls_first = asc if nf is None else nf
            null_rank = (validity if nulls_first else ~validity).astype(jnp.uint8)
            perm = perm[jnp.argsort(null_rank[perm], stable=True)]
    if sel is not None:
        dead = (~sel).astype(jnp.uint8)
        perm = perm[jnp.argsort(dead[perm], stable=True)]
    return perm


def take_column(col: Column, perm) -> Column:
    data = col.data[perm]
    validity = None if col.validity is None else col.validity[perm]
    return Column(data, validity, col.dtype)


def take_batch(batch: DeviceBatch, perm) -> DeviceBatch:
    cols = {n: take_column(c, perm) for n, c in batch.columns.items()}
    return DeviceBatch(cols, batch.sel[perm])


def compact_perm(sel) -> jnp.ndarray:
    """Permutation moving live rows to the front, preserving order."""
    dead = (~sel).astype(jnp.uint8)
    return jnp.argsort(dead, stable=True).astype(jnp.int32)


def compact(batch: DeviceBatch) -> DeviceBatch:
    return take_batch(batch, compact_perm(batch.sel))


def limit(batch: DeviceBatch, n: int, offset: int = 0) -> DeviceBatch:
    """LIMIT/OFFSET over live rows (compacts first)."""
    out = compact(batch)
    idx = jnp.arange(out.capacity, dtype=jnp.int32)
    count = out.num_rows()
    new_sel = (idx >= offset) & (idx < jnp.minimum(count, offset + n))
    return out.with_sel(new_sel)
