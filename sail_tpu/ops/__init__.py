"""Device-side relational kernels (jit-compiled, static-shape).

The TPU-native counterpart of DataFusion's physical operators + arrow-rs
compute kernels (SURVEY.md §2.4-2.6): sort/compact/limit, sort-based
grouped aggregation, sort-probe equi-joins, key hashing/packing.
"""

from . import aggregate, hash, join, sort  # noqa: F401
