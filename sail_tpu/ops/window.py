"""Window function kernels (sort + segmented prefix scans).

Reference role: sail-function's window functions + DataFusion's
WindowAggExec (SURVEY.md §2.6). TPU-first design: one sort by
(partition keys, order keys), then every window function is a segmented
scan/gather over the sorted order — cumulative sums with segment-start
subtraction for running aggregates, rank arithmetic from segment offsets —
followed by an inverse-permutation gather to restore row order. No
per-partition loops; everything is O(n log n) sort + O(n) scans.
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar.batch import Column
from ..spec import data_type as dt
from .sort import lexsort_perm, order_bits


class WindowContext:
    """Sorted row order + partition segmentation, shared by all windows
    with the same (partition_by, order_by)."""

    def __init__(self, perm, inv_perm, seg_start, seg_len, pos_in_seg,
                 alive_sorted):
        self.perm = perm                  # sorted order (alive rows first)
        self.inv_perm = inv_perm          # original position ← sorted position
        self.seg_start = seg_start        # int32[n] start index of row's segment
        self.seg_len = seg_len            # int32[n]
        self.pos = pos_in_seg             # int32[n] 0-based position in segment
        self.alive = alive_sorted


def build_window_context(partition_cols: Sequence[Column],
                         order_keys: Sequence[Tuple], sel) -> WindowContext:
    """order_keys: (data, validity, dtype, ascending, nulls_first) tuples."""
    n = sel.shape[0]
    keys = []
    for c in partition_cols:
        keys.append((c.data, c.validity, c.dtype, True, None))
    keys.extend(order_keys)
    perm = lexsort_perm(keys, sel) if keys else jnp.arange(n, dtype=jnp.int32)
    if keys == [] and sel is not None:
        from .sort import compact_perm
        perm = compact_perm(sel)
    alive = sel[perm]
    # new segment when any partition key changes (among alive rows)
    new_seg = jnp.zeros(n, dtype=jnp.bool_).at[0].set(True)
    for c in partition_cols:
        d = c.data[perm]
        prev = jnp.roll(d, 1)
        diff = d != prev
        if jnp.issubdtype(d.dtype, jnp.floating):
            diff = diff & ~(jnp.isnan(d) & jnp.isnan(prev))
        if c.validity is not None:
            v = c.validity[perm]
            pv = jnp.roll(v, 1)
            diff = diff | (v != pv)
        new_seg = new_seg | diff
    new_seg = new_seg.at[0].set(True)
    # dead rows sort last; give them their own segment start
    seg_id = jnp.cumsum(new_seg.astype(jnp.int32)) - 1
    idx = jnp.arange(n, dtype=jnp.int32)
    seg_start = jax.ops.segment_min(idx, seg_id, num_segments=n)
    seg_start = seg_start[seg_id]
    seg_end = jax.ops.segment_max(idx, seg_id, num_segments=n)
    seg_end = seg_end[seg_id]
    # clip segment to alive prefix
    alive_count = jnp.sum(alive.astype(jnp.int32))
    seg_end = jnp.minimum(seg_end, alive_count - 1)
    seg_len = jnp.maximum(seg_end - seg_start + 1, 0)
    pos = idx - seg_start
    inv_perm = jnp.zeros(n, dtype=jnp.int32).at[perm].set(idx)
    return WindowContext(perm, inv_perm, seg_start, seg_len, pos, alive)


def _unsort(ctx: WindowContext, sorted_vals):
    return sorted_vals[ctx.inv_perm]


def _peer_group_start(ctx: WindowContext, order_key_bits) -> jnp.ndarray:
    """First position of each row's peer group (equal order keys).

    ``order_key_bits``: list of (bits, validity|None) in SORTED order — a
    NULL order key is never a peer of a non-NULL row even when the stored
    fill value collides."""
    n = ctx.pos.shape[0]
    if not order_key_bits:
        return ctx.seg_start
    change = jnp.zeros(n, dtype=jnp.bool_)
    for bits, valid in order_key_bits:
        change = change | (bits != jnp.roll(bits, 1))
        if valid is not None:
            change = change | (valid != jnp.roll(valid, 1))
    change = change | (ctx.pos == 0)
    change = change.at[0].set(True)
    grp = jnp.cumsum(change.astype(jnp.int32)) - 1
    idx = jnp.arange(n, dtype=jnp.int32)
    start = jax.ops.segment_min(idx, grp, num_segments=n)
    return start[grp]


def row_number(ctx: WindowContext) -> jnp.ndarray:
    return _unsort(ctx, ctx.pos.astype(jnp.int64) + 1)


def rank(ctx: WindowContext, order_key_bits) -> jnp.ndarray:
    start = _peer_group_start(ctx, order_key_bits)
    return _unsort(ctx, (start - ctx.seg_start).astype(jnp.int64) + 1)


def dense_rank(ctx: WindowContext, order_key_bits) -> jnp.ndarray:
    n = ctx.pos.shape[0]
    start = _peer_group_start(ctx, order_key_bits)
    # count distinct peer groups up to and including this row's, per segment
    firsts = (jnp.arange(n, dtype=jnp.int32) == start).astype(jnp.int64)
    cum = jnp.cumsum(firsts)
    seg_first_cum = cum[ctx.seg_start] - firsts[ctx.seg_start]
    return _unsort(ctx, cum - seg_first_cum)


def peer_group_end(ctx: WindowContext, order_key_bits) -> jnp.ndarray:
    """Last position of each row's peer group (for RANGE frames)."""
    n = ctx.pos.shape[0]
    start = _peer_group_start(ctx, order_key_bits)
    grp_change = jnp.arange(n, dtype=jnp.int32) == start
    grp = jnp.cumsum(grp_change.astype(jnp.int32)) - 1
    idx = jnp.arange(n, dtype=jnp.int32)
    return jax.ops.segment_max(idx, grp, num_segments=n)[grp]


def percent_rank(ctx: WindowContext, order_key_bits) -> jnp.ndarray:
    start = _peer_group_start(ctx, order_key_bits)
    r = (start - ctx.seg_start).astype(jnp.float64)
    denom = jnp.maximum(ctx.seg_len - 1, 1).astype(jnp.float64)
    return _unsort(ctx, jnp.where(ctx.seg_len > 1, r / denom, 0.0))


def cume_dist(ctx: WindowContext, order_key_bits) -> jnp.ndarray:
    # peers share the HIGHEST position of the peer group
    n = ctx.pos.shape[0]
    start = _peer_group_start(ctx, order_key_bits)
    grp_change = jnp.arange(n, dtype=jnp.int32) == start
    grp = jnp.cumsum(grp_change.astype(jnp.int32)) - 1
    idx = jnp.arange(n, dtype=jnp.int32)
    grp_end = jax.ops.segment_max(idx, grp, num_segments=n)[grp]
    return _unsort(ctx, (grp_end - ctx.seg_start + 1).astype(jnp.float64)
                   / jnp.maximum(ctx.seg_len, 1).astype(jnp.float64))


def ntile(ctx: WindowContext, n_tiles: int) -> jnp.ndarray:
    sl = jnp.maximum(ctx.seg_len, 1).astype(jnp.int64)
    pos = ctx.pos.astype(jnp.int64)
    base = sl // n_tiles
    rem = sl % n_tiles
    # first `rem` tiles have base+1 rows
    big = rem * (base + 1)
    tile = jnp.where(pos < big,
                     pos // jnp.maximum(base + 1, 1),
                     rem + (pos - big) // jnp.maximum(base, 1))
    return _unsort(ctx, jnp.clip(tile, 0, n_tiles - 1) + 1)


def shift(ctx: WindowContext, value: Column, offset: int, default=None):
    """lag (offset>0 looks back) / lead (negative looks forward)."""
    n = ctx.pos.shape[0]
    sorted_d = value.data[ctx.perm]
    sorted_v = value.validity[ctx.perm] if value.validity is not None else None
    idx = jnp.arange(n, dtype=jnp.int32)
    src = idx - offset
    in_seg = (src >= ctx.seg_start) & (src < ctx.seg_start + ctx.seg_len)
    src_c = jnp.clip(src, 0, n - 1)
    data = sorted_d[src_c]
    validity = in_seg
    if sorted_v is not None:
        validity = validity & sorted_v[src_c]
    if default is not None:
        data = jnp.where(in_seg, data, jnp.full_like(data, default))
        validity = validity | ~in_seg
    return _unsort(ctx, data), _unsort(ctx, validity)


def nth(ctx: WindowContext, value: Column, n_th: int, peer_end=None):
    """nth_value: the value at the n-th row of the frame (frame start =
    partition start; the default RANGE frame ends at the current row's
    LAST PEER, so pass peer_end from peer_group_end)."""
    n = ctx.pos.shape[0]
    sorted_d = value.data[ctx.perm]
    sorted_v = value.validity[ctx.perm] if value.validity is not None else None
    src = ctx.seg_start + (n_th - 1)
    src_c = jnp.clip(src, 0, n - 1)
    data = sorted_d[src_c]
    end = peer_end if peer_end is not None \
        else ctx.seg_start + ctx.pos
    validity = (end - ctx.seg_start >= (n_th - 1)) & \
        (src < ctx.seg_start + ctx.seg_len)
    if sorted_v is not None:
        validity = validity & sorted_v[src_c]
    return _unsort(ctx, data), _unsort(ctx, validity)


def framed_agg(ctx: WindowContext, value: Optional[Column], fn: str,
               lower: Optional[int], upper: Optional[int],
               peer_end=None):
    """Aggregate over a frame [lower, upper] relative to the current row
    (None = unbounded). ROWS semantics by default; passing ``peer_end``
    (from peer_group_end) gives RANGE semantics for the
    unbounded-preceding..current-row frame — the frame extends to the last
    peer. Prefix-scan differences for sum/count/avg; segmented doubling
    scans for unbounded-start min/max.
    """
    n = ctx.pos.shape[0]
    if value is not None:
        sorted_d = value.data[ctx.perm]
        sorted_v = value.validity[ctx.perm] if value.validity is not None \
            else None
        valid = ctx.alive if sorted_v is None else (ctx.alive & sorted_v)
    else:
        sorted_d = jnp.ones(n, dtype=jnp.int64)
        sorted_v = None
        valid = ctx.alive

    idx = jnp.arange(n, dtype=jnp.int32)
    seg_end = ctx.seg_start + ctx.seg_len - 1
    lo = ctx.seg_start if lower is None else jnp.maximum(idx + lower, ctx.seg_start)
    if peer_end is not None and upper == 0:
        hi = jnp.minimum(peer_end, seg_end)
    else:
        hi = seg_end if upper is None else jnp.minimum(idx + upper, seg_end)
    empty = hi < lo

    if fn in ("sum", "count", "avg"):
        vals = jnp.where(valid, sorted_d, 0).astype(
            jnp.float64 if jnp.issubdtype(sorted_d.dtype, jnp.floating)
            else jnp.int64)
        csum = jnp.cumsum(vals)
        ccnt = jnp.cumsum(valid.astype(jnp.int64))

        def range_sum(c):
            hi_c = jnp.clip(hi, 0, n - 1)
            lo_c = jnp.clip(lo, 0, n - 1)
            return c[hi_c] - jnp.where(lo_c > 0, c[lo_c - 1], 0)

        s = range_sum(csum)
        cnt = range_sum(ccnt)
        if fn == "count":
            return _unsort(ctx, jnp.where(empty, 0, cnt)), None
        valid_out = (cnt > 0) & ~empty
        if fn == "avg":
            out = s.astype(jnp.float64) / jnp.maximum(cnt, 1)
            return _unsort(ctx, out), _unsort(ctx, valid_out)
        return _unsort(ctx, s), _unsort(ctx, valid_out)

    if fn in ("min", "max"):
        is_min = fn == "min"
        if jnp.issubdtype(sorted_d.dtype, jnp.floating):
            fill = jnp.inf if is_min else -jnp.inf
        else:
            info = jnp.iinfo(sorted_d.dtype)
            fill = info.max if is_min else info.min
        masked = jnp.where(valid, sorted_d, fill)
        if lower is None and (upper is None or upper == 0):
            # running extreme from segment start: segmented cummin/cummax
            run = _segmented_scan(masked, ctx.seg_start, is_min)
            # value at the frame end (segment end / peer end / current row)
            out = run[jnp.clip(hi, 0, n - 1)]
            cnt = _segment_count(valid, ctx, lo, hi, n)
            return _unsort(ctx, out), _unsort(ctx, (cnt > 0) & ~empty)
        # bounded frames: sparse-table range extremes — log2(n) doubling
        # levels of pairwise extremes, then a two-gather query per row
        # (static shapes, pure gathers/elementwise: TPU-friendly)
        out = _range_extreme(masked, lo, hi, n, is_min, fill)
        cnt = _segment_count(valid, ctx, lo, hi, n)
        return _unsort(ctx, out), _unsort(ctx, (cnt > 0) & ~empty)

    if fn in ("first", "last"):
        pos_idx = lo if fn == "first" else hi
        pos_c = jnp.clip(pos_idx, 0, n - 1)
        data = sorted_d[pos_c]
        v = ~empty
        if value is not None and sorted_v is not None:
            v = v & sorted_v[pos_c]
        return _unsort(ctx, data), _unsort(ctx, v)

    raise NotImplementedError(f"window aggregate {fn!r}")


def _range_extreme(vals, lo, hi, n: int, is_min: bool, fill):
    """Per-row extreme of vals[lo[i]..hi[i]] via a sparse table.

    st[k, i] = extreme(vals[i : i + 2^k]); a query of length m uses the
    two overlapping power-of-two blocks at lo and hi - 2^k + 1."""
    ex = jnp.minimum if is_min else jnp.maximum
    levels = max(1, int(math.ceil(math.log2(max(n, 2)))) + 1)
    tables = [vals]
    for k in range(1, levels):
        half = 1 << (k - 1)
        prev = tables[-1]
        shifted = jnp.concatenate(
            [prev[half:], jnp.full((half,), fill, dtype=prev.dtype)])
        tables.append(ex(prev, shifted))
    st = jnp.stack(tables)  # [levels, n]
    lo_c = jnp.clip(lo, 0, n - 1)
    hi_c = jnp.clip(hi, 0, n - 1)
    length = jnp.maximum(hi_c - lo_c + 1, 1)
    # floor(log2(length)) in integer arithmetic (length <= n < 2^31)
    k = (jnp.ceil(jnp.log2(length.astype(jnp.float64) + 0.5)) - 1) \
        .astype(jnp.int32)
    k = jnp.clip(k, 0, levels - 1)
    block = (jnp.int32(1) << k)
    a = st[k, lo_c]
    b = st[k, jnp.clip(hi_c - block + 1, 0, n - 1)]
    return ex(a, b)


def _segment_count(valid, ctx, lo, hi, n):
    ccnt = jnp.cumsum(valid.astype(jnp.int64))
    hi_c = jnp.clip(hi, 0, n - 1)
    lo_c = jnp.clip(lo, 0, n - 1)
    return ccnt[hi_c] - jnp.where(lo_c > 0, ccnt[lo_c - 1], 0)


def _segmented_scan(vals, seg_start, is_min: bool):
    """Segmented running min/max: out[i] = extreme(vals[seg_start[i]..i]).
    Hillis–Steele doubling scan (log2(n) vector steps) with segment-boundary
    masking — maps to pure VPU element-wise ops on TPU."""
    n = vals.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    out = vals
    step = 1
    while step < n:
        prev = jnp.where(idx - step >= seg_start, jnp.roll(out, step), out)
        out = jnp.minimum(out, prev) if is_min else jnp.maximum(out, prev)
        step *= 2
    return out
