"""Runtime join-filter kernels (blocked bloom + min/max key bounds).

Sideways information passing for equi-joins: after the build side of a
join materializes, a compact filter derived from its keys prunes the
probe side *upstream* — in probe-side scans (min/max and exact
membership conjuncts for parquet row-group skipping and host-side Arrow
filtering), in spill-join partition pairs, and as a device mask on the
probe selection before ``probe_ranges``/``join_expand``.

Key derivation is shared with the join kernels (``ops/join._join_keys``):
multi-column keys pack losslessly into one uint64 when they fit
(exact — the only false positives are bloom collisions), otherwise the
same seed-0 ``hash64`` both sides use. Equal keys on the two sides
therefore always produce equal filter keys, so the filter NEVER yields a
false negative; Spark key semantics (-0.0 ≡ 0.0, NaN ≡ NaN) ride the
shared ``_to_bits`` normalization.

Reference role: DataFusion's dynamic filter pushdown / Spark's runtime
bloom filter join rewrite, reshaped for XLA: the filter is a flat bool
bit array built with three drop-mode scatters and probed with three
gathers — static shapes, no host sync during build or apply.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax.numpy as jnp

from .join import _join_keys

_KEY_MAX = jnp.uint64(0xFFFFFFFFFFFFFFFF)


def _mix(x: jnp.ndarray) -> jnp.ndarray:
    """splitmix64 finalizer: packed keys are raw values (low entropy in
    the low bits), so bit positions must come from a full-width mix."""
    x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    return x ^ (x >> jnp.uint64(31))


def _positions(keys: jnp.ndarray, num_bits: int):
    """Three bit positions per key from independent slices of the mix."""
    m = _mix(keys)
    b = jnp.uint64(num_bits)
    p1 = (m % b).astype(jnp.int32)
    p2 = ((m >> jnp.uint64(17)) % b).astype(jnp.int32)
    p3 = ((m >> jnp.uint64(34)) % b).astype(jnp.int32)
    return p1, p2, p3


class BuildResult(NamedTuple):
    bits: jnp.ndarray    # bool[num_bits] membership bit array
    kmin: jnp.ndarray    # uint64 scalar: min packed/hashed key (usable rows)
    kmax: jnp.ndarray    # uint64 scalar: max packed/hashed key
    n_build: jnp.ndarray  # int32 scalar: usable build rows
    ndv: jnp.ndarray     # int32 scalar: distinct keys among usable rows
    exact: bool          # keys are lossless packs (no hash aliasing)


def build(key_cols: Sequence, sel, num_bits: int, seed: int = 0
          ) -> BuildResult:
    """Build the filter from build-side key columns.

    Dead/null-key rows are excluded: an equi-join key with any NULL part
    never matches, so the filter may reject such probe rows outright.
    """
    keys, usable, exact = _join_keys(key_cols, sel, seed=seed)
    n = keys.shape[0]
    p1, p2, p3 = _positions(keys, num_bits)
    # drop-mode scatter: dead rows aim one past the end
    oob = jnp.int32(num_bits)
    p1 = jnp.where(usable, p1, oob)
    p2 = jnp.where(usable, p2, oob)
    p3 = jnp.where(usable, p3, oob)
    bits = jnp.zeros(num_bits, dtype=jnp.bool_)
    on = jnp.ones(n, dtype=jnp.bool_)
    bits = bits.at[p1].max(on, mode="drop")
    bits = bits.at[p2].max(on, mode="drop")
    bits = bits.at[p3].max(on, mode="drop")
    kmin = jnp.min(jnp.where(usable, keys, _KEY_MAX))
    kmax = jnp.max(jnp.where(usable, keys, jnp.uint64(0)))
    n_build = jnp.sum(usable.astype(jnp.int32))
    # distinct count over the usable prefix of the sorted keys
    skeys = jnp.sort(jnp.where(usable, keys, _KEY_MAX))
    pos = jnp.arange(n, dtype=jnp.int32)
    first = (pos == 0) | (skeys != jnp.concatenate(
        [skeys[:1], skeys[:-1]]))
    ndv = jnp.sum((first & (pos < n_build)).astype(jnp.int32))
    return BuildResult(bits, kmin, kmax, n_build, ndv, exact)


def apply(bits: jnp.ndarray, kmin, kmax, key_cols: Sequence, sel,
          seed: int = 0) -> jnp.ndarray:
    """Probe-side selection mask: keep rows whose key may be in the build
    set. Rows with NULL key parts are rejected (they cannot equi-match).
    Sound for inner/semi probe sides only — never apply to a side whose
    unmatched rows survive (left/anti probes, outer builds)."""
    keys, usable, _ = _join_keys(key_cols, sel, seed=seed)
    num_bits = bits.shape[0]
    p1, p2, p3 = _positions(keys, num_bits)
    member = bits[p1] & bits[p2] & bits[p3]
    in_range = (keys >= kmin) & (keys <= kmax)
    return sel & usable & member & in_range


def column_bounds(data: jnp.ndarray, usable: jnp.ndarray):
    """(min, max) of one key column over usable rows, in the column's
    physical dtype. With zero usable rows min > max (callers detect the
    empty build via n_build and may prune the whole probe side)."""
    if jnp.issubdtype(data.dtype, jnp.floating):
        lo, hi = jnp.array(-jnp.inf, data.dtype), jnp.array(jnp.inf,
                                                            data.dtype)
    elif data.dtype == jnp.bool_:
        lo, hi = jnp.array(False), jnp.array(True)
    else:
        info = jnp.iinfo(data.dtype)
        lo, hi = jnp.array(info.min, data.dtype), jnp.array(info.max,
                                                            data.dtype)
    cmin = jnp.min(jnp.where(usable, data, hi))
    cmax = jnp.max(jnp.where(usable, data, lo))
    return cmin, cmax
