"""Equi-join kernels (sort + binary-search probe).

TPU-first replacement for DataFusion's HashJoinExec (SURVEY.md §2.4): the
build side is sorted by key; probes binary-search the sorted keys
(``jnp.searchsorted`` lowers to a vectorized search — no serialized
scatter-probe hash table). Dynamic output size is handled in two phases:

  1. ``join_match``: static-shape match ranges per probe row, plus the total
     output row count as a device scalar — the *only* host sync point.
  2. ``join_expand``: given a static output capacity chosen by the host
     (bucketed, so shapes cache), materialize the joined batch.

A unique-build fast path (``join_unique``) skips the sync entirely: with at
most one build match per probe row, output capacity equals probe capacity.
Null join keys never match (SQL equi-join semantics).

Multi-column keys pack losslessly into uint64 when they fit; otherwise a
64-bit hash is used for the sort order and candidate ranges are verified
against the true key columns.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar.batch import Column, DeviceBatch
from ..spec import data_type as dt
from .hash import can_pack, hash64, pack_keys


def _join_keys(cols: Sequence[Column], sel, seed: int = 0) -> Tuple[jnp.ndarray, jnp.ndarray, bool]:
    """(key_bits, usable_mask, exact). usable = alive and no null key part.
    Dead/null rows keep their key but are excluded via the mask."""
    types = [c.dtype for c in cols]
    usable = sel
    datas = []
    for c in cols:
        if c.validity is not None:
            usable = usable & c.validity
        datas.append(c.data)
    if can_pack(types, reserve_bits=0):
        return pack_keys(datas, types), usable, True
    return hash64(datas, types, seed=seed), usable, False


def _values_eq(a, b):
    """Key-value equality with Spark semantics (NaN == NaN; -0.0 == 0.0)."""
    eq = a == b
    if jnp.issubdtype(a.dtype, jnp.floating):
        eq = eq | (jnp.isnan(a) & jnp.isnan(b))
    return eq


def _verify_eq(build_cols, probe_cols, bidx, valid):
    """Exact key equality check for the hashed path."""
    ok = valid
    for bc, pc in zip(build_cols, probe_cols):
        ok = ok & _values_eq(bc.data[bidx], pc.data)
    return ok


class BuildTable(NamedTuple):
    """Sorted build side, shareable across probes (broadcast join reuse)."""

    perm: jnp.ndarray         # int32[bn]: usable rows first, in key order
    sorted_keys: jnp.ndarray  # uint64[bn]; positions >= num_valid hold KEY_MAX
    exact: bool
    num_valid: jnp.ndarray    # dynamic count of usable build rows
    seed: int = 0             # hash seed (hashed path; bumped on ambiguity)


_KEY_MAX = jnp.uint64(0xFFFFFFFFFFFFFFFF)


def build_side(build_key_cols: Sequence[Column], build_sel, seed: int = 0) -> BuildTable:
    keys, usable, exact = _join_keys(build_key_cols, build_sel, seed=seed)
    # Sort usable rows to a prefix in key order (two stable passes), then
    # overwrite the suffix with KEY_MAX so the array stays globally sorted.
    # A *real* key equal to KEY_MAX lives in the prefix; probe ranges clip
    # against num_valid, so the sentinel suffix can never produce a match.
    perm = jnp.argsort(keys, stable=True).astype(jnp.int32)
    perm = perm[jnp.argsort((~usable[perm]).astype(jnp.uint8), stable=True)]
    num_valid = jnp.sum(usable.astype(jnp.int32))
    pos = jnp.arange(keys.shape[0], dtype=jnp.int32)
    sorted_keys = jnp.where(pos < num_valid, keys[perm], _KEY_MAX)
    return BuildTable(perm, sorted_keys, exact, num_valid, seed)


def hash_ambiguous(bt: BuildTable, build_key_cols: Sequence[Column]) -> jnp.ndarray:
    """Device scalar: two adjacent usable build rows share a 64-bit hash but
    differ in true key — probing by hash ranges would be wrong. The executor
    re-builds with seed+1 until unambiguous (astronomically rare to recur).
    Only meaningful when ``bt.exact`` is False."""
    n = bt.sorted_keys.shape[0]
    pos = jnp.arange(n - 1, dtype=jnp.int32)
    both_valid = (pos + 1) < bt.num_valid
    same_hash = (bt.sorted_keys[1:] == bt.sorted_keys[:-1]) & both_valid
    diff_key = jnp.zeros(n - 1, dtype=jnp.bool_)
    a, b = bt.perm[:-1], bt.perm[1:]
    for c in build_key_cols:
        neq = ~_values_eq(c.data[a], c.data[b])
        if c.validity is not None:
            neq = neq | (c.validity[a] != c.validity[b])
        diff_key = diff_key | neq
    return jnp.any(same_hash & diff_key)


class MatchRanges(NamedTuple):
    lo: jnp.ndarray      # int32[pn] first matching sorted-build position
    cnt: jnp.ndarray     # int32[pn] number of matches (0 if none)
    usable: jnp.ndarray  # bool[pn] probe row alive with non-null key


def probe_ranges(bt: BuildTable, probe_key_cols: Sequence[Column], probe_sel,
                 build_key_cols: Optional[Sequence[Column]] = None) -> MatchRanges:
    pkeys, pusable, _ = _join_keys(probe_key_cols, probe_sel, seed=bt.seed)
    lo = jnp.searchsorted(bt.sorted_keys, pkeys, side="left").astype(jnp.int32)
    hi = jnp.searchsorted(bt.sorted_keys, pkeys, side="right").astype(jnp.int32)
    hi = jnp.minimum(hi, bt.num_valid)  # clip off the KEY_MAX sentinel suffix
    cnt = jnp.where(pusable, jnp.maximum(hi - lo, 0), 0).astype(jnp.int32)
    if not bt.exact:
        # Hashed path: given an ambiguity-free build (see hash_ambiguous),
        # each hash range holds exactly one distinct true key, so verifying
        # the first candidate decides the whole range exactly.
        assert build_key_cols is not None
        cap = bt.sorted_keys.shape[0]
        cand = bt.perm[jnp.clip(lo, 0, cap - 1)]
        ok = _verify_eq(build_key_cols, probe_key_cols, cand, cnt > 0)
        cnt = jnp.where(ok, cnt, 0)
    return MatchRanges(lo, cnt, pusable)


def join_unique(bt: BuildTable, ranges: MatchRanges, probe: DeviceBatch,
                build_payload: DeviceBatch, join_type: str,
                build_names: Sequence[str]) -> DeviceBatch:
    """Join assuming ≤1 build match per probe row (PK-FK). Output capacity =
    probe capacity. join_type ∈ {inner, left, semi, anti}."""
    cap = bt.sorted_keys.shape[0]
    matched = ranges.cnt > 0
    bidx = bt.perm[jnp.clip(ranges.lo, 0, cap - 1)]
    if join_type == "semi":
        return probe.with_sel(probe.sel & matched)
    if join_type == "anti":
        return probe.with_sel(probe.sel & ~matched)
    cols = dict(probe.columns)
    for name in build_names:
        c = build_payload.columns[name]
        data = c.data[bidx]
        validity = matched if c.validity is None else matched & c.validity[bidx]
        cols[name] = Column(data, validity, c.dtype)
    if join_type == "inner":
        sel = probe.sel & matched
    elif join_type == "left":
        sel = probe.sel
    else:
        raise ValueError(join_type)
    return DeviceBatch(cols, sel)


def join_output_count(ranges: MatchRanges, probe_sel, join_type: str) -> jnp.ndarray:
    """Total output rows for the expanding join (device scalar)."""
    cnt = ranges.cnt
    if join_type in ("left", "full"):
        cnt = jnp.where(probe_sel, jnp.maximum(cnt, 1), 0)
    else:
        cnt = jnp.where(probe_sel, cnt, 0)
    return jnp.sum(cnt.astype(jnp.int64))


class ExpandResult(NamedTuple):
    batch: DeviceBatch
    probe_index: jnp.ndarray  # int32[out_capacity] originating probe row
    is_match: jnp.ndarray     # bool[out_capacity] row is a key match
    build_index: jnp.ndarray  # int32[out_capacity] originating build row


def join_expand(bt: BuildTable, ranges: MatchRanges, probe: DeviceBatch,
                build_payload: DeviceBatch, join_type: str,
                build_names: Sequence[str], out_capacity: int) -> ExpandResult:
    """Materialize a many-to-many join into a batch of static capacity.

    join_type ∈ {inner, left}. (right/full are planned as swapped/left+anti
    unions by the physical layer.)
    """
    bn = bt.sorted_keys.shape[0]
    cnt = ranges.cnt
    if join_type == "left":
        eff = jnp.where(probe.sel, jnp.maximum(cnt, 1), 0)
    else:
        eff = jnp.where(probe.sel, cnt, 0)
    offsets = jnp.cumsum(eff) - eff  # exclusive prefix sum
    total = jnp.sum(eff)
    j = jnp.arange(out_capacity, dtype=jnp.int32)
    # probe row for output j: last i with offsets[i] <= j (among eff>0 rows)
    pi = jnp.searchsorted(offsets + eff, j, side="right").astype(jnp.int32)
    pi = jnp.clip(pi, 0, probe.capacity - 1)
    k = j - offsets[pi]
    is_match = k < cnt[pi]
    bpos = jnp.clip(ranges.lo[pi] + jnp.where(is_match, k, 0), 0, bn - 1)
    bidx = bt.perm[bpos]
    out_sel = j < total
    cols = {}
    for name, c in probe.columns.items():
        data = c.data[pi]
        validity = None if c.validity is None else c.validity[pi]
        cols[name] = Column(data, validity, c.dtype)
    for name in build_names:
        c = build_payload.columns[name]
        data = c.data[bidx]
        validity = is_match if c.validity is None else is_match & c.validity[bidx]
        cols[name] = Column(data, validity, c.dtype)
    return ExpandResult(DeviceBatch(cols, out_sel), pi, is_match, bidx)


def build_matched_mask(bt: BuildTable, ranges: MatchRanges, probe_sel) -> jnp.ndarray:
    """bool[build_capacity]: build rows matched by ≥1 probe row (for right/
    full outer). Computed as a range-increment difference array over sorted
    build positions, then mapped back through the sort permutation."""
    bn = bt.sorted_keys.shape[0]
    active = (ranges.cnt > 0) & probe_sel
    lo = jnp.where(active, ranges.lo, 0)
    hi = jnp.where(active, ranges.lo + ranges.cnt, 0)
    diff = jnp.zeros(bn + 1, dtype=jnp.int32)
    diff = diff.at[lo].add(active.astype(jnp.int32))
    diff = diff.at[hi].add(-active.astype(jnp.int32))
    covered_sorted = jnp.cumsum(diff[:bn]) > 0
    matched = jnp.zeros(bn, dtype=jnp.bool_).at[bt.perm].set(covered_sorted)
    return matched


def has_duplicate_build_keys(bt: BuildTable) -> jnp.ndarray:
    """Device scalar: any two usable build rows share a key (→ the unique
    fast path is invalid and the planner must expand)."""
    k = bt.sorted_keys
    pos = jnp.arange(k.shape[0] - 1, dtype=jnp.int32)
    dup = (k[1:] == k[:-1]) & ((pos + 1) < bt.num_valid)
    return jnp.any(dup)
