"""Grouped aggregation kernels (sort + segmented reduction).

TPU-first design for GROUP BY: instead of a scatter-probe hash table (the
DataFusion approach — SURVEY.md §2.4; serializes on TPU), rows are sorted
by their group key and reduced with ``jax.ops.segment_*`` primitives, which
XLA lowers to parallel scans. The number of output group slots is a static
capacity; the live group count is dynamic and exported via the output
selection mask.

NULL semantics follow Spark: null group keys form their own group; null
values are skipped by aggregates; COUNT(*) counts rows, COUNT(x) counts
non-null x; SUM over an all-null group is NULL; MIN/MAX ignore nulls.

Planner-level rewrites decompose compound aggregates before reaching this
kernel: AVG → SUM/COUNT, VAR/STD → SUM/SUM2/COUNT, COUNT(DISTINCT) →
two-level group-by.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar.batch import Column, DeviceBatch
from ..spec import data_type as dt
from .hash import can_pack, pack_keys
from .sort import order_bits


def _group_sort_perm(key_cols: Sequence[Column], sel) -> jnp.ndarray:
    """Sort permutation grouping equal keys together, dead rows last."""
    n = sel.shape[0]
    types = [c.dtype for c in key_cols]
    if can_pack(types, reserve_bits=len(key_cols) + 1):
        # Fast path: one argsort over a packed key with null flags folded in.
        datas = []
        for c in key_cols:
            datas.append(jnp.where(c.validity, c.data, jnp.zeros_like(c.data))
                         if c.validity is not None else c.data)
        packed = pack_keys(datas, types)
        shift = 64 - (len(key_cols) + 1)
        packed = packed & jnp.uint64((1 << shift) - 1)
        for i, c in enumerate(key_cols):
            if c.validity is not None:
                packed = packed | (jnp.where(c.validity, jnp.uint64(0), jnp.uint64(1))
                                   << jnp.uint64(shift + i))
        packed = jnp.where(sel, packed, jnp.uint64(0xFFFFFFFFFFFFFFFF))
        return jnp.argsort(packed, stable=True).astype(jnp.int32)
    perm = jnp.arange(n, dtype=jnp.int32)
    for c in reversed(list(key_cols)):
        bits = order_bits(c.data, c.dtype)
        perm = perm[jnp.argsort(bits[perm], stable=True)]
        if c.validity is not None:
            perm = perm[jnp.argsort(c.validity[perm].astype(jnp.uint8), stable=True)]
    dead = (~sel).astype(jnp.uint8)
    return perm[jnp.argsort(dead[perm], stable=True)].astype(jnp.int32)


def _keys_equal_adjacent(sorted_keys: Sequence[Column]) -> jnp.ndarray:
    """eq[i] = row i has the same group key as row i-1 (eq[0] = False)."""
    n = sorted_keys[0].data.shape[0]
    eq = jnp.ones(n, dtype=jnp.bool_)
    for c in sorted_keys:
        prev = jnp.roll(c.data, 1)
        same_val = c.data == prev
        if jnp.issubdtype(c.data.dtype, jnp.floating):
            # Spark groups all NaNs together (and -0.0 with 0.0; == covers it)
            same_val = same_val | (jnp.isnan(c.data) & jnp.isnan(prev))
        if c.validity is not None:
            prev_v = jnp.roll(c.validity, 1)
            same = (same_val & c.validity & prev_v) | (~c.validity & ~prev_v)
        else:
            same = same_val
        eq = eq & same
    return eq.at[0].set(False)


class GroupContext:
    """Per-row segment ids + masks, shared by all aggregate columns.

    Two construction modes:
    - sort-based (group_rows): rows sorted by key, dense segment ids,
      groups front-compacted;
    - direct-binned (group_rows_direct): segment id = packed dictionary
      code, no sort — bins may be sparse, ``group_mask`` marks live ones,
      and ``perm`` is None (identity): large gathers are pathologically
      slow on TPU, so the direct path must touch values in place.
    """

    def __init__(self, perm, seg_ids, alive_sorted, num_groups, max_groups,
                 group_mask=None):
        self.perm = perm  # int32[n] sort permutation, or None = identity
        self.seg_ids = seg_ids            # int32[n], dead rows → max_groups
        self.alive_sorted = alive_sorted  # bool[n]
        self.num_groups = num_groups      # dynamic scalar
        self.max_groups = max_groups      # static
        self.group_mask = group_mask      # bool[max_groups] (direct mode)


def group_rows(key_cols: Sequence[Column], sel, max_groups: int) -> Tuple[GroupContext, List[Column]]:
    """Group rows by key; returns (context, key columns in ORIGINAL order).

    The sort is used only to derive dense segment ids (adjacent-equal
    detection needs key order); the ids are then scattered back to the
    original row order so every aggregate reduces values IN PLACE. This
    trades the former per-column permutation gathers — pathologically slow
    on TPU — for one int32 scatter, and keeps within-group row order equal
    to input order (first/last semantics)."""
    if not key_cols:
        n = sel.shape[0]
        seg = jnp.where(sel, 0, max_groups).astype(jnp.int32)
        return GroupContext(None, seg, sel, jnp.int32(1), max_groups), []
    perm = _group_sort_perm(key_cols, sel)
    sorted_keys = [Column(c.data[perm],
                          None if c.validity is None else c.validity[perm],
                          c.dtype) for c in key_cols]
    alive = sel[perm]
    eq = _keys_equal_adjacent(sorted_keys)
    new_group = alive & ~eq
    seg_sorted = jnp.cumsum(new_group.astype(jnp.int32)) - 1
    seg_sorted = jnp.where(alive, jnp.clip(seg_sorted, 0, max_groups),
                           max_groups).astype(jnp.int32)
    n = sel.shape[0]
    seg = jnp.zeros(n, dtype=jnp.int32).at[perm].set(seg_sorted)
    num_groups = jnp.sum(new_group.astype(jnp.int32))
    return GroupContext(None, seg, sel, num_groups, max_groups), \
        list(key_cols)


def group_key_output(ctx: GroupContext, sorted_keys: Sequence[Column]) -> List[Column]:
    """Representative key values per group (first row of each segment)."""
    n = ctx.seg_ids.shape[0]
    first_idx = _seg_reduce(jnp.arange(n, dtype=jnp.int32), ctx.seg_ids,
                            ctx.max_groups + 1, "min", n)[: ctx.max_groups]
    first_idx = jnp.clip(first_idx, 0, n - 1)
    out = []
    for c in sorted_keys:
        data = c.data[first_idx]
        validity = None if c.validity is None else c.validity[first_idx]
        out.append(Column(data, validity, c.dtype))
    return out


def group_rows_direct(key_cols: Sequence[Column], domains: Sequence[int],
                      sel) -> Tuple[GroupContext, List[Column]]:
    """Sort-free grouping for low-cardinality keys with known domains
    (dictionary codes, booleans): segment id = packed code. The dominant
    TPC-H aggregations (Q1's returnflag×linestatus, Q12's shipmode, …) hit
    this path, turning an O(n log n) sort into O(n) segment reductions.

    Each key gets domain_i + 1 slots (the extra one encodes NULL).
    """
    n = sel.shape[0]
    gid = jnp.zeros(n, dtype=jnp.int32)
    g_total = 1
    for c, dom in zip(key_cols, domains):
        slots = dom + 1
        code = jnp.clip(c.data.astype(jnp.int32), 0, dom - 1)
        if c.validity is not None:
            code = jnp.where(c.validity, code, dom)
        gid = gid * slots + code
        g_total *= slots
    seg = jnp.where(sel, gid, g_total).astype(jnp.int32)
    counts = _seg_sum(sel.astype(jnp.int32), seg, g_total + 1)[:g_total]
    mask = counts > 0
    ctx = GroupContext(None, seg, sel, jnp.int32(g_total), g_total, mask)
    return ctx, list(key_cols)


def group_sel(ctx: GroupContext) -> jnp.ndarray:
    if ctx.group_mask is not None:
        return ctx.group_mask
    return jnp.arange(ctx.max_groups, dtype=jnp.int32) < ctx.num_groups


def group_overflow(ctx: GroupContext) -> jnp.ndarray:
    """Device scalar: the input had more distinct groups than max_groups and
    the output is truncated. The executor must host-check this whenever it
    chose max_groups smaller than the input capacity, and re-run with a
    larger capacity."""
    return ctx.num_groups > ctx.max_groups



# TPU scatter pitfall: XLA lowers scatter-based segment reductions with
# unpredictable indices to a serialized per-row loop (~600 ms per 8M-row
# scatter-add measured on v5e). For bounded segment counts a masked
# broadcast-reduction runs as G vectorized passes that XLA fuses (the
# [G, n] compare/select fuses into the row reduction — nothing
# materializes), ~100x faster. Above the threshold the compute cost of
# G*n element ops exceeds the scatter cost and we fall back. On CPU the
# scatter lowering is already fast, and the masked form is a slowdown —
# so the masked path is TPU(-like)-only.
_MASKED_SEGMENTS_MAX = 128
_MASKED_BACKENDS = ("tpu",)


def _masked_max_segments() -> int:
    try:
        backend = jax.default_backend()
    except Exception:  # pragma: no cover — backend init failure
        backend = "cpu"
    return _MASKED_SEGMENTS_MAX if backend in _MASKED_BACKENDS else 0


def _seg_reduce(vals, seg_ids, num_segments: int, kind: str, identity):
    if num_segments <= _masked_max_segments():
        gids = jnp.arange(num_segments, dtype=seg_ids.dtype)[:, None]
        hit = seg_ids[None, :] == gids
        body = jnp.where(hit, vals[None, :],
                         jnp.asarray(identity, dtype=vals.dtype))
        if kind == "sum":
            return jnp.sum(body, axis=1)
        if kind == "min":
            return jnp.min(body, axis=1)
        return jnp.max(body, axis=1)
    fn = {"sum": jax.ops.segment_sum, "min": jax.ops.segment_min,
          "max": jax.ops.segment_max}[kind]
    return fn(vals, seg_ids, num_segments=num_segments)


def _seg_sum(vals, seg_ids, num_segments: int):
    return _seg_reduce(vals, seg_ids, num_segments, "sum", 0)


def _masked(vals, mask, fill):
    return jnp.where(mask, vals, jnp.full_like(vals, fill))


def _perm(ctx: GroupContext, arr):
    """Row permutation, skipped entirely for the identity (direct) mode —
    an explicit arange gather would lower to a full random gather on TPU."""
    return arr if ctx.perm is None else arr[ctx.perm]


def agg_count(ctx: GroupContext, value: Optional[Column]) -> Column:
    """COUNT(*) when value is None, else COUNT(value)."""
    mask = ctx.alive_sorted
    if value is not None and value.validity is not None:
        mask = mask & _perm(ctx, value.validity)
    ones = mask.astype(jnp.int64)
    out = _seg_sum(ones, ctx.seg_ids, ctx.max_groups + 1)
    return Column(out[: ctx.max_groups], None, dt.LongType())


def agg_sum(ctx: GroupContext, value: Column, out_type: dt.DataType) -> Column:
    vals = _perm(ctx, value.data)
    mask = ctx.alive_sorted
    if value.validity is not None:
        mask = mask & _perm(ctx, value.validity)
    odt = jnp.dtype(out_type.physical_dtype)
    vals = _masked(vals.astype(odt), mask, 0)
    out = _seg_sum(vals, ctx.seg_ids, ctx.max_groups + 1)
    cnt = _seg_sum(mask.astype(jnp.int32), ctx.seg_ids, ctx.max_groups + 1)
    return Column(out[: ctx.max_groups], cnt[: ctx.max_groups] > 0, out_type)


def _extreme_for(dtype_np, is_min: bool):
    if jnp.issubdtype(dtype_np, jnp.floating):
        return jnp.inf if is_min else -jnp.inf
    info = jnp.iinfo(dtype_np)
    return info.max if is_min else info.min


def agg_min_max(ctx: GroupContext, value: Column, is_min: bool) -> Column:
    vals = _perm(ctx, value.data)
    mask = ctx.alive_sorted
    if value.validity is not None:
        mask = mask & _perm(ctx, value.validity)
    if vals.dtype == jnp.bool_:
        vals = vals.astype(jnp.int8)
    fill = _extreme_for(vals.dtype, is_min)
    vals = _masked(vals, mask, fill)
    out = _seg_reduce(vals, ctx.seg_ids, ctx.max_groups + 1,
                      "min" if is_min else "max", fill)[: ctx.max_groups]
    cnt = _seg_sum(mask.astype(jnp.int32), ctx.seg_ids,
                   ctx.max_groups + 1)[: ctx.max_groups]
    if value.data.dtype == jnp.bool_:
        out = out.astype(jnp.bool_)
    return Column(out, cnt > 0, value.dtype)


def agg_first_last(ctx: GroupContext, value: Column, is_first: bool,
                   ignore_nulls: bool = True) -> Column:
    n = ctx.seg_ids.shape[0]
    mask = ctx.alive_sorted
    if ignore_nulls and value.validity is not None:
        mask = mask & _perm(ctx, value.validity)
    idx = jnp.arange(n, dtype=jnp.int32)
    sentinel = n if is_first else -1
    idx_m = _masked(idx, mask, sentinel)
    pos = _seg_reduce(idx_m, ctx.seg_ids, ctx.max_groups + 1,
                      "min" if is_first else "max",
                      sentinel)[: ctx.max_groups]
    has = (pos < n) if is_first else (pos >= 0)
    pos = jnp.clip(pos, 0, n - 1)
    vals = _perm(ctx, value.data)[pos]
    validity = has
    if value.validity is not None:
        validity = validity & _perm(ctx, value.validity)[pos]
    return Column(vals, validity, value.dtype)


def agg_bool(ctx: GroupContext, value: Column, is_any: bool) -> Column:
    vals = _perm(ctx, value.data).astype(jnp.int8)
    mask = ctx.alive_sorted
    if value.validity is not None:
        mask = mask & _perm(ctx, value.validity)
    fill = 0 if is_any else 1
    vals = _masked(vals, mask, fill)
    out = _seg_reduce(vals, ctx.seg_ids, ctx.max_groups + 1,
                      "max" if is_any else "min", fill)[: ctx.max_groups]
    cnt = _seg_sum(mask.astype(jnp.int32), ctx.seg_ids,
                   ctx.max_groups + 1)[: ctx.max_groups]
    return Column(out.astype(jnp.bool_), cnt > 0, dt.BooleanType())
