"""Key hashing / packing kernels.

Used for shuffle partitioning, hash joins, and group-by keys. On TPU the
VPU has no native 64-bit multiply-heavy hash, so the mixers below stick to
shifts/xors/adds plus 32-bit multiplies, which lower cleanly. When a set of
key columns fits losslessly in 64 bits they are *packed* instead of hashed,
making sort-based joins and aggregations exact (no collision handling).

Reference role: hash repartitioning in shuffle_write (InputMode::Shuffle /
OutputDistribution::Hash, crates/sail-execution/src/plan/shuffle_write.rs)
and DataFusion's hash join/aggregate — here re-designed as sort/pack
kernels, which map better to XLA than scatter-probe hash tables.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..spec import data_type as dt

# Bit width of each physical dtype when used as a join/group key.
_KEY_BITS = {
    "bool": 1,
    "int8": 8,
    "int16": 16,
    "int32": 32,
    "int64": 64,
    "float32": 32,
    "float64": 64,
}


def key_bits(d: dt.DataType) -> int:
    return _KEY_BITS[d.physical_dtype]


def can_pack(types: Sequence[dt.DataType], reserve_bits: int = 1) -> bool:
    """True if the key columns (plus ``reserve_bits`` for null/sel flags)
    fit losslessly in a single int64 sort key."""
    try:
        total = sum(key_bits(t) for t in types)
    except KeyError:
        return False
    return total + reserve_bits <= 64


def _normalize_float(data):
    """Spark key semantics: -0.0 keys equal 0.0, and all NaNs are one value."""
    data = data + jnp.zeros_like(data)  # -0.0 + 0.0 == +0.0
    return jnp.where(jnp.isnan(data), jnp.full_like(data, jnp.nan), data)


def _to_bits(data, d: dt.DataType):
    """Map a column to unsigned key bits preserving equality."""
    pd = d.physical_dtype
    if pd == "bool":
        return data.astype(jnp.uint64) & jnp.uint64(1)
    if pd in ("int8", "int16", "int32", "int64"):
        bits = _KEY_BITS[pd]
        u = data.astype(jnp.int64).astype(jnp.uint64)
        if bits < 64:
            u = u & jnp.uint64((1 << bits) - 1)
        return u
    if pd == "float32":
        return jax.lax.bitcast_convert_type(
            _normalize_float(data.astype(jnp.float32)), jnp.uint32).astype(jnp.uint64)
    if pd == "float64":
        return jax.lax.bitcast_convert_type(_normalize_float(data.astype(jnp.float64)), jnp.uint64)
    raise TypeError(pd)


def pack_keys(columns, types: Sequence[dt.DataType]) -> jnp.ndarray:
    """Pack key columns into one uint64. Null/dead rows are NOT encoded here;
    callers combine with validity separately. Requires can_pack(types)."""
    acc = jnp.zeros(columns[0].shape[0], dtype=jnp.uint64)
    for data, d in zip(columns, types):
        bits = key_bits(d)
        acc = (acc << jnp.uint64(bits)) | _to_bits(data, d)
    return acc


def hash64(columns, types: Sequence[dt.DataType], seed: int = 0) -> jnp.ndarray:
    """64-bit mixing hash over key columns (splitmix64-style finalizer)."""
    acc = jnp.full(columns[0].shape[0], jnp.uint64(0x9E3779B97F4A7C15 ^ seed), dtype=jnp.uint64)
    for data, d in zip(columns, types):
        x = _to_bits(data, d)
        x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
        x = x ^ (x >> jnp.uint64(31))
        acc = (acc ^ x) * jnp.uint64(0x9E3779B97F4A7C15)
        acc = acc ^ (acc >> jnp.uint64(29))
    return acc


def hash_partition_ids(columns, types: Sequence[dt.DataType], num_partitions: int) -> jnp.ndarray:
    """Partition id per row for hash shuffle (int32 in [0, num_partitions))."""
    h = hash64(columns, types)
    return (h % jnp.uint64(num_partitions)).astype(jnp.int32)
