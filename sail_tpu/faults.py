"""Deterministic fault injection for the cluster and IO paths.

Reference role: the chaos harnesses every distributed query engine grows
once retry machinery exists (Theseus' fault-tolerant data movement,
PAPERS.md) — none of the retry paths (heartbeat eviction, per-task
attempts, fetch-failed producer re-runs, backoff, speculation,
quarantine) can be trusted unless they can be exercised on demand,
deterministically, in tests.

Named sites are threaded through the runtime:

========================  ====================================  =========
site                      where it fires                        key
========================  ====================================  =========
``rpc.call``              every driver<->worker unary RPC       method
``worker.task_exec``      worker task execution, pre-plan       worker:sSpP
``shuffle.fetch``         peer/driver stream fetch              addr/sSpPcC
``worker.heartbeat``      worker heartbeat loop                 worker_id
``io.read``               ``io.formats.read_table`` entry       format
``io.cache``              persistent program-cache load/store   load:site:digest
``streaming.source``      streaming trigger, pre-read           source name
``streaming.sink``        epoch sink stage / commit             stage:eN, commit:eN
``streaming.checkpoint``  state / offsets checkpoint write      state:eN, offsets:eN
``streaming.marker``      continuous marker inject / align      inject:mN, sSpP:mN
``shuffle.credit``        continuous record-batch push          sSpP (dst)
========================  ====================================  =========

Rules are a semicolon-separated spec (``SAIL_FAULTS`` env var, the
``faults.spec`` app-config key, or :func:`configure` in tests)::

    SAIL_FAULTS="seed=42;shuffle.fetch=error@0.5#2;worker.task_exec:worker-1*=delay(0.8)"

Each rule is ``site[:key-glob]=kind[(arg)][@prob][#limit]`` where kind is

- ``error`` — raise :class:`FaultInjectedError` (``error(not_found)``
  marks it non-retryable, like a gRPC NOT_FOUND);
- ``delay(seconds)`` — sleep, turning the call site into a straggler;
- ``crash`` — raise :class:`WorkerCrash`; the worker loop treats it as
  process death (server + heartbeats stop, nothing is reported).
  ``crash(hard)`` calls ``os._exit`` — only for real process workers.

``@prob`` (default 1.0) draws from a per-site PRNG stream seeded by
``seed`` and the site name, so a fixed seed yields the same decision
sequence at every site regardless of cross-site interleaving. ``#limit``
caps the number of injections for the rule (deterministic even under
probability 1.0). Every injection increments
``faults.injected_count{site,kind}`` in the metrics registry.

When no spec is configured the module holds no state and
:func:`inject` is a single attribute load + ``is None`` test — the
disabled layer adds no measurable overhead to the hot paths.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import os
import random
import re
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple


class FaultInjectedError(RuntimeError):
    """An injected failure. ``code`` mirrors gRPC status semantics:
    ``unavailable`` (default) is transient/retryable, ``not_found``
    must not be retried (the resource is gone)."""

    def __init__(self, site: str, key: str = "", code: str = "unavailable"):
        super().__init__(f"injected fault at {site}"
                         + (f" [{key}]" if key else ""))
        self.site = site
        self.key = key
        self.code = code


class WorkerCrash(FaultInjectedError):
    """An injected process-level crash: the worker must die silently
    (no status report, no heartbeats), not fail the task."""


_RULE_RE = re.compile(
    r"^(?P<site>[a-z_.]+)(?::(?P<key>[^=]+))?="
    r"(?P<kind>error|delay|crash)(?:\((?P<arg>[^)]*)\))?"
    r"(?:@(?P<prob>[0-9.]+))?(?:#(?P<limit>[0-9]+))?$")


@dataclasses.dataclass
class Rule:
    site: str
    kind: str                      # error | delay | crash
    key_glob: str = "*"
    prob: float = 1.0
    limit: Optional[int] = None    # max injections; None = unbounded
    arg: str = ""                  # delay seconds / error code / "hard"
    injected: int = 0

    def matches(self, key: str) -> bool:
        return self.key_glob == "*" or fnmatch.fnmatchcase(key,
                                                           self.key_glob)


def parse_spec(spec: str) -> Tuple[int, List[Rule]]:
    """Parse a fault spec into (seed, rules). Raises ValueError on a
    malformed rule so typos fail loudly instead of silently not
    injecting."""
    seed = 0
    rules: List[Rule] = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        if part.startswith("seed="):
            seed = int(part[len("seed="):])
            continue
        m = _RULE_RE.match(part)
        if m is None:
            raise ValueError(f"malformed fault rule: {part!r}")
        rules.append(Rule(
            site=m.group("site"), kind=m.group("kind"),
            key_glob=(m.group("key") or "*").strip(),
            prob=float(m.group("prob") or 1.0),
            limit=int(m.group("limit")) if m.group("limit") else None,
            arg=(m.group("arg") or "").strip()))
    return seed, rules


class _Injector:
    """Active fault state: the parsed rules plus one deterministic PRNG
    stream per site (seeded from the global seed and the site name, so
    decision sequences are reproducible per site independent of the
    interleaving of other sites)."""

    def __init__(self, seed: int, rules: List[Rule]):
        self.seed = seed
        self.rules = rules
        self._streams: Dict[str, random.Random] = {}
        self._lock = threading.Lock()

    def _stream(self, site: str) -> random.Random:
        rng = self._streams.get(site)
        if rng is None:
            rng = random.Random(
                (self.seed << 32) ^ zlib.crc32(site.encode()))
            self._streams[site] = rng
        return rng

    def maybe_inject(self, site: str, key: str):
        for rule in self.rules:
            if rule.site != site or not rule.matches(key):
                continue
            with self._lock:
                if rule.limit is not None and rule.injected >= rule.limit:
                    continue
                if rule.prob < 1.0 and \
                        self._stream(site).random() >= rule.prob:
                    continue
                rule.injected += 1
            self._count(site, rule.kind)
            self._fire(rule, site, key)

    @staticmethod
    def _count(site: str, kind: str):
        try:
            from .metrics import record as _record_metric
            _record_metric("faults.injected_count", 1, site=site, kind=kind)
        except Exception:  # noqa: BLE001 — accounting never masks the fault
            pass

    @staticmethod
    def _fire(rule: Rule, site: str, key: str):
        if rule.kind == "delay":
            try:
                time.sleep(float(rule.arg or 0.1))
            except (TypeError, ValueError):
                time.sleep(0.1)
            return
        if rule.kind == "crash":
            if rule.arg == "hard":
                os._exit(17)
            raise WorkerCrash(site, key)
        raise FaultInjectedError(site, key,
                                 code=rule.arg or "unavailable")


# The module-level fast path: None when disabled. inject() is then one
# global load + identity test — no dict lookups, no env reads.
_STATE: Optional[_Injector] = None
_SOURCE: Optional[str] = None      # "explicit" (configure) | "env" (reload)


def is_active() -> bool:
    return _STATE is not None


def inject(site: str, key: str = "") -> None:
    """Fault hook: no-op unless a spec is configured. May raise
    FaultInjectedError / WorkerCrash or sleep (straggler)."""
    state = _STATE  # snapshot: a concurrent reset() must no-op, not raise
    if state is None:
        return
    state.maybe_inject(site, key)


def configure(spec: str = "", seed: Optional[int] = None,
              rules: Optional[List[Rule]] = None) -> None:
    """Programmatic setup (tests): either a spec string or Rule objects.
    An empty configuration disables injection entirely."""
    global _STATE, _SOURCE
    parsed_seed, parsed = parse_spec(spec) if spec else (0, [])
    if rules:
        parsed = parsed + list(rules)
    if seed is not None:
        parsed_seed = seed
    _STATE = _Injector(parsed_seed, parsed) if parsed else None
    _SOURCE = "explicit" if _STATE is not None else None


def reset() -> None:
    """Disable injection and drop all rule state."""
    global _STATE, _SOURCE
    _STATE = None
    _SOURCE = None


def reload() -> None:
    """(Re)load the spec from the environment / app config. Called at
    import, by cluster entry points, and by tests after setting
    SAIL_FAULTS. Precedence: SAIL_FAULTS env > faults.spec config. A
    configuration installed programmatically via :func:`configure` is
    kept when the environment carries no spec (so building a
    LocalCluster does not wipe a test's injected rules)."""
    global _STATE, _SOURCE
    spec = os.environ.get("SAIL_FAULTS", "")
    seed = None
    if not spec:
        try:
            from .config import get as config_get
            spec = str(config_get("faults.spec", "") or "")
            raw_seed = config_get("faults.seed", None)
            if raw_seed not in (None, ""):
                seed = int(raw_seed)
        except Exception:  # noqa: BLE001 — config layer optional here
            spec = ""
    if not spec:
        if _SOURCE == "env":
            _STATE = None
            _SOURCE = None
        return
    configure(spec, seed=seed)
    _SOURCE = "env" if _STATE is not None else None


def injection_counts() -> Dict[str, int]:
    """Per-site injection totals of the active configuration (tests)."""
    if _STATE is None:
        return {}
    out: Dict[str, int] = {}
    for rule in _STATE.rules:
        out[rule.site] = out.get(rule.site, 0) + rule.injected
    return out


reload()
