"""Spark/Java-style value formatting shared by CAST-to-string and the
host function layer (reference role: the display formatter in
crates/sail-common-datafusion/src/display.rs)."""

from __future__ import annotations

import math


def format_double(v: float) -> str:
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "Infinity" if v > 0 else "-Infinity"
    if v == int(v) and abs(v) < 1e16:
        return f"{int(v)}.0"
    r = repr(float(v))
    if "e" in r:
        m, _, e = r.partition("e")
        if "." not in m:
            m += ".0"
        return f"{m}E{int(e)}"
    return r
