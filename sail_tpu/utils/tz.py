"""Session timezone context (spark.sql.session.timeZone).

Spark interprets naive timestamp literals, string→timestamp casts, and
make_timestamp without an explicit zone in the SESSION timezone, and
renders timestamps in it. The engine stores timestamps as UTC
microseconds; this contextvar carries the session zone through literal
resolution, host datetime functions, and display."""

from __future__ import annotations

import contextvars
import datetime
import zoneinfo

_SESSION_TZ = contextvars.ContextVar("sail_session_tz", default="UTC")


def set_session_timezone(tz: str):
    return _SESSION_TZ.set(tz or "UTC")


def reset_session_timezone(token):
    _SESSION_TZ.reset(token)


def session_timezone_name() -> str:
    return _SESSION_TZ.get()


def session_zone():
    name = _SESSION_TZ.get()
    if name.upper() == "UTC":
        return datetime.timezone.utc
    return zoneinfo.ZoneInfo(name)


def localize(naive: datetime.datetime) -> datetime.datetime:
    """Interpret a naive timestamp in the session zone → aware."""
    return naive.replace(tzinfo=session_zone())


_TRANSITIONS_CACHE = {}


def utc_offset_transitions(name: str = None):
    """(starts_us, offsets_us) numpy arrays for the session zone: UTC→local
    offset as a step function over 1900–2100. Lets device kernels convert
    epoch-us to local time with a searchsorted + gather instead of per-row
    host callbacks (DST-correct, TPU-friendly)."""
    import numpy as np

    name = name or session_timezone_name()
    hit = _TRANSITIONS_CACHE.get(name)
    if hit is not None:
        return hit
    zone = (datetime.timezone.utc if name.upper() == "UTC"
            else zoneinfo.ZoneInfo(name))
    if zone is datetime.timezone.utc:
        out = (np.asarray([-(2**62)], dtype=np.int64),
               np.asarray([0], dtype=np.int64))
        _TRANSITIONS_CACHE[name] = out
        return out
    starts = [-(2**62)]
    offsets = []
    t = datetime.datetime(1900, 1, 1, tzinfo=datetime.timezone.utc)
    end = datetime.datetime(2100, 1, 1, tzinfo=datetime.timezone.utc)
    cur = zone.utcoffset(t)
    offsets.append(int(cur.total_seconds() * 1e6))
    # scan in 6h steps, bisect each change to the exact second
    step = datetime.timedelta(hours=6)
    while t < end:
        nxt = t + step
        off = zone.utcoffset(nxt)
        if off != cur:
            lo, hi = t, nxt
            while hi - lo > datetime.timedelta(seconds=1):
                mid = lo + (hi - lo) / 2
                if zone.utcoffset(mid) != cur:
                    hi = mid
                else:
                    lo = mid
            epoch = hi.timestamp()
            starts.append(int(round(epoch)) * 1_000_000)
            offsets.append(int(off.total_seconds() * 1e6))
            cur = off
        t = nxt
    out = (np.asarray(starts, dtype=np.int64),
           np.asarray(offsets, dtype=np.int64))
    _TRANSITIONS_CACHE[name] = out
    return out
