"""Session timezone context (spark.sql.session.timeZone).

Spark interprets naive timestamp literals, string→timestamp casts, and
make_timestamp without an explicit zone in the SESSION timezone, and
renders timestamps in it. The engine stores timestamps as UTC
microseconds; this contextvar carries the session zone through literal
resolution, host datetime functions, and display."""

from __future__ import annotations

import contextvars
import datetime
import zoneinfo

_SESSION_TZ = contextvars.ContextVar("sail_session_tz", default="UTC")


def set_session_timezone(tz: str):
    return _SESSION_TZ.set(tz or "UTC")


def reset_session_timezone(token):
    _SESSION_TZ.reset(token)


def session_timezone_name() -> str:
    return _SESSION_TZ.get()


def session_zone():
    name = _SESSION_TZ.get()
    if name.upper() == "UTC":
        return datetime.timezone.utc
    return zoneinfo.ZoneInfo(name)


def localize(naive: datetime.datetime) -> datetime.datetime:
    """Interpret a naive timestamp in the session zone → aware."""
    return naive.replace(tzinfo=session_zone())
