"""Host string/regexp function breadth (registered into HOST_FNS).

Reference role: crates/sail-function/src/scalar/string/ and the regexp
family. Java-regex-flavored patterns are translated approximately to
Python re (the common constructs coincide).
"""

from __future__ import annotations

import math
import re

from ..spec import data_type as dt
from .host_functions import _reg, _t, _t0

_S = dt.StringType()
_I = dt.IntegerType()
_L = dt.LongType()
_B = dt.BooleanType()


_PY_RE_ESCAPES = set("dDwWsSbBAZnrtfv0123456789\\.^$*+?()[]{}|/")


def _jre(pattern: str) -> str:
    """Java-regex → python re, leniently: escapes python's re rejects
    (like \\U outside known classes) lose the backslash instead of
    failing the whole query."""
    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == "\\" and i + 1 < len(pattern):
            nxt = pattern[i + 1]
            if nxt in _PY_RE_ESCAPES:
                out.append(c)
                out.append(nxt)
            else:
                out.append(re.escape(nxt))
            i += 2
            continue
        out.append(c)
        i += 1
    return "".join(out)


_reg(["split"], _t(dt.ArrayType(_S)),
     lambda s, pat, *limit: _split(s, pat, limit[0] if limit else -1))
_reg(["split_part"], _t(_S), lambda s, d, n: _split_part(s, d, n))
_reg(["substring_index"], _t(_S),
     lambda s, delim, n: _substring_index(s, delim, int(n)))
_reg(["find_in_set"], _t(_I),
     lambda s, ss: 0 if "," in s else (
         ss.split(",").index(s) + 1 if s in ss.split(",") else 0))
_reg(["overlay"], _t0, lambda s, repl, pos, *l: _overlay(
    s, repl, int(pos), int(l[0]) if l else -1))
_reg(["levenshtein"], _t(_I), lambda a, b, *th: _levenshtein(
    a, b, int(th[0]) if th else None))
_reg(["regexp_like", "regexp", "rlike"], _t(_B),
     lambda s, p: re.search(_jre(p), s) is not None)
_reg(["regexp_count"], _t(_I),
     lambda s, p: len(re.findall(_jre(p), s)))
_reg(["regexp_extract"], _t(_S),
     lambda s, p, *g: _re_extract(s, p, int(g[0]) if g else 1))
_reg(["regexp_extract_all"], _t(dt.ArrayType(_S)),
     lambda s, p, *g: _re_extract_all(s, p, int(g[0]) if g else 1))
_reg(["regexp_instr"], _t(_I),
     lambda s, p, *g: _re_instr(s, p))
_reg(["regexp_substr"], _t(_S),
     lambda s, p: (lambda m: m.group(0) if m else None)(
         re.search(_jre(p), s)))
_reg(["regexp_replace"], _t(_S),
     lambda s, p, r, *pos: _re_replace(s, p, r,
                                       int(pos[0]) if pos else 1))
_reg(["mask"], _t(_S), lambda s, *a: _mask(s, *a), null_tolerant=True)
_reg(["printf", "format_string"], _t(_S),
     lambda fmt, *args: _printf(fmt, args), null_tolerant=True)
_reg(["to_binary"], _t(dt.BinaryType()),
     lambda s, *f: _to_binary(s, f[0] if f else "hex"))
_reg(["try_to_binary"], _t(dt.BinaryType()),
     lambda s, *f: _try_null(_to_binary, s, f[0] if f else "hex"))
_reg(["to_char", "to_varchar"], _t(_S), lambda v, fmt: _to_char(v, fmt))
_reg(["to_number"],
     lambda ts: dt.DecimalType(38, 6), lambda s, fmt: _to_number(s, fmt))
_reg(["try_to_number"],
     lambda ts: dt.DecimalType(38, 6),
     lambda s, fmt: _try_null(_to_number, s, fmt))


def _try_null(fn, *args):
    try:
        return fn(*args)
    except Exception:  # noqa: BLE001 — try_ semantics
        return None
_reg(["btrim"], _t(_S),
     lambda s, *chars: s.strip(chars[0]) if chars else s.strip())
_reg(["char_length", "character_length", "len"], _t(_I), lambda s: len(s))
_reg(["contains"], _t(_B), lambda a, b: b in a)
_reg(["startswith"], _t(_B), lambda a, b: a.startswith(b))
_reg(["endswith"], _t(_B), lambda a, b: a.endswith(b))
_reg(["sentences"], _t(dt.ArrayType(dt.ArrayType(_S))),
     lambda s, *lc: [[w for w in re.split(r"\W+", sent) if w]
                     for sent in re.split(r"[.!?]", s) if sent.strip()])
_reg(["initcap"], _t(_S),
     lambda s: " ".join(w[:1].upper() + w[1:].lower() if w else w
                        for w in s.split(" ")))
_reg(["quote"], _t(_S), lambda s: "'" + s.replace("'", "\\'") + "'")
_reg(["istrue", "isfalse"], _t(_B), None)
_reg(["soundex"], _t(_S), lambda s: _soundex(s))
_reg(["crc32"], _t(_L), lambda s: __import__("zlib").crc32(
    s if isinstance(s, bytes) else str(s).encode()) & 0xFFFFFFFF)
_reg(["octet_length"], _t(_I),
     lambda s: len(s if isinstance(s, bytes) else str(s).encode()))
_reg(["bit_length"], _t(_I),
     lambda s: 8 * len(s if isinstance(s, bytes) else str(s).encode()))


def _split(s, pat, limit=-1):
    limit = int(limit)
    if limit > 0:
        return re.split(_jre(pat), s, maxsplit=limit - 1)
    out = re.split(_jre(pat), s)
    if limit == 0 or limit == -1:
        # Java semantics: limit<=0 keeps all; limit=0 drops trailing empties
        pass
    return out


def _split_part(s, delim, n):
    n = int(n)
    if n == 0:
        raise ValueError("split_part index must not be 0")
    parts = s.split(delim) if delim else [s]
    idx = n - 1 if n > 0 else len(parts) + n
    if 0 <= idx < len(parts):
        return parts[idx]
    return ""


def _substring_index(s, delim, n):
    if not delim:
        return ""
    if n > 0:
        parts = s.split(delim)
        return delim.join(parts[:n])
    if n < 0:
        parts = s.split(delim)
        return delim.join(parts[n:])
    return ""


def _overlay(s, repl, pos, length):
    if length < 0:
        length = len(repl)
    i = pos - 1
    return s[:i] + repl + s[i + length:]


def _levenshtein(a, b, threshold=None):
    m, n = len(a), len(b)
    prev = list(range(n + 1))
    for i in range(1, m + 1):
        cur = [i] + [0] * n
        for j in range(1, n + 1):
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1,
                         prev[j - 1] + (a[i - 1] != b[j - 1]))
        prev = cur
    d = prev[n]
    if threshold is not None and d > threshold:
        return -1
    return d


def _re_extract(s, p, g):
    m = re.search(_jre(p), s)
    if not m:
        return ""
    try:
        return m.group(g) or ""
    except (IndexError, error_types()):
        raise


def _re_extract_all(s, p, g):
    out = []
    for m in re.finditer(_jre(p), s):
        out.append(m.group(g) or "")
    return out


def _re_instr(s, p):
    m = re.search(_jre(p), s)
    return (m.start() + 1) if m else 0


def _re_replace(s, p, r, pos=1):
    r = re.sub(r"\$(\d)", r"\\\1", r)
    prefix = s[:pos - 1]
    return prefix + re.sub(_jre(p), r, s[pos - 1:])


def error_types():
    return re.error


def _mask(s, *args):
    if s is None:
        return None
    upper = args[0] if len(args) > 0 else "X"
    lower = args[1] if len(args) > 1 else "x"
    digit = args[2] if len(args) > 2 else "n"
    other = args[3] if len(args) > 3 else None
    out = []
    for ch in s:
        if ch.isupper():
            out.append(upper if upper is not None else ch)
        elif ch.islower():
            out.append(lower if lower is not None else ch)
        elif ch.isdigit():
            out.append(digit if digit is not None else ch)
        else:
            out.append(other if other is not None else ch)
    return "".join(out)


def _printf(fmt, args):
    if fmt is None:
        return None
    out = []
    ai = 0
    i = 0
    while i < len(fmt):
        ch = fmt[i]
        if ch != "%":
            out.append(ch)
            i += 1
            continue
        m = re.match(r"%([-+ 0#]*\d*(?:\.\d+)?)([sdfeEgGxXob%])", fmt[i:])
        if not m:
            out.append(ch)
            i += 1
            continue
        spec = m.group(0)
        if m.group(2) == "%":
            out.append("%")
        else:
            v = args[ai]
            ai += 1
            if m.group(2) == "b":
                out.append("true" if v else "false")
            elif m.group(2) in "dxXo":
                out.append(spec % int(v))
            elif m.group(2) in "feEgG":
                out.append(spec % float(v))
            else:
                out.append(spec % (v,))
        i += len(spec)
    return "".join(out)


def _to_binary(s, fmt):
    f = (fmt or "hex").lower()
    if f == "hex":
        from .host_functions import _unhex
        return _unhex(s)
    if f == "utf-8" or f == "utf8":
        return s.encode()
    if f == "base64":
        import base64 as b64
        return b64.b64decode(s)
    return None


def _split_number_format(fmt):
    """Oracle-style template → (int positions, dec digits, flags).

    int positions is the template's integer section right-to-left, each
    element '0', '9', or ',' (G normalized to ',')."""
    f = fmt.upper().replace("G", ",").replace("D", ".")
    dollar = "$" in f
    f = f.replace("$", "")
    trail_minus = f.endswith("MI")
    if trail_minus:
        f = f[:-2]
    lead_s = f.startswith("S")
    trail_s = f.endswith("S")
    f = f.strip("S")
    ip, _, fp = f.partition(".")
    return ip, fp, dollar, lead_s, trail_s, trail_minus


def _to_char(v, fmt):
    import datetime as _dt

    # date/timestamp: to_char == date_format; binary: encoding name
    if isinstance(v, (_dt.date, _dt.datetime)):
        from .host_datetime import _java_fmt, _to_ts
        return _java_fmt(_to_ts(v), fmt)
    if isinstance(v, bytes):
        fl = fmt.lower()
        if fl in ("utf-8", "utf8"):
            return v.decode("utf-8", errors="replace")
        if fl == "hex":
            return v.hex().upper()
        if fl == "base64":
            import base64 as b64
            return b64.b64encode(v).decode()
        return None
    ip, fp, dollar, lead_s, trail_s, trail_mi = _split_number_format(fmt)
    decs = sum(1 for c in fp if c in "09")
    import decimal as _decm
    d = _decm.Decimal(str(v)).quantize(
        _decm.Decimal(1).scaleb(-decs), rounding=_decm.ROUND_HALF_UP)
    neg = d < 0
    digits, _, frac = format(abs(d), "f").partition(".")
    # map integer digits onto the template right-to-left; positions at or
    # right of the leftmost '0' zero-fill, leading '9' positions stay empty
    out = []
    di = len(digits) - 1
    first_zero = min((i for i, c in enumerate(ip) if c == "0"),
                     default=None)
    for i in range(len(ip) - 1, -1, -1):
        c = ip[i]
        if c in "09":
            if di >= 0:
                out.append(digits[di])
                di -= 1
            elif first_zero is not None and i >= first_zero:
                out.append("0")
        elif c == ",":
            more = di >= 0 or (first_zero is not None and first_zero < i)
            if out and more:
                out.append(",")
    if di >= 0:  # digits overflow the template
        return "#" * len(fmt)
    body = "".join(reversed(out))
    if not body:
        body = "0" if decs == 0 else ""
    if decs:
        body += "." + (frac or "").ljust(decs, "0")[:decs]
    if dollar:
        body = "$" + body
    if trail_s:
        return body + ("-" if neg else "+")
    if trail_mi:
        return body + ("-" if neg else " ")
    return ("-" if neg else "") + body


def _to_number(s, fmt):
    import decimal

    ip, fp, dollar, lead_s, trail_s, trail_mi = _split_number_format(fmt)
    decs = sum(1 for c in fp if c in "09")
    t = s.strip()
    neg = False
    if trail_s or trail_mi:
        if t.endswith("-"):
            neg = True
            t = t[:-1]
        elif t.endswith("+"):
            t = t[:-1]
    if t.startswith("-"):
        neg = True
        t = t[1:]
    elif t.startswith("+"):
        t = t[1:]
    if t.startswith("$"):
        t = t[1:]
    t = t.replace(",", "")
    if not re.fullmatch(r"\d*(?:\.\d*)?", t) or not t.strip("."):
        raise ValueError(f"cannot parse {s!r} with format {fmt!r}")
    try:
        d = decimal.Decimal(t)
    except decimal.InvalidOperation:
        return None
    d = d.quantize(decimal.Decimal(1).scaleb(-decs))
    return -d if neg else d


def _soundex(s):
    if not s:
        return s
    s = s.upper()
    codes = {"B": "1", "F": "1", "P": "1", "V": "1",
             "C": "2", "G": "2", "J": "2", "K": "2", "Q": "2", "S": "2",
             "X": "2", "Z": "2", "D": "3", "T": "3", "L": "4",
             "M": "5", "N": "5", "R": "6"}
    out = s[0]
    prev = codes.get(s[0], "")
    for ch in s[1:]:
        c = codes.get(ch, "")
        if c and c != prev:
            out += c
        if ch not in "HW":
            prev = c
    return (out + "000")[:4]
