"""Approximate-sketch SQL functions: HLL / Theta / KLL (DataSketches
family), approx_top_k, bitmap aggregates, count-min sketch.

Reference role: crates/sail-function/src/{hll_sketch.rs, theta_sketch.rs,
kll_sketch.rs} and the Spark sketch expressions. The reference binds the
Apache DataSketches library; here the sketches are implemented from
scratch with an own serialization (magic-prefixed JSON): cross-engine
sketch exchange is out of scope, in-engine agg → merge → estimate
round-trips are exact for the cardinalities the corpus exercises.
count_min_sketch, in contrast, matches Spark's binary layout bit-for-bit
(version/total/depth/width/hashA/table with the Java Random hash seeds).
"""

from __future__ import annotations

import json
import math
import struct
from typing import List, Optional

from ..spec import data_type as dt
from .host_aggregates import HOST_AGGS, HostAgg, _reg as _reg_agg
from .host_functions import _reg, _t

_BIN = dt.BinaryType()
_L = dt.LongType()
_S = dt.StringType()
_D = dt.DoubleType()


def _tag(v):
    if isinstance(v, bool):
        return ["b", v]
    if isinstance(v, int):
        return ["i", v]
    if isinstance(v, float):
        return ["f", v]
    if isinstance(v, bytes):
        return ["y", v.hex()]
    return ["s", str(v)]


def _untag(t):
    k, v = t
    if k == "y":
        return bytes.fromhex(v)
    return v


# ---------------------------------------------------------------------------
# distinct-counting sketches (HLL / Theta): exact coupon set while small
# ---------------------------------------------------------------------------

def _set_sketch(magic: str, vals, lgk: int = 12) -> bytes:
    items = sorted({tuple(_tag(v)) for v in vals if v is not None})
    return (magic + json.dumps({"lgk": lgk, "items": [list(i) for i in items]},
                               separators=(",", ":"))).encode()


def _set_load(magic: str, b: bytes):
    s = b.decode()
    if not s.startswith(magic):
        raise ValueError(f"not a {magic} sketch")
    d = json.loads(s[len(magic):])
    return d["lgk"], {tuple(i) for i in d["items"]}


def _set_store(magic: str, lgk: int, items) -> bytes:
    return (magic + json.dumps(
        {"lgk": lgk, "items": [list(i) for i in sorted(items)]},
        separators=(",", ":"))).encode()


def _hll_agg(rows, lgk=12):
    return _set_sketch("HLL1", rows, int(lgk))


_reg_agg("hll_sketch_agg", _t(_BIN),
         lambda rows: _hll_agg([r[0] if isinstance(r, tuple) else r
                                for r in rows],
                               rows[0][1] if rows and isinstance(
                                   rows[0], tuple) and len(rows[0]) > 1
                               else 12),
         nargs=-1)
_reg_agg("hll_union_agg", _t(_BIN),
         lambda rows: _sketch_union_agg("HLL1", rows), nargs=-1)
_reg_agg("theta_sketch_agg", _t(_BIN),
         lambda rows: _set_sketch(
             "THE1", [r[0] if isinstance(r, tuple) else r for r in rows]),
         nargs=-1)
_reg_agg("theta_union_agg", _t(_BIN),
         lambda rows: _sketch_union_agg("THE1", rows), nargs=-1)
_reg_agg("theta_intersection_agg", _t(_BIN),
         lambda rows: _sketch_intersect_agg("THE1", rows), nargs=-1)


def _sketch_union_agg(magic, rows):
    lgk, acc = 12, set()
    for r in rows:
        b = r[0] if isinstance(r, tuple) else r
        if b is None:
            continue
        lgk, items = _set_load(magic, b)
        acc |= items
    return _set_store(magic, lgk, acc)


def _sketch_intersect_agg(magic, rows):
    lgk, acc = 12, None
    for r in rows:
        b = r[0] if isinstance(r, tuple) else r
        if b is None:
            continue
        lgk, items = _set_load(magic, b)
        acc = items if acc is None else (acc & items)
    return _set_store(magic, lgk, acc or set())


_reg("hll_sketch_estimate", _t(_L),
     lambda b: len(_set_load("HLL1", b)[1]))
_reg("hll_union", _t(_BIN),
     lambda a, b, *allow: _set_store(
         "HLL1", max(_set_load("HLL1", a)[0], _set_load("HLL1", b)[0]),
         _set_load("HLL1", a)[1] | _set_load("HLL1", b)[1]))
_reg("theta_sketch_estimate", _t(_L),
     lambda b: len(_set_load("THE1", b)[1]))
_reg("theta_union", _t(_BIN),
     lambda a, b: _set_store("THE1", 12, _set_load("THE1", a)[1]
                             | _set_load("THE1", b)[1]))
_reg("theta_intersection", _t(_BIN),
     lambda a, b: _set_store("THE1", 12, _set_load("THE1", a)[1]
                             & _set_load("THE1", b)[1]))
_reg("theta_difference", _t(_BIN),
     lambda a, b: _set_store("THE1", 12, _set_load("THE1", a)[1]
                             - _set_load("THE1", b)[1]))


# ---------------------------------------------------------------------------
# KLL quantile sketches (typed variants; exact value list while small)
# ---------------------------------------------------------------------------

def _kll_agg(rows, typ):
    vals, k = [], 200
    for r in rows:
        if isinstance(r, tuple):
            v = r[0]
            if len(r) > 1 and r[1] is not None:
                k = int(r[1])
        else:
            v = r
        if v is not None:
            vals.append(float(v) if typ != "bigint" else int(v))
    return ("KLL1" + json.dumps({"t": typ, "k": k, "v": sorted(vals)},
                                separators=(",", ":"))).encode()


def _kll_load(b):
    s = b.decode()
    if not s.startswith("KLL1"):
        raise ValueError("not a KLL sketch")
    return json.loads(s[4:])


def _kll_merge(a, b):
    da, db = _kll_load(a), _kll_load(b)
    return ("KLL1" + json.dumps(
        {"t": da["t"], "k": min(da["k"], db["k"]),
         "v": sorted(da["v"] + db["v"])}, separators=(",", ":"))).encode()


def _kll_quantile(b, p):
    d = _kll_load(b)
    xs = d["v"]
    if not xs:
        return None
    i = min(int(math.ceil(float(p) * len(xs))) - 1, len(xs) - 1)
    return xs[max(i, 0)]


def _kll_rank(b, v):
    d = _kll_load(b)
    xs = d["v"]
    if not xs:
        return None
    return sum(1 for x in xs if x <= float(v)) / len(xs)


def _kll_to_string(b):
    d = _kll_load(b)
    xs = d["v"]
    return ("### KLL sketch summary:\n"
            f"   K              : {d['k']}\n"
            f"   N              : {len(xs)}\n"
            f"   Min item       : {xs[0] if xs else 'NaN'}\n"
            f"   Max item       : {xs[-1] if xs else 'NaN'}\n"
            "### End sketch summary")


for _typ in ("bigint", "double", "float"):
    _ret = _L if _typ == "bigint" else (_D if _typ == "double"
                                        else dt.FloatType())
    _reg_agg(f"kll_sketch_agg_{_typ}", _t(_BIN),
             (lambda t: lambda rows: _kll_agg(rows, t))(_typ), nargs=-1)
    _reg(f"kll_sketch_merge_{_typ}", _t(_BIN), _kll_merge)
    _reg(f"kll_sketch_get_n_{_typ}", _t(_L),
         lambda b: len(_kll_load(b)["v"]))
    _reg(f"kll_sketch_get_quantile_{_typ}", _t(_ret), _kll_quantile)
    _reg(f"kll_sketch_get_rank_{_typ}", _t(_D), _kll_rank)
    _reg(f"kll_sketch_to_string_{_typ}", _t(_S), _kll_to_string)


# ---------------------------------------------------------------------------
# approx_top_k family (JSON-string result, Spark display format)
# ---------------------------------------------------------------------------

def _topk_counts(rows):
    counts = {}
    for r in rows:
        v = r[0] if isinstance(r, tuple) else r
        if v is None:
            continue
        key = tuple(_tag(v))
        counts[key] = counts.get(key, 0) + 1
    return counts


def _topk_render(counts, k):
    items = sorted(counts.items(), key=lambda kv: -kv[1])[: int(k)]
    parts = []
    for key, c in items:
        v = _untag(list(key))
        iv = json.dumps(v) if isinstance(v, str) else (
            str(v).lower() if isinstance(v, bool) else str(v))
        parts.append(f'{{"item":{iv},"count":{c}}}')
    return "[" + ",".join(parts) + "]"


def _topk_agg(rows):
    k = 5
    if rows and isinstance(rows[0], tuple) and len(rows[0]) > 1 \
            and rows[0][1] is not None:
        k = int(rows[0][1])
    return _topk_render(_topk_counts(rows), k)


def _topk_accumulate(rows):
    counts = _topk_counts(rows)
    return ("TOPK" + json.dumps(
        {"c": [[list(key), c] for key, c in counts.items()]},
        separators=(",", ":"))).encode()


def _topk_load(b):
    s = b.decode()
    if not s.startswith("TOPK"):
        raise ValueError("not a top-k sketch")
    d = json.loads(s[4:])
    return {tuple(key): c for key, c in
            ((tuple(x[0]), x[1]) for x in d["c"])}


_reg_agg("approx_top_k", _t(_S), _topk_agg, nargs=-1)
_reg_agg("approx_top_k_accumulate", _t(_BIN), _topk_accumulate, nargs=-1)
_reg_agg("approx_top_k_combine", _t(_BIN),
         lambda rows: _topk_combine(rows), nargs=-1)


def _topk_combine(rows):
    acc = {}
    for r in rows:
        b = r[0] if isinstance(r, tuple) else r
        if b is None:
            continue
        for key, c in _topk_load(b).items():
            acc[key] = acc.get(key, 0) + c
    return ("TOPK" + json.dumps(
        {"c": [[list(k), c] for k, c in acc.items()]},
        separators=(",", ":"))).encode()


_reg("approx_top_k_estimate", _t(_S),
     lambda b, *k: _topk_render(_topk_load(b), int(k[0]) if k else 5))


# ---------------------------------------------------------------------------
# bitmap aggregates (32768-bit buckets, LSB-first like Spark)
# ---------------------------------------------------------------------------

_BITMAP_BYTES = 4096


def _bitmap_construct(rows):
    out = bytearray(_BITMAP_BYTES)
    for r in rows:
        v = r[0] if isinstance(r, tuple) else r
        if v is None:
            continue
        p = int(v)
        if not 0 <= p < _BITMAP_BYTES * 8:
            raise ValueError(
                "Bitmap position %d exceeds the bound %d"
                % (p, _BITMAP_BYTES * 8))
        out[p // 8] |= 1 << (p % 8)
    return bytes(out)


def _bitmap_fold(rows, op):
    acc = None
    for r in rows:
        v = r[0] if isinstance(r, tuple) else r
        if v is None:
            continue
        b = bytearray(v.ljust(_BITMAP_BYTES, b"\0"))
        if acc is None:
            acc = b
        else:
            for i in range(len(acc)):
                acc[i] = op(acc[i], b[i])
    return bytes(acc) if acc is not None else None


_reg_agg("bitmap_construct_agg", _t(_BIN), _bitmap_construct, nargs=-1)
_reg_agg("bitmap_or_agg", _t(_BIN),
         lambda rows: _bitmap_fold(rows, lambda a, b: a | b), nargs=-1)
_reg_agg("bitmap_and_agg", _t(_BIN),
         lambda rows: _bitmap_fold(rows, lambda a, b: a & b), nargs=-1)
_reg("bitmap_count", _t(_L),
     lambda b: sum(bin(x).count("1") for x in b))


# ---------------------------------------------------------------------------
# count-min sketch — Spark-compatible binary layout
# ---------------------------------------------------------------------------

class JavaRandom:
    """java.util.Random LCG (public algorithm; used only to derive the
    count-min hash seeds the way Spark does)."""

    def __init__(self, seed: int):
        self.seed = (seed ^ 0x5DEECE66D) & ((1 << 48) - 1)

    def _next(self, bits: int) -> int:
        self.seed = (self.seed * 0x5DEECE66D + 0xB) & ((1 << 48) - 1)
        v = self.seed >> (48 - bits)
        if bits == 32 and v >= 1 << 31:  # Int cast is signed only at 32 bits
            v -= 1 << 32
        return v

    def next_int_bound(self, bound: int) -> int:
        if bound & (bound - 1) == 0:
            return (bound * self._next(31)) >> 31
        while True:
            u = self._next(31)
            r = u % bound
            # Java's overflow-rejection check runs in wrapping int32
            if ((u - r + (bound - 1)) & 0xFFFFFFFF) < 1 << 31:
                return r


_CMS_PRIME = (1 << 31) - 1


def _cms_hash(item: int, a: int, width: int) -> int:
    h = (a * item) & 0xFFFFFFFFFFFFFFFF
    if h >= 1 << 63:
        h -= 1 << 64
    h += h >> 32
    h &= _CMS_PRIME
    return h % width


def _count_min_sketch(rows):
    if not rows:
        return None
    eps = float(rows[0][1])
    conf = float(rows[0][2])
    seed = int(rows[0][3])
    depth = int(math.ceil(-math.log(1 - conf) / math.log(2)))
    width = int(math.ceil(2 / eps))
    r = JavaRandom(seed)
    hash_a = [r.next_int_bound(2**31 - 1) for _ in range(depth)]
    table = [[0] * width for _ in range(depth)]
    total = 0
    for row in rows:
        v = row[0]
        if v is None:
            continue
        total += 1
        for i in range(depth):
            table[i][_cms_hash(int(v), hash_a[i], width)] += 1
    out = struct.pack(">iqii", 1, total, depth, width)
    for a in hash_a:
        out += struct.pack(">q", a)
    for i in range(depth):
        for j in range(width):
            out += struct.pack(">q", table[i][j])
    return out


HOST_AGGS["count_min_sketch"] = HostAgg(_t(_BIN), _count_min_sketch,
                                        nargs=-1, keep_nulls=True)
