"""Pure-python AES (ECB/CBC/GCM) for the aes_encrypt/aes_decrypt SQL
functions.

Reference role: crates/sail-function/src/scalar/misc.rs aes_* (which uses
a Rust crypto crate); this image has no crypto library, so the cipher is
implemented from the FIPS-197 spec. Layouts match Spark:

- ECB: raw ciphertext, PKCS#5 padding
- CBC: random 16-byte IV || ciphertext (PKCS#5)
- GCM (default): random 12-byte IV || ciphertext || 16-byte tag
"""

from __future__ import annotations

import os

_SBOX = bytes.fromhex(
    "637c777bf26b6fc53001672bfed7ab76ca82c97dfa5947f0add4a2af9ca472c0"
    "b7fd9326363ff7cc34a5e5f171d8311504c723c31896059a071280e2eb27b275"
    "09832c1a1b6e5aa0523bd6b329e32f8453d100ed20fcb15b6acbbe394a4c58cf"
    "d0efaafb434d338545f9027f503c9fa851a3408f929d38f5bcb6da2110fff3d2"
    "cd0c13ec5f974417c4a77e3d645d197360814fdc222a908846eeb814de5e0bdb"
    "e0323a0a4906245cc2d3ac629195e479e7c8376d8dd54ea96c56f4ea657aae08"
    "ba78252e1ca6b4c6e8dd741f4bbd8b8a703eb5664803f60e613557b986c11d9e"
    "e1f8981169d98e949b1e87e9ce5528df8ca1890dbfe6426841992d0fb054bb16")
_INV_SBOX = bytearray(256)
for _i, _v in enumerate(_SBOX):
    _INV_SBOX[_v] = _i
_INV_SBOX = bytes(_INV_SBOX)

_RCON = (0x01, 0x02, 0x04, 0x08, 0x10, 0x20, 0x40, 0x80, 0x1B, 0x36,
         0x6C, 0xD8, 0xAB, 0x4D)


def _xtime(a: int) -> int:
    a <<= 1
    if a & 0x100:
        a ^= 0x11B
    return a & 0xFF


_MUL = [[0] * 256 for _ in range(256)]
for _a in (2, 3, 9, 11, 13, 14):
    for _b in range(256):
        r, x, a = 0, _b, _a
        while a:
            if a & 1:
                r ^= x
            x = _xtime(x)
            a >>= 1
        _MUL[_a][_b] = r


def _expand_key(key: bytes):
    nk = len(key) // 4
    nr = nk + 6
    w = [list(key[4 * i: 4 * i + 4]) for i in range(nk)]
    for i in range(nk, 4 * (nr + 1)):
        t = list(w[i - 1])
        if i % nk == 0:
            t = t[1:] + t[:1]
            t = [_SBOX[b] for b in t]
            t[0] ^= _RCON[i // nk - 1]
        elif nk > 6 and i % nk == 4:
            t = [_SBOX[b] for b in t]
        w.append([a ^ b for a, b in zip(w[i - nk], t)])
    rounds = []
    for r in range(nr + 1):
        rk = []
        for c in range(4):
            rk.extend(w[4 * r + c])
        rounds.append(bytes(rk))
    return rounds, nr


def _encrypt_block(block: bytes, rounds, nr: int) -> bytes:
    s = bytearray(a ^ b for a, b in zip(block, rounds[0]))
    for rnd in range(1, nr):
        s = bytearray(_SBOX[b] for b in s)
        s = bytearray(s[(i + 4 * (i % 4)) % 16] for i in range(16))  # shift rows
        ns = bytearray(16)
        for c in range(4):
            col = s[4 * c: 4 * c + 4]
            ns[4 * c + 0] = _MUL[2][col[0]] ^ _MUL[3][col[1]] ^ col[2] ^ col[3]
            ns[4 * c + 1] = col[0] ^ _MUL[2][col[1]] ^ _MUL[3][col[2]] ^ col[3]
            ns[4 * c + 2] = col[0] ^ col[1] ^ _MUL[2][col[2]] ^ _MUL[3][col[3]]
            ns[4 * c + 3] = _MUL[3][col[0]] ^ col[1] ^ col[2] ^ _MUL[2][col[3]]
        s = bytearray(a ^ b for a, b in zip(ns, rounds[rnd]))
    s = bytearray(_SBOX[b] for b in s)
    s = bytearray(s[(i + 4 * (i % 4)) % 16] for i in range(16))
    return bytes(a ^ b for a, b in zip(s, rounds[nr]))


def _decrypt_block(block: bytes, rounds, nr: int) -> bytes:
    s = bytearray(a ^ b for a, b in zip(block, rounds[nr]))
    for rnd in range(nr - 1, 0, -1):
        s = bytearray(s[(i - 4 * (i % 4)) % 16] for i in range(16))  # inv shift
        s = bytearray(_INV_SBOX[b] for b in s)
        s = bytearray(a ^ b for a, b in zip(s, rounds[rnd]))
        ns = bytearray(16)
        for c in range(4):
            col = s[4 * c: 4 * c + 4]
            ns[4 * c + 0] = (_MUL[14][col[0]] ^ _MUL[11][col[1]]
                             ^ _MUL[13][col[2]] ^ _MUL[9][col[3]])
            ns[4 * c + 1] = (_MUL[9][col[0]] ^ _MUL[14][col[1]]
                             ^ _MUL[11][col[2]] ^ _MUL[13][col[3]])
            ns[4 * c + 2] = (_MUL[13][col[0]] ^ _MUL[9][col[1]]
                             ^ _MUL[14][col[2]] ^ _MUL[11][col[3]])
            ns[4 * c + 3] = (_MUL[11][col[0]] ^ _MUL[13][col[1]]
                             ^ _MUL[9][col[2]] ^ _MUL[14][col[3]])
        s = ns
    s = bytearray(s[(i - 4 * (i % 4)) % 16] for i in range(16))
    s = bytearray(_INV_SBOX[b] for b in s)
    return bytes(a ^ b for a, b in zip(s, rounds[0]))


def _pkcs_pad(data: bytes) -> bytes:
    p = 16 - len(data) % 16
    return data + bytes([p]) * p


def _pkcs_unpad(data: bytes) -> bytes:
    p = data[-1] if data else 0
    if (not data or len(data) % 16 or p < 1 or p > 16
            or data[-p:] != bytes([p]) * p):
        raise ValueError("bad PKCS padding")
    return data[:-p]


def _ctr_blocks(rounds, nr, j0: bytes, n_blocks: int):
    ctr = int.from_bytes(j0, "big")
    hi = ctr - (ctr & 0xFFFFFFFF)
    out = []
    for i in range(n_blocks):
        c = hi + ((ctr + 1 + i) & 0xFFFFFFFF)
        out.append(_encrypt_block(c.to_bytes(16, "big"), rounds, nr))
    return out


def _ghash_mult(x: int, h: int) -> int:
    z = 0
    v = h
    for i in range(127, -1, -1):
        if (x >> i) & 1:
            z ^= v
        if v & 1:
            v = (v >> 1) ^ (0xE1 << 120)
        else:
            v >>= 1
    return z


def _ghash(h: bytes, aad: bytes, ct: bytes) -> bytes:
    hi = int.from_bytes(h, "big")

    def blocks(data):
        for i in range(0, len(data), 16):
            yield data[i: i + 16].ljust(16, b"\0")

    y = 0
    for b in blocks(aad):
        y = _ghash_mult(y ^ int.from_bytes(b, "big"), hi)
    for b in blocks(ct):
        y = _ghash_mult(y ^ int.from_bytes(b, "big"), hi)
    lens = (len(aad) * 8).to_bytes(8, "big") + (len(ct) * 8).to_bytes(8, "big")
    y = _ghash_mult(y ^ int.from_bytes(lens, "big"), hi)
    return y.to_bytes(16, "big")


def _gcm(key: bytes, iv: bytes, data: bytes, aad: bytes, encrypt: bool):
    rounds, nr = _expand_key(key)
    h = _encrypt_block(b"\0" * 16, rounds, nr)
    if len(iv) == 12:
        j0 = iv + b"\0\0\0\1"
    else:
        j0 = _ghash(h, b"", iv)
    ks = _ctr_blocks(rounds, nr, j0, (len(data) + 15) // 16)
    out = bytearray()
    for i, b in enumerate(range(0, len(data), 16)):
        chunk = data[b: b + 16]
        out.extend(a ^ k for a, k in zip(chunk, ks[i]))
    out = bytes(out)
    ct = out if encrypt else data
    tag_mask = _encrypt_block(j0, rounds, nr)
    tag = bytes(a ^ b for a, b in zip(_ghash(h, aad, ct), tag_mask))
    return out, tag


def aes_encrypt(data: bytes, key: bytes, mode: str = "GCM",
                padding: str = "DEFAULT", iv: bytes = b"",
                aad: bytes = b"") -> bytes:
    mode = (mode or "GCM").upper()
    rounds, nr = _expand_key(key)
    if mode == "ECB":
        data = _pkcs_pad(data)
        return b"".join(_encrypt_block(data[i: i + 16], rounds, nr)
                        for i in range(0, len(data), 16))
    if mode == "CBC":
        iv = iv or os.urandom(16)
        data = _pkcs_pad(data)
        prev = iv
        out = bytearray()
        for i in range(0, len(data), 16):
            blk = bytes(a ^ b for a, b in zip(data[i: i + 16], prev))
            prev = _encrypt_block(blk, rounds, nr)
            out.extend(prev)
        return iv + bytes(out)
    if mode == "GCM":
        iv = iv or os.urandom(12)
        ct, tag = _gcm(key, iv, data, aad, True)
        return iv + ct + tag
    raise ValueError(f"unsupported AES mode {mode!r}")


def aes_decrypt(data: bytes, key: bytes, mode: str = "GCM",
                padding: str = "DEFAULT", aad: bytes = b"") -> bytes:
    mode = (mode or "GCM").upper()
    rounds, nr = _expand_key(key)
    if mode == "ECB":
        pt = b"".join(_decrypt_block(data[i: i + 16], rounds, nr)
                      for i in range(0, len(data), 16))
        return _pkcs_unpad(pt)
    if mode == "CBC":
        iv, ct = data[:16], data[16:]
        prev = iv
        out = bytearray()
        for i in range(0, len(ct), 16):
            blk = ct[i: i + 16]
            out.extend(a ^ b for a, b in
                       zip(_decrypt_block(blk, rounds, nr), prev))
            prev = blk
        return _pkcs_unpad(bytes(out))
    if mode == "GCM":
        iv, ct, tag = data[:12], data[12:-16], data[-16:]
        pt, expect = _gcm(key, iv, ct, aad, False)
        if expect != tag:
            raise ValueError("AES-GCM tag mismatch")
        return pt
    raise ValueError(f"unsupported AES mode {mode!r}")
