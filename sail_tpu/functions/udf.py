"""User-defined functions.

Reference role: sail-python-udf (crates/sail-python-udf — PySpark UDF
execution via an embedded interpreter with Arrow FFI; SURVEY.md §2.5).
Being Python-native, this engine inverts the design:

- ``pandas_udf``/arrow-batch UDFs are first **traced with jax**: if the
  function body is expressible in numpy-compatible ops it compiles straight
  into the surrounding XLA pipeline and runs ON DEVICE (the reference's
  UDFs always pay a host round-trip).
- Untraceable functions run through ``jax.pure_callback`` — the host
  executes the Python function on numpy/pandas batches while the
  surrounding query stays jitted; string arguments are decoded through the
  bind-time dictionary.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

from ..spec import data_type as dt


@dataclass(frozen=True)
class UserDefinedFunction:
    func: Callable
    return_type: dt.DataType
    eval_type: str = "batch"  # "batch" (row-at-a-time) | "pandas" | "arrow"
    name: str = "<lambda>"
    deterministic: bool = True

    def __call__(self, *cols):
        from ..session import Column, _to_expr
        args = tuple(_to_expr(c) for c in cols)
        return Column(UdfExpr(self, args))


# Expression node carrying the UDF handle (kept out of spec.expression's
# core set; the resolver special-cases it).
from ..spec import expression as _ex  # noqa: E402


@dataclass(frozen=True)
class UdfExpr(_ex.Expr):
    udf: UserDefinedFunction = None
    args: tuple = ()


def udf(f=None, returnType=None):
    """F.udf(lambda, T) or @F.udf(returnType=T) decorator."""
    rt = _parse_rt(returnType) if returnType is not None else dt.StringType()
    if f is None:
        return lambda fn: UserDefinedFunction(fn, rt, "batch",
                                              getattr(fn, "__name__", "udf"))
    return UserDefinedFunction(f, rt, "batch", getattr(f, "__name__", "udf"))


def pandas_udf(f=None, returnType=None, functionType=None):
    rt = _parse_rt(returnType) if returnType is not None else dt.DoubleType()
    if f is None:
        return lambda fn: UserDefinedFunction(fn, rt, "pandas",
                                              getattr(fn, "__name__", "udf"))
    return UserDefinedFunction(f, rt, "pandas", getattr(f, "__name__", "udf"))


def _parse_rt(t) -> dt.DataType:
    if isinstance(t, dt.DataType):
        return t
    from ..sql import parse_data_type
    return parse_data_type(str(t))


class UDFRegistry:
    """session.udf — named UDF registration for SQL."""

    def __init__(self):
        self._udfs = {}
        self._udtfs = {}

    def register(self, name: str, f, returnType=None) -> UserDefinedFunction:
        if isinstance(f, UserDefinedFunction):
            u = f
        else:
            u = UserDefinedFunction(f, _parse_rt(returnType)
                                    if returnType is not None else dt.StringType(),
                                    "batch", name)
        self._udfs[name.lower()] = u
        return u

    def get(self, name: str) -> Optional[UserDefinedFunction]:
        return self._udfs.get(name.lower())

    # -- table functions (UDTF handler classes) ------------------------
    def register_udtf(self, name: str, handler, return_type) -> None:
        self._udtfs[name.lower()] = (handler, return_type)

    def get_udtf(self, name: str):
        return self._udtfs.get(name.lower())
